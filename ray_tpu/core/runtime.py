"""Driver-side runtime: scheduler, worker pool, public API.

Role analogs in the reference:
  - scheduler/dispatch: ``src/ray/raylet/local_task_manager.h`` +
    ``scheduling/cluster_task_manager.h`` (single node, so no spillback)
  - worker pool: ``src/ray/raylet/worker_pool.h`` (prestart, dedicated
    actor workers)
  - public API: ``python/ray/_private/worker.py`` (init/get/put/wait/remote)

Control transport is one duplex pipe per worker; the driver runs one reader
thread per worker plus an event-driven dispatch loop under a single lock
(fine for a single node; the multi-node design moves this behind gRPC).
"""

from __future__ import annotations

import atexit
import logging
import os
import threading
import time
import uuid
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional

import cloudpickle

from ray_tpu import config
from ray_tpu.core import serialization, task_spec as ts
from ray_tpu.core.exceptions import (
    ActorDiedError,
    GetTimeoutError,
    TaskCancelledError,
    WorkerCrashedError,
)
from ray_tpu.core.gcs import ERROR, Gcs, READY, ActorInfo
from ray_tpu.core.ids import ActorID, NodeID, ObjectID, TaskID, WorkerID
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.object_store import StoreClient

logger = logging.getLogger(__name__)

_runtime = None
_runtime_lock = threading.Lock()

# -- built-in pipe/spawn instrumentation (defs in util/metric_defs) ------
# Pre-sorted tag keys: the pipe counters sit on the per-message hot
# path, so each message pays two cached _inc_key calls and nothing
# else. metric_defs.get is itself a cached fast path that re-registers
# after a test's clear_registry, so the accessor just rebuilds.
_SENT_KEY = (("direction", "sent"),)
_RECV_KEY = (("direction", "recv"),)
_SPAWN_KEYS = {"zygote": (("mode", "zygote"),), "exec": (("mode", "exec"),)}

#: wire magic of a packed worker->driver refpin frame (parsed natively by
#: the pipe engine; the Python fallback reader understands it too)
_REFPIN_MAGIC = b"RTP1"
#: wire magic of a native-coalesced driver->worker batch frame
_BATCH_MAGIC = b"RTB1"


def _pipe_metrics():
    from ray_tpu.util import metric_defs as md

    return {"sent": md.get("rtpu_pipe_sent_bytes_total"),
            "recv": md.get("rtpu_pipe_recv_bytes_total"),
            "msgs": md.get("rtpu_pipe_messages_total"),
            "batch": md.get("rtpu_pipe_batch_messages"),
            "nsend": md.get("rtpu_pipe_native_send_seconds"),
            "ndrain": md.get("rtpu_pipe_native_drain_messages")}


def _set_runtime(rt):
    global _runtime
    _runtime = rt


def _get_runtime():
    if _runtime is None:
        raise RuntimeError("ray_tpu.init() has not been called in this process")
    return _runtime


class _WorkerState:
    __slots__ = (
        "worker_id", "proc", "conn", "kind", "status", "current",
        "held", "actor_id", "reader", "released", "send_lock", "log_path",
        "pending_spec", "inflight_specs", "pinned", "spawn_ts",
        "spawn_mode", "npipe", "sent_ctr", "native_pin_q",
    )

    def __init__(self, worker_id: WorkerID, proc, kind: str):
        from ray_tpu.util.contention import timed_lock

        self.worker_id = worker_id
        self.proc = proc  # subprocess.Popen
        self.conn = None  # attached when the worker dials back
        # GIL-free pipe engine for this connection (None = Python path).
        # Once attached, the engine owns every read/write on the fd; the
        # Connection object only keeps the fd alive.
        self.npipe = None
        self.sent_ctr = 0  # 1-in-64 sampling of the nsend histogram
        # refpin transitions surfaced by the engine, pending application
        # (appended lock-free by _native_cb_refpins, drained by THIS
        # connection's reader thread — per-worker so no other reader can
        # steal a +1 and apply it after a later 'done' in our burst)
        from collections import deque as _wdeque

        self.native_pin_q: "_wdeque" = _wdeque()
        self.kind = kind  # "pool" | "actor"
        self.status = "starting"  # starting | idle | busy | dead
        self.current: Optional[dict] = None
        self.held: Dict[str, float] = {}
        self.actor_id: Optional[bytes] = None
        self.released = False
        self.send_lock = timed_lock("driver.worker_send")
        self.log_path = ""
        self.pending_spec: Optional[dict] = None  # dispatch once connected
        # all dispatched-but-unfinished specs keyed by task id (>1 only for
        # actors with max_concurrency > 1)
        self.inflight_specs: Dict[bytes, dict] = {}
        # objects this worker process borrows (oid -> transition count)
        self.pinned: Dict[bytes, int] = {}
        # spawn-latency stamp (zygote | exec), observed on "ready"
        self.spawn_ts = time.monotonic()
        self.spawn_mode = "exec"

    def send(self, msg):
        if self.conn is None:
            raise OSError("worker not connected yet")
        from ray_tpu.util import failpoints

        if failpoints.hit("pipe.send", msg[0]):
            return  # chaos: drop this driver->worker control message
        # pre-pickle so the framed byte count is known (what conn.send
        # does internally anyway — same reducer, no extra copy)
        from multiprocessing.reduction import ForkingPickler

        buf = ForkingPickler.dumps(msg)
        np_ = self.npipe
        if np_ is not None:
            # GIL-free fast path: the engine frames and writes inline
            # (nonblocking) or hands off to its sender thread when the
            # socket backs up. NO per-message Python metric work here —
            # the engine counts natively and the runtime's collector
            # reconciles rtpu_pipe_* at exposition time; only a sampled
            # 1-in-64 enqueue-latency observation stays on this path.
            self.sent_ctr += 1
            if self.sent_ctr & 63:
                if not np_.send(buf):
                    raise OSError("native pipe closed (worker gone)")
                return
            t0 = time.perf_counter()
            if not np_.send(buf):
                raise OSError("native pipe closed (worker gone)")
            try:
                _pipe_metrics()["nsend"]._observe_key(
                    (), time.perf_counter() - t0)
            except Exception:
                pass
            return
        with self.send_lock:
            self.conn.send_bytes(buf)
        try:
            m = _pipe_metrics()
            m["sent"]._inc_key((), len(buf))
            m["msgs"]._inc_key(_SENT_KEY)
        except Exception:
            pass


def _worker_site_dirs() -> list:
    """Every site dir a -S worker must re-add: system site-packages PLUS
    the user site (pip install --user) when enabled — dropping the latter
    would break imports that work in the driver."""
    import site

    dirs = list(site.getsitepackages())
    try:
        if site.ENABLE_USER_SITE:
            user = site.getusersitepackages()
            if user and user not in dirs:
                dirs.append(user)
    except Exception:
        pass
    return dirs


class _ZygoteChild:
    """Popen-like handle for a worker forked by the zygote.

    The zygote (the fork parent) reaps the child and reports its exit over
    the control pipe; this proxy turns that report into the wait()/poll()/
    terminate()/kill() surface _WorkerState expects. If the zygote itself
    dies, liveness falls back to signal-0 probing."""

    def __init__(self, zygote: "_Zygote", wid_hex: str):
        self._zygote = zygote
        self._wid = wid_hex
        self.pid: Optional[int] = None
        self.returncode: Optional[int] = None
        self._exit_ev = threading.Event()
        self._pid_ev = threading.Event()

    def _on_spawned(self, pid: int) -> None:
        self.pid = pid
        self._pid_ev.set()

    def _on_exit(self, status: int) -> None:
        self.returncode = status
        self._exit_ev.set()

    def wait(self, timeout: Optional[float] = None) -> Optional[int]:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            step = 0.5
            if deadline is not None:
                step = min(step, deadline - time.monotonic())
                if step <= 0:
                    import subprocess

                    raise subprocess.TimeoutExpired("zygote-child",
                                                    timeout or 0)
            if self._exit_ev.wait(step):
                return self.returncode
            if self._zygote.dead:
                # exit reports are gone; probe the process directly
                if self.pid is None or not _pid_alive(self.pid):
                    self.returncode = self.returncode or -1
                    self._exit_ev.set()
                    return self.returncode

    def poll(self) -> Optional[int]:
        if self.returncode is not None:
            return self.returncode
        if self._zygote.dead and (self.pid is None
                                  or not _pid_alive(self.pid)):
            self.returncode = -1
            self._exit_ev.set()
        return self.returncode

    def _signal(self, sig: int) -> None:
        if not self._pid_ev.wait(5.0) or self.pid is None:
            return
        try:
            os.kill(self.pid, sig)
        except ProcessLookupError:
            pass

    def terminate(self) -> None:
        import signal as _signal_mod

        self._signal(_signal_mod.SIGTERM)

    def kill(self) -> None:
        import signal as _signal_mod

        self._signal(_signal_mod.SIGKILL)


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True


def _worker_crashed_error(ws, spec, pm) -> WorkerCrashedError:
    """A ``WorkerCrashedError`` carrying the death postmortem: the exit
    cause class rides ``error_type`` (the r16 machine-readable contract,
    e.g. ``worker_died:signal:SIGKILL``), the structured forensics ride
    ``postmortem``, and the message folds in the readable excerpt so a
    bare ``ray_tpu.get`` shows WHY the worker died."""
    from ray_tpu.util import events as _events

    cause = (pm or {}).get("cause", "unknown")
    msg = (f"worker {ws.worker_id.hex()} died running task "
           f"{spec.get('name') if spec else '?'} ({cause})")
    detail = _events.format_postmortem(pm)
    if detail:
        msg += "\n--- worker postmortem ---\n" + detail
    err = WorkerCrashedError(msg)
    err.error_type = f"worker_died:{cause}"
    err.postmortem = pm
    return err


def _actor_died_error(actor_hex: str, pm) -> ActorDiedError:
    """``ActorDiedError`` twin of :func:`_worker_crashed_error`."""
    from ray_tpu.util import events as _events

    cause = (pm or {}).get("cause", "unknown")
    msg = f"actor {actor_hex} died ({cause})"
    detail = _events.format_postmortem(pm)
    if detail:
        msg += "\n--- worker postmortem ---\n" + detail
    err = ActorDiedError(msg)
    err.error_type = f"actor_died:{cause}"
    err.postmortem = pm
    return err


class _Zygote:
    """Driver-side handle for the fork-server process (core/zygote.py)."""

    def __init__(self, env: Dict[str, str]):
        import subprocess
        import sys

        dirs = ", ".join(repr(d) for d in _worker_site_dirs())
        bootstrap = (
            "import signal; signal.signal(signal.SIGUSR1, signal.SIG_IGN); "
            f"import site; [site.addsitedir(d) for d in ({dirs},)]; "
            "import runpy; "
            "runpy.run_module('ray_tpu.core.zygote', run_name='__main__')"
        )
        self.proc = subprocess.Popen(
            [sys.executable, "-S", "-c", bootstrap],
            env=env, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL)
        self.dead = False
        self.restartable = True
        self._lock = threading.Lock()
        self._children: Dict[str, _ZygoteChild] = {}
        self._ready = threading.Event()
        threading.Thread(target=self._reader_loop, daemon=True,
                         name="rtpu-zygote-reader").start()
        deadline = time.monotonic() + 20.0
        while not self._ready.wait(0.25):
            # abort EARLY on child death — a crashing bootstrap must not
            # cost the full timeout (and callers latch the failure so no
            # later spawn re-pays it)
            if self.proc.poll() is not None:
                self.dead = True
                self.restartable = False
                raise RuntimeError(
                    f"zygote exited rc={self.proc.returncode} at boot")
            if time.monotonic() > deadline:
                self.dead = True
                self.restartable = False
                try:
                    self.proc.kill()
                except Exception:
                    pass
                raise RuntimeError("zygote did not come up within 20s")

    def spawn(self, wid_hex: str, addr: str, session: str,
              log_path: str) -> _ZygoteChild:
        import json as _json

        child = _ZygoteChild(self, wid_hex)
        with self._lock:
            if self.dead:
                raise OSError("zygote dead")
            self._children[wid_hex] = child
            req = _json.dumps({"wid": wid_hex, "addr": addr,
                               "session": session, "log": log_path})
            self.proc.stdin.write((req + "\n").encode())
            self.proc.stdin.flush()
        return child

    def _reader_loop(self) -> None:
        import json as _json

        for line in self.proc.stdout:
            try:
                msg = _json.loads(line)
            except _json.JSONDecodeError:
                continue
            ev = msg.get("event")
            if ev == "ready":
                self._ready.set()
            elif ev == "spawned":
                with self._lock:
                    c = self._children.get(msg["wid"])
                if c is not None:
                    c._on_spawned(msg["pid"])
            elif ev == "exit":
                # map mutation under the same lock spawn() inserts with;
                # the child callback runs outside it (it only sets events,
                # but lock scope stays minimal on principle)
                with self._lock:
                    c = self._children.pop(msg["wid"], None)
                if c is not None:
                    c._on_exit(msg.get("status", -1))
        self.dead = True  # stdout EOF: zygote gone; proxies self-probe

    def close(self) -> None:
        self.dead = True
        self.restartable = False
        try:
            self.proc.stdin.close()  # zygote exits on stdin EOF
        except Exception:
            pass
        try:
            self.proc.wait(2.0)
        except Exception:
            try:
                self.proc.kill()
            except Exception:
                pass


class DriverRuntime:
    is_driver = True

    def __init__(
        self,
        num_cpus: Optional[int] = None,
        num_tpus: Optional[int] = None,
        resources: Optional[Dict[str, float]] = None,
        namespace: str = "default",
        worker_env: Optional[Dict[str, str]] = None,
        log_to_driver: bool = True,
        labels: Optional[Dict[str, str]] = None,
        _pool_prestart: Optional[int] = None,
    ):
        self.session = uuid.uuid4().hex[:12]
        self.namespace = namespace
        self.node_id = NodeID.from_random()
        # static node labels (reference NodeLabels role): user labels +
        # RTPU_NODE_LABELS env ("k=v,k=v"); NodeLabelSchedulingStrategy
        # targets them (TPU generation / slice type in real deployments)
        from ray_tpu.util.labels import parse_labels

        self.labels: Dict[str, str] = parse_labels(
            os.environ.get("RTPU_NODE_LABELS", ""))
        self.labels.update(labels or {})
        self.gcs = Gcs()
        self.store = StoreClient(self.session)
        self.worker_env = dict(worker_env or {})
        # Workers must not grab the TPU runtime by default — the driver (or
        # a designated actor) owns the chip. A hard "cpu" default, NOT the
        # driver's env value: on TPU boxes the global env often pins
        # JAX_PLATFORMS to the accelerator platform, and propagating that
        # would make every pool worker fight for the chip (and hang when
        # it is unclaimable). Opt back in per-actor with
        # @remote(runtime_env={"env_vars": {"JAX_PLATFORMS": ""}}).
        self.worker_env.setdefault("JAX_PLATFORMS", "cpu")

        cpus = num_cpus if num_cpus is not None else (os.cpu_count() or 1)
        from ray_tpu.accelerators.tpu import detect_num_tpu_chips

        tpus = num_tpus if num_tpus is not None else detect_num_tpu_chips()
        self.total: Dict[str, float] = {"CPU": float(cpus)}
        if tpus:
            self.total["TPU"] = float(tpus)
            # pod-slice resources (pod-name on every host, head marker on
            # worker 0) so slice-aware scheduling patterns resolve. Only
            # probed when TPU env/hardware signals are present — the GCE
            # metadata lookups inside would stall init for seconds off-GCP.
            import glob as _glob

            on_tpu_host = bool(
                os.environ.get("TPU_NAME")
                or os.environ.get("TPU_ACCELERATOR_TYPE")
                or _glob.glob("/dev/accel*"))
            if on_tpu_host:
                try:
                    from ray_tpu.accelerators.tpu import TPUAcceleratorManager

                    extras = TPUAcceleratorManager().get_extra_resources()
                    for k, v in extras.items():
                        self.total[k] = float(v)
                except Exception:
                    pass
        for k, v in (resources or {}).items():
            self.total[k] = float(v)
        self.avail = dict(self.total)

        # hot-lock contention accounting (util/contention.py): the
        # dispatch lock and ref lock are the driver's scalability
        # bottlenecks under multi-client load — instrument them so
        # state.summarize_contention() can say WHERE time goes
        from ray_tpu.util.contention import timed_lock, timed_rlock

        self.lock = timed_rlock("driver.lock")
        self.workers: Dict[WorkerID, _WorkerState] = {}
        self.ready_tasks: deque = deque()
        self.waiting_specs: Dict[bytes, dict] = {}
        self.cancelled: set = set()
        # pg_id -> {"bundles": {global idx: avail dict}, "totals": {...}}.
        # Keyed by GLOBAL bundle index: in cluster mode a node holds only
        # the bundles reserved on it (reference
        # placement_group_resource_manager.h role).
        self.pgs: Dict[bytes, dict] = {}
        # 2-phase reservation staging (reference GCS placement group
        # scheduler's prepare/commit, gcs_placement_group_scheduler.h:111):
        # resources are deducted at prepare, become a live pg at commit,
        # and return at abort (or reap, if the creator died mid-protocol).
        self._pg_staged: Dict[bytes, dict] = {}
        self.timeline_events: List[dict] = []
        self._task_start_ts: Dict[bytes, float] = {}
        # Task-lifecycle flight recorder (reference task_event_buffer.h
        # role): bounded ring of per-task phase timings feeding
        # state.summarize_tasks percentiles; built-in phase histograms are
        # created lazily (first finished task), with pre-sorted tag keys so
        # the per-task observe cost stays a few microseconds.
        self.task_ring: deque = deque(maxlen=int(config.get("task_ring")))
        self._flight_enabled = bool(config.get("flight_recorder"))
        # trace plane (receiver side): workers' span batches and this
        # process's own ring land here; on a node daemon the heartbeat
        # ships deltas to the GCS, on the head state.list_spans() reads it
        from ray_tpu.util.trace_store import TraceStore

        self.trace_store = TraceStore()
        # arming payload for workers spawned after enable_tracing()
        # (delivered on dial-back, like _fp_specs)
        self._trace_push = None
        # profiling plane (receiver side): workers' profile batches and
        # this process's own sampler window land here; daemons ship
        # deltas on the heartbeat, the head merges at state.profile()
        from ray_tpu.util import profiling as _profiling

        self.profile_store = _profiling.ProfileStore()
        self._profile_push = None
        # event plane (receiver side): workers' lifecycle-event batches
        # and this process's own ring land here; daemons ship deltas on
        # the heartbeat, the head serves state.list_events()
        from ray_tpu.util.event_store import EventStore

        self.event_store = EventStore()
        self._event_push = None
        # device plane (receiver side): workers' compiled-program
        # registry snapshots land here (replace-by-origin, like the
        # metric FederationStore — registry rows are mutable state, not
        # an append log); state.device_report() merges this with the
        # driver's own registry and remote nodes' GCS payloads
        from ray_tpu.util.device_plane import DeviceStore

        self.device_store = DeviceStore()
        # alerting watchdog (head-side): declarative rules over the
        # metric view, RTPU_ALERTS=0 kills it. Started here (the driver
        # IS the head in local mode and the head node's driver in
        # cluster mode); daemons don't evaluate — their metrics reach
        # the head on heartbeats.
        try:
            from ray_tpu.util import alerts as _alerts

            _alerts.start_watchdog()
        except Exception:
            pass
        # env-armed boot (RTPU_PROFILING=1 before init): resolving here
        # starts this process's sampler; one dict get when disarmed
        _profiling.profiling_enabled()
        # live cluster-wide stack dumps (`ray_tpu stack` py-spy role):
        # workers reply to a "stackdump" push with a "stacks" cast
        self._stack_replies: Dict[bytes, dict] = {}
        # object-memory forensics: creation metadata per object id
        # (owner process, wall-clock birth, optional call-site when the
        # profiler is armed) — bounded FIFO, pure dict work on hot paths
        self._obj_meta: "OrderedDict[bytes, dict]" = OrderedDict()
        self._obj_meta_cap = int(config.get("obj_meta_max"))
        self._phase_hist = None
        self._phase_keys: Dict[str, tuple] = {}
        self._status_keys = {False: (("status", "ok"),),
                             True: (("status", "error"),)}
        self._finished_counter = None
        # built-in scheduler/worker-pool counters (defs in
        # util/metric_defs.py, reference metric_defs.cc role); tag keys
        # pre-sorted for the submit/dispatch hot paths
        from ray_tpu.util import metric_defs as _md

        self._m_submitted = _md.get("rtpu_scheduler_tasks_submitted_total")
        self._m_dispatched = _md.get(
            "rtpu_scheduler_tasks_dispatched_total")
        self._m_spawns = _md.get("rtpu_worker_spawns_total")
        self._m_spawn_lat = _md.get("rtpu_worker_spawn_seconds")
        self._m_deaths = _md.get("rtpu_worker_deaths_total")
        self._m_zygote_restarts = _md.get("rtpu_zygote_restarts_total")
        self._type_keys = {ts.TASK: (("type", "task"),),
                           ts.ACTOR_CREATE: (("type", "actor_create"),),
                           ts.ACTOR_METHOD: (("type", "actor_method"),)}
        self.pool_cap = max(4, cpus)
        self.pool_hard_cap = max(64, cpus * 8)
        self._spawning = 0  # spawns decided but not yet registered
        self._shutdown = False

        # cluster-mode adapter (ray_tpu/cluster/adapter.py); None single-node
        self.cluster = None

        # Lineage for object reconstruction (reference
        # object_recovery_manager.h:41 / task_manager.h:468): return-id ->
        # producing TASK spec, bounded FIFO. A lost segment with live refs
        # re-executes the producer; recursion through lost deps happens
        # naturally (the re-executed task's worker hits the same path).
        # streaming-generator backpressure: task_id -> items consumed by
        # the ObjectRefGenerator; producers block on stream_permit until
        # consumption catches up (reference generator_waiter.cc). Permit
        # waits are entries in _stream_waiters serviced by whichever
        # thread advances consumption — no thread per permit. The counter
        # dict is bounded (entries are re-creatable by late acks).
        self._stream_consumed: Dict[bytes, int] = {}
        self._stream_waiters: List[tuple] = []  # (task_id, need, reply)
        self._stream_cv = threading.Condition(self.lock)

        # Distributed object lifetime (reference ReferenceCounter,
        # reference_count.h:61 role): per-object pin counts aggregate
        # (a) live ObjectRef instances in THIS process, (b) worker-reported
        # borrows, (c) task-argument pins held from submit until the task's
        # first return turns terminal. Node-level 0<->1 transitions are
        # reported to the cluster directory, which never evicts pinned
        # entries and tells holders to free segments on the last unpin.
        self._ref_lock = timed_lock("driver.ref_lock")
        self._pin_total: Dict[bytes, int] = {}
        self._arg_pins: Dict[bytes, List[bytes]] = {}
        # GC-safety (advisor r3): ObjectRef.__del__ can fire at ANY
        # allocation point — including on a thread that already holds
        # _ref_lock (a dict mutation inside _pin_delta triggering cycle
        # collection) or an rpc send lock. The __del__ hook therefore only
        # appends to a deque; normal code paths and a small janitor thread
        # drain it, and directory pin/unpin casts are queued under the lock
        # (preserving transition order) but shipped outside it. Shared
        # machinery: ray_tpu/core/refqueue.py.
        from ray_tpu.core.refqueue import DeferredDrops, OrderedCastFlusher

        self._cast_flusher = OrderedCastFlusher(self._send_pin_cast)
        # store pins to drop once outside _ref_lock: when the driver's
        # local refcount for an object hits zero, its store pin (taken by
        # get()) must drop too, or a free()d object consumed with the
        # get-then-free pattern stays kDeleting on the driver's reader ref
        # forever (the worker-side twin lives in worker.py)
        from collections import deque as _deque

        self._local_pin_releases: "_deque" = _deque()
        self._deferred_unpins = DeferredDrops(
            self._ref_lock, lambda b: self._apply_pin_locked(b, -1),
            self._after_ref_unpins)
        # outer object id -> ids of refs nested in its stored bytes, pinned
        # by THIS owner until the outer object is freed
        self._result_ref_pins: Dict[bytes, set] = {}
        from ray_tpu.core import object_ref as _object_ref

        _object_ref.set_ref_hook(
            lambda b: self._pin_delta(b, 1),
            self._deferred_unpins.append)
        self.gcs.on_terminal = self._release_arg_pins
        self._janitor_wake = threading.Event()  # never set; idle-typed wait
        threading.Thread(target=self._ref_janitor_loop, daemon=True,
                         name="rtpu-ref-janitor").start()

        self._lineage: Dict[bytes, dict] = {}
        self._lineage_cap = int(config.get("lineage_max"))
        # byte bound too (reference RAY_max_lineage_bytes role): specs keep
        # inlined serialized args alive, so count alone can hold GBs
        self._lineage_max_bytes = int(config.get("lineage_max_bytes"))
        self._lineage_bytes = 0
        self._lineage_sizes: Dict[bytes, int] = {}
        self._reconstructing: Dict[bytes, threading.Event] = {}

        self.session_dir = f"/tmp/rtpu-{self.session}"
        os.makedirs(os.path.join(self.session_dir, "logs"), exist_ok=True)
        self._sock_addr = os.path.join(self.session_dir, "driver.sock")
        from multiprocessing.connection import Listener

        # backlog: the default of 1 makes a 16-actor burst race the serial
        # accept loop — unix sockets return EAGAIN (not block) on a full
        # backlog, crashing the connecting worker (workers also retry)
        self._listener = Listener(self._sock_addr, family="AF_UNIX",
                                  backlog=64,
                                  authkey=self.session.encode())
        threading.Thread(target=self._accept_loop, daemon=True).start()

        self._zygote_obj = None
        self._zygote_disabled = False
        self._zygote_lock = threading.Lock()
        if _pool_prestart is None:
            _pool_prestart = int(config.get("pool_prestart"))
        self._prestart = min(_pool_prestart, self.pool_cap)
        for _ in range(self._prestart):
            self._spawn_worker("pool")

        # Log streaming to the driver (reference log_monitor.py +
        # GcsLogSubscriber, _raylet.pyx:3148 role): tail the session's
        # worker log files and echo new lines to the driver's stdout with
        # a worker prefix.
        self._log_monitor_stop = threading.Event()
        if log_to_driver and config.get("log_to_driver"):
            threading.Thread(target=self._log_monitor_loop, daemon=True,
                             name="rtpu-log-monitor").start()

        # OOM protection (reference MemoryMonitor + worker-killing policy):
        # kill the newest retriable task under host-RAM pressure. Killed
        # workers re-enter the normal death path, which retries the task.
        self._memory_monitor = None
        if config.get("memory_monitor"):
            from ray_tpu.core.memory_monitor import (MemoryMonitor,
                                                     kill_retriable_policy)

            threshold = float(config.get("memory_usage_threshold"))
            self._memory_monitor = MemoryMonitor(
                usage_threshold=threshold,
                on_pressure=kill_retriable_policy(self),
            ).start()

        self._metrics_collector = None
        self._register_core_gauges()

    def _register_core_gauges(self) -> None:
        """Sampled scheduler gauges (queue depth, in-flight, pool size,
        refcount/lineage table sizes), refreshed by the metrics collector
        hook at every exposition/federation snapshot — the mutation hot
        paths pay nothing. Lock-free reads: dict/deque sizes are
        approximate by nature here and a torn read only skews one sample."""
        from ray_tpu.util import metric_defs, metrics

        g_ready = metric_defs.get("rtpu_scheduler_ready_queue_depth")
        g_inflight = metric_defs.get("rtpu_scheduler_inflight_tasks")
        g_pending = metric_defs.get("rtpu_scheduler_actor_pending_calls")
        g_pool = metric_defs.get("rtpu_worker_pool_size")
        g_ref = metric_defs.get("rtpu_refcount_entries")
        g_argpin = metric_defs.get("rtpu_refcount_arg_pin_entries")
        g_lin = metric_defs.get("rtpu_lineage_entries")
        g_linb = metric_defs.get("rtpu_lineage_bytes")
        g_nframes = metric_defs.get("rtpu_pipe_native_frames")
        g_nmsgs = metric_defs.get("rtpu_pipe_native_messages")
        g_ntrans = metric_defs.get("rtpu_pipe_native_refpin_transitions")
        # last reconciled native totals per worker id (the engine counts
        # bytes/messages off-GIL; the rtpu_pipe_* counters are advanced by
        # the DELTA here so scrapes stay correct with zero per-message
        # Python cost on the native path)
        native_seen: Dict[bytes, dict] = {}

        def collect():
            if self._shutdown:
                metrics.unregister_collector(collect)
                return
            g_ready.set(len(self.ready_tasks))
            inflight = 0
            pool = {"starting": 0, "idle": 0, "busy": 0}
            nstats = {"sent_frames": 0, "sent_msgs": 0, "recv_frames": 0,
                      "recv_msgs": 0, "refpin_transitions": 0}
            native_any = False
            live_wids = set()
            for ws in list(self.workers.values()):
                inflight += len(ws.inflight_specs)
                if ws.status in pool:
                    pool[ws.status] += 1
                if ws.npipe is not None:
                    native_any = True
                    live_wids.add(ws.worker_id.binary())
                    try:
                        st = ws.npipe.stats()
                        if not st:
                            st = native_seen.get(ws.worker_id.binary(), {})
                        for k in nstats:
                            nstats[k] += st.get(k, 0)
                        last = native_seen.setdefault(
                            ws.worker_id.binary(), {})
                        d_sb = st.get("sent_bytes", 0) - last.get(
                            "sent_bytes", 0)
                        d_sm = st.get("sent_msgs", 0) - last.get(
                            "sent_msgs", 0)
                        d_rb = st.get("recv_bytes", 0) - last.get(
                            "recv_bytes", 0)
                        # FRAMES, not sub-messages: the Python reader
                        # counts one "message" per received frame (a
                        # coalesced batch counts once, its size going to
                        # rtpu_pipe_batch_messages) — keep the native
                        # reconciliation on the same definition so the
                        # off/on msgs-per-task A/B stays comparable
                        d_rm = st.get("recv_frames", 0) - last.get(
                            "recv_frames", 0)
                        if d_sb or d_sm or d_rb or d_rm:
                            m = _pipe_metrics()
                            m["sent"]._inc_key((), d_sb)
                            m["recv"]._inc_key((), d_rb)
                            m["msgs"]._inc_key(_SENT_KEY, d_sm)
                            m["msgs"]._inc_key(_RECV_KEY, d_rm)
                        native_seen[ws.worker_id.binary()] = dict(st)
                    except Exception:
                        pass
            g_inflight.set(inflight)
            for k, v in pool.items():
                g_pool.set(v, tags={"state": k})
            # prune reconciliation state for departed workers (their
            # final deltas were taken while they were still listed)
            for wid in list(native_seen):
                if wid not in live_wids:
                    del native_seen[wid]
            if native_any:
                # monotonic-within-a-worker-set counters, sampled (the
                # contention-stats pattern): mean msgs/frame is the
                # coalescing factor the A/B bench reads
                g_nframes.set(nstats["sent_frames"],
                              tags={"direction": "sent"})
                g_nframes.set(nstats["recv_frames"],
                              tags={"direction": "recv"})
                g_nmsgs.set(nstats["sent_msgs"], tags={"direction": "sent"})
                g_nmsgs.set(nstats["recv_msgs"], tags={"direction": "recv"})
                g_ntrans.set(nstats["refpin_transitions"])
            g_pending.set(sum(
                len(i.pending_queue)
                for i in list(self.gcs.actors.values())))
            g_ref.set(len(self._pin_total))
            g_argpin.set(len(self._arg_pins))
            g_lin.set(len(self._lineage))
            g_linb.set(self._lineage_bytes)

        self._metrics_collector = collect
        metrics.register_collector(collect)

    # ------------------------------------------------------------------
    # log streaming
    # ------------------------------------------------------------------

    def _log_monitor_loop(self):
        import sys

        logs_dir = os.path.join(self.session_dir, "logs")
        offsets: Dict[str, int] = {}
        partial: Dict[str, bytes] = {}
        while not self._log_monitor_stop.wait(0.2):
            try:
                names = os.listdir(logs_dir)
            except OSError:
                continue
            for name in names:
                if not name.endswith(".log"):
                    continue
                path = os.path.join(logs_dir, name)
                pos = offsets.get(name, 0)
                try:
                    size = os.path.getsize(path)
                    if size <= pos:
                        continue
                    with open(path, "rb") as f:
                        f.seek(pos)
                        chunk = f.read(size - pos)
                    offsets[name] = size
                except OSError:
                    continue
                data = partial.pop(name, b"") + chunk
                lines = data.split(b"\n")
                if lines and lines[-1]:
                    partial[name] = lines[-1]  # keep the unterminated tail
                prefix = f"({name[:-4]}) "
                for line in lines[:-1]:
                    try:
                        sys.stdout.write(
                            prefix + line.decode("utf-8", "replace") + "\n")
                    except Exception:
                        pass
            try:
                sys.stdout.flush()
            except Exception:
                pass

    # ------------------------------------------------------------------
    # worker lifecycle
    # ------------------------------------------------------------------

    def _accept_loop(self):
        while not self._shutdown:
            try:
                conn = self._listener.accept()
            except (OSError, EOFError):
                return
            try:
                kind, wid_bytes = conn.recv()
                assert kind == "hello"
            except Exception:
                conn.close()
                continue
            wid = WorkerID(wid_bytes)
            with self.lock:
                ws = self.workers.get(wid)
            if ws is None or ws.status == "dead":
                conn.close()
                continue
            ws.conn = conn
            ws.npipe = self._attach_native_pipe(conn)
            target = (self._native_reader_loop if ws.npipe is not None
                      else self._reader_loop)
            reader = threading.Thread(target=target, args=(ws,), daemon=True)
            ws.reader = reader
            reader.start()

    def _attach_native_pipe(self, conn):
        """The GIL-free engine for one worker connection, or None (kill
        switch RTPU_NATIVE_PIPE=0, missing/stale .so — hasattr-gated like
        rtpu_frag_stats, so a pre-pipe .so degrades to the Python path
        instead of crashing)."""
        if not config.get("native_pipe"):
            return None
        try:
            from ray_tpu import _native

            if not _native.pipe_engine_available():
                return None
            return _native.NativePipe(
                conn.fileno(),
                coalesce_us=int(config.get("pipe_native_coalesce_us")))
        except Exception:
            logger.exception("native pipe attach failed; Python pipe path")
            return None

    def _zygote(self):
        """The fork-server spawner (see core/zygote.py), started lazily.
        Returns None when disabled or dead (callers fall back to exec)."""
        if not config.get("worker_zygote") or self._zygote_disabled:
            return None
        with self._zygote_lock:
            z = self._zygote_obj
            if z is not None and not z.dead:
                return z
            if z is not None and z.dead and not z.restartable:
                return None
            try:
                env = dict(os.environ)
                env.update(self.worker_env)
                if env.get("JAX_PLATFORMS") == "axon" \
                        or env.get("RTPU_WORKER_FULL_SITE") == "1":
                    return None  # full-site workers need the real exec path
                env["RTPU_WORKER"] = "1"
                env["RTPU_NODE_ID"] = self.node_id.hex()
                if self.labels:
                    from ray_tpu.util.labels import format_labels

                    env["RTPU_NODE_LABELS"] = format_labels(self.labels)
                pkg_root = os.path.dirname(os.path.dirname(
                    os.path.dirname(os.path.abspath(__file__))))
                env["PYTHONPATH"] = (pkg_root + os.pathsep
                                     + env.get("PYTHONPATH", ""))
                self._zygote_obj = _Zygote(env)
                if z is not None:  # a previous fork-server died
                    self._m_zygote_restarts._inc_key(())
                return self._zygote_obj
            except Exception:
                logger.exception("zygote start failed; exec spawning only")
                # latch the failure: a crashing bootstrap must not re-pay
                # its boot timeout on every subsequent spawn
                self._zygote_disabled = True
                self._zygote_obj = None
                return None

    def _spawn_worker(self, kind: str) -> _WorkerState:
        import subprocess
        import sys

        # fast path: fork from the pre-warmed zygote (~5ms) instead of a
        # fresh interpreter exec (~0.15s CPU each, the actor/task launch
        # bottleneck on small hosts — VERDICT r3 #3)
        z = self._zygote()
        if z is not None:
            wid = WorkerID.from_random()
            log_path = os.path.join(self.session_dir, "logs",
                                    f"worker-{wid.hex()[:8]}.log")
            try:
                proc = z.spawn(wid.hex(), self._sock_addr, self.session,
                               log_path)
            except Exception:
                logger.exception("zygote spawn failed; falling back to exec")
            else:
                ws = _WorkerState(wid, proc, kind)
                ws.spawn_mode = "zygote"
                ws.log_path = log_path
                self._m_spawns._inc_key(_SPAWN_KEYS["zygote"])
                with self.lock:
                    self.workers[wid] = ws
                threading.Thread(target=self._reap, args=(ws,),
                                 daemon=True).start()
                self._note_spawn_event(ws)
                return ws

        wid = WorkerID.from_random()
        env = dict(os.environ)
        env.update(self.worker_env)
        env["RTPU_WORKER"] = "1"
        env["RTPU_NODE_ID"] = self.node_id.hex()
        if self.labels:
            # workers surface their node's labels (runtime context)
            from ray_tpu.util.labels import format_labels

            env["RTPU_NODE_LABELS"] = format_labels(self.labels)
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        log_path = os.path.join(self.session_dir, "logs", f"worker-{wid.hex()[:8]}.log")
        log_f = open(log_path, "wb", buffering=0)
        # The bootstrap ignores SIGUSR1 FIRST: a `ray_tpu stack` signal
        # landing during interpreter boot must not kill the worker before
        # its faulthandler registers. Done in-child via -c (preexec_fn is
        # documented-unsafe in threaded parents); the literal
        # "ray_tpu.core.worker" stays in the cmdline for `ray_tpu stack`
        # discovery.
        #
        # -S spawn (the actor/task launch-latency fix, VERDICT r3 #3): the
        # axon sitecustomize imports jax into EVERY python process (~1.9s
        # of a ~2.1s worker boot). Workers default to CPU jax, which needs
        # no plugin registration, so we skip site processing and re-add
        # site-packages by hand (addsitedir handles .pth files) — worker
        # boot drops to ~0.15s. Workers that really need the axon backend
        # (JAX_PLATFORMS=axon in worker_env, or RTPU_WORKER_FULL_SITE=1)
        # keep the full-site boot.
        full_site = (env.get("JAX_PLATFORMS") == "axon"
                     or env.get("RTPU_WORKER_FULL_SITE") == "1")
        if full_site:
            site_boot = ""
            py_flags = []
        else:
            dirs = ", ".join(repr(d) for d in _worker_site_dirs())
            site_boot = (f"import site; "
                         f"[site.addsitedir(d) for d in ({dirs},)]; ")
            py_flags = ["-S"]
        bootstrap = (
            "import signal; "
            "signal.signal(signal.SIGUSR1, signal.SIG_IGN); "
            + site_boot +
            "import runpy; "
            "runpy.run_module('ray_tpu.core.worker', run_name='__main__')"
        )
        proc = subprocess.Popen(
            [
                sys.executable,
                *py_flags,
                "-c",
                bootstrap,
                "--addr",
                self._sock_addr,
                "--session",
                self.session,
                "--worker-id",
                wid.hex(),
            ],
            env=env,
            stdout=log_f,
            stderr=subprocess.STDOUT,
            stdin=subprocess.DEVNULL,
        )
        log_f.close()
        ws = _WorkerState(wid, proc, kind)
        ws.log_path = log_path
        self._m_spawns._inc_key(_SPAWN_KEYS["exec"])
        with self.lock:
            self.workers[wid] = ws
        threading.Thread(target=self._reap, args=(ws,), daemon=True).start()
        self._note_spawn_event(ws)
        return ws

    def _note_spawn_event(self, ws: _WorkerState) -> None:
        """One worker_spawn lifecycle event per spawn (both paths)."""
        try:
            from ray_tpu.util import events as _events

            _events.emit("worker_spawn",
                         worker_id=ws.worker_id.hex()[:8],
                         kind=ws.kind, spawn_mode=ws.spawn_mode,
                         pid=getattr(ws.proc, "pid", None))
        except Exception:
            pass

    def _reap(self, ws: _WorkerState):
        ws.proc.wait()
        if not self._shutdown:
            self._on_worker_death(ws)

    def _reader_loop(self, ws: _WorkerState):
        import pickle as _pickle

        while True:
            try:
                # recv_bytes + loads == conn.recv() internals, with the
                # framed size in hand for the pipe byte counters
                buf = ws.conn.recv_bytes()
                if buf[:4] == _REFPIN_MAGIC:
                    # packed borrow transitions (workers ship these
                    # whether or not the driver's native engine loaded)
                    self._apply_refpin_frame(ws, buf[4:])
                    continue
                msg = _pickle.loads(buf)
            except (EOFError, OSError):
                self._on_worker_death(ws)
                return
            try:
                m = _pipe_metrics()
                m["recv"]._inc_key((), len(buf))
                m["msgs"]._inc_key(_RECV_KEY)
                if msg[0] == "batch":
                    m["batch"].observe(len(msg[1]))
            except Exception:
                pass
            # r13 coalescing: workers ship bursts of casts (and the
            # piggybacked urgent message) as ONE framed batch. Each
            # sub-message keeps its own error isolation — one bad cast
            # must not swallow the piggybacked done/req behind it.
            for sub in (msg[1] if msg[0] == "batch" else (msg,)):
                try:
                    self._handle_msg(ws, sub)
                except Exception:
                    import traceback

                    traceback.print_exc()

    def _apply_refpin_frame(self, ws: _WorkerState, payload: bytes) -> None:
        """Python-fallback twin of the native refpin table: parse a packed
        (id[16] + i8 delta)* frame and apply each transition in order."""
        import struct as _struct

        for oid_b, d in _struct.iter_unpack("<16sb", payload):
            self.worker_ref_delta(ws, oid_b, d)

    def _native_reader_loop(self, ws: _WorkerState):
        """Drain thread over the GIL-free engine: the engine's receiver
        thread already did the length-prefix reads, batch unpacking and
        refpin bookkeeping; this thread wakes per BURST (not per message),
        unpickles, and dispatches. Refpin-transition records go through
        the lock-free ``_native_cb_*`` callback and are applied at the
        explicit drain point below — never inside the callback."""
        import pickle as _pickle

        from ray_tpu import _native

        np_ = ws.npipe
        # metric handles hoisted out of the wake loop (a test's
        # clear_registry orphans them at worst — lost samples, not
        # errors; the byte/message counters are reconciled freshly by
        # the exposition collector either way), and the drain-shape
        # histogram is sampled 1-in-16 wakes
        try:
            m = _pipe_metrics()
        except Exception:
            m = None
        wakes = 0
        while True:
            recs = np_.drain(timeout=0.5)
            if recs is None:  # EOF: worker gone (all records delivered)
                if ws.native_pin_q:
                    self._drain_native_pins(ws)
                try:
                    # stop the engine's sender thread now (join happens at
                    # driver shutdown — never from this drain thread);
                    # drain_pins in the death path below still works
                    np_.shutdown()
                except Exception:
                    pass
                self._on_worker_death(ws)
                return
            if not recs:
                if ws.native_pin_q:
                    self._drain_native_pins(ws)
                continue
            wakes += 1
            if m is not None and not (wakes & 15):
                try:
                    m["ndrain"].observe(len(recs))
                except Exception:
                    pass
            for typ, payload in recs:
                if typ == _native.REC_REFPINS:
                    # queue (lock-free callback contract) AND drain
                    # IMMEDIATELY: transitions must apply in record order
                    # relative to the messages around them — a +1 borrow
                    # deferred past a later 'done' in the same burst
                    # would re-open the 1->0->1 unpin race the worker
                    # prevents by sending pins first
                    self._native_cb_refpins(ws, payload)
                    self._drain_native_pins(ws)
                    continue
                try:
                    msg = _pickle.loads(payload)
                except Exception:
                    # a mis-framed/corrupt record must be LOUD — if it
                    # carried a done, its caller is now hung (rate limit:
                    # one line per drop burst is fine at this severity)
                    logger.exception(
                        "dropping unpicklable pipe record from worker "
                        "%s (%d bytes)", ws.worker_id.hex()[:8],
                        len(payload))
                    continue
                if msg[0] == "batch":
                    try:
                        if m is not None:
                            m["batch"].observe(len(msg[1]))
                    except Exception:
                        pass
                    subs = msg[1]
                else:
                    subs = (msg,)
                for sub in subs:
                    try:
                        self._handle_msg(ws, sub)
                    except Exception:
                        import traceback

                        traceback.print_exc()
            # the drain point: apply queued transitions with locks allowed
            if ws.native_pin_q:
                self._drain_native_pins(ws)

    def _native_cb_refpins(self, ws: _WorkerState, payload: bytes) -> None:
        """Callback the native receiver drain hands packed refpin
        transitions to. MUST stay lock-free (graftlint
        native-callback-lock-discipline): it only appends to the
        CONNECTION's pending queue; ``_drain_native_pins`` applies at
        the reader's drain point."""
        # graftlint: deque append is GIL-atomic; no locks by contract
        ws.native_pin_q.append(payload)

    def _drain_native_pins(self, ws: _WorkerState) -> None:
        """Apply refpin transitions queued by the native callback (the
        only place they may take ``_ref_lock``-family locks). The queue
        is per-worker and drained only by that connection's reader
        thread, so a +1 borrow can never be applied after a LATER 'done'
        of the same burst (another reader stealing from a shared queue
        could be preempted holding the +1 while this thread releases the
        matching arg pin)."""
        import struct as _struct

        while True:
            try:
                payload = ws.native_pin_q.popleft()
            except IndexError:
                return
            for oid_b, d in _struct.iter_unpack("<16sb", payload):
                # per-worker bookkeeping lives in the NATIVE table (see
                # _drop_worker_pins); only the node-level pin moves here
                self._pin_delta(oid_b, d)

    def _on_worker_death(self, ws: _WorkerState):
        with self.lock:
            if ws.status == "dead":
                return
            was = ws.status
            ws.status = "dead"
        try:
            self._m_deaths._inc_key(())
        except Exception:
            pass
        # Death forensics at the reaping site (event plane): exit
        # code/signal from the Popen/zygote exit report, stderr tail +
        # error lines + last USR1 stack from the worker's log file —
        # built ONCE here and shared by the worker_death lifecycle event
        # and the WorkerCrashedError/ActorDiedError users see.
        pm = None
        try:
            from ray_tpu.util import events as _events

            # the pipe-EOF reader usually gets here BEFORE the reaper /
            # zygote exit report lands; the process is already dead, so
            # a short wait turns "unknown" into the real exit signal
            status = ws.proc.poll()
            if status is None:
                try:
                    status = ws.proc.wait(timeout=2.0)
                except Exception:
                    status = ws.proc.poll()
            pm = _events.build_postmortem(
                exit_status=status,
                log_path=ws.log_path,
                pid=getattr(ws.proc, "pid", None))
        except Exception:
            pm = None
        self._drop_worker_pins(ws)
        with self.lock:
            if not ws.released:
                self._release_locked(ws.held)
            spec = ws.current
            inflight = list(ws.inflight_specs.values())
            ws.inflight_specs.clear()
            ws.current = None
        try:
            from ray_tpu.util import events as _events

            _events.emit(
                "worker_death",
                worker_id=ws.worker_id.hex()[:8],
                kind=ws.kind,
                spawn_mode=ws.spawn_mode,
                pid=getattr(ws.proc, "pid", None),
                actor_id=(ActorID(ws.actor_id).hex()
                          if ws.actor_id else None),
                task=((spec.get("name") or spec.get("method"))
                      if spec else None),
                task_id=(spec["task_id"].hex()[:16]
                         if spec and spec.get("task_id") else None),
                cause=(pm or {}).get("cause", "unknown"),
                postmortem=pm)
        except Exception:
            pass
        if spec is not None and spec["type"] == ts.ACTOR_CREATE:
            self._actor_process_died(ws, [], pm)
        elif ws.actor_id is not None:
            self._actor_process_died(ws, inflight, pm)
        elif spec is not None:
            if spec.get("retries_left", 0) > 0:
                spec["retries_left"] -= 1
                self._enqueue_ready(spec)
            else:
                if spec["task_id"] in self.cancelled:
                    err = cloudpickle.dumps(
                        TaskCancelledError("task was cancelled (force)"))
                else:
                    err = cloudpickle.dumps(
                        _worker_crashed_error(ws, spec, pm))
                for rid in spec["return_ids"]:
                    self.gcs.mark_error(ObjectID(rid), err)
        with self.lock:
            alive_pool = sum(
                1 for w in self.workers.values() if w.kind == "pool" and w.status != "dead"
            )
            need = (
                ws.kind == "pool"
                and (self.ready_tasks or was == "busy")
                and alive_pool < self.pool_cap
            )
            shutdown = self._shutdown
        if need and not shutdown:
            self._spawn_worker("pool")
        self._pump()

    def _actor_process_died(self, ws: _WorkerState,
                            inflight_specs: List[dict],
                            pm: Optional[dict] = None):
        aid = ws.actor_id or next(
            (s.get("actor_id") for s in inflight_specs if s.get("actor_id")),
            None)
        if aid is None:
            return
        info = self.gcs.get_actor(ActorID(aid))
        if info is None:
            return
        err = cloudpickle.dumps(_actor_died_error(ActorID(aid).hex(), pm))
        for s in inflight_specs:
            for rid in s["return_ids"]:
                self.gcs.mark_error(ObjectID(rid), err)
        with self.lock:
            info.inflight = 0
            if info.restarts < info.max_restarts or info.max_restarts == -1:
                info.restarts += 1
                info.state = "RESTARTING"
                restart = True
            else:
                restart = False
        try:
            from ray_tpu.util import events as _events

            if restart:
                _events.emit("actor_restart",
                             actor_id=ActorID(aid).hex(),
                             restarts=info.restarts,
                             max_restarts=info.max_restarts,
                             worker_id=ws.worker_id.hex()[:8],
                             cause=(pm or {}).get("cause", "unknown"))
            else:
                _events.emit("actor_death",
                             actor_id=ActorID(aid).hex(),
                             restarts=info.restarts,
                             worker_id=ws.worker_id.hex()[:8],
                             cause=(pm or {}).get("cause", "unknown"),
                             postmortem=pm)
        except Exception:
            pass
        if restart:
            new_ws = self._spawn_worker("actor")
            new_ws.actor_id = aid
            info.worker_id = new_ws.worker_id
            create_spec = dict(info.create_spec)
            new_ws.pending_spec = create_spec
            # the dead process's holdings were released on death; the
            # restarted actor re-holds its creation resources (forced as a
            # fallback: a restart must not deadlock on a transiently busy
            # node — accounting catches up as other work finishes)
            res = create_spec.get("resources") or {}
            with self.lock:
                held = self._acquire_locked(res, create_spec.get("pg"),
                                     create_spec.get("bundle_index", -1))
                if held is None:
                    held = dict(res)
                    self._acquire_forced_locked(held)
                new_ws.held = held
        else:
            self._mark_actor_dead_and_flush(ActorID(aid), "process died", err)

    def _mark_actor_dead_and_flush(self, actor_id: ActorID, cause: str, err_blob: bytes):
        """Mark an actor DEAD and fail every queued method call — otherwise
        callers blocked on queued refs would hang forever."""
        info = self.gcs.get_actor(actor_id)
        self.gcs.mark_actor_dead(actor_id, cause)
        if self.cluster is not None:
            self.cluster.publish_actor_state(actor_id.binary(), "DEAD")
        if info is None:
            return
        with self.lock:
            queued = list(info.pending_queue)
            info.pending_queue.clear()
        for q in queued:
            for rid in q["return_ids"]:
                self.gcs.mark_error(ObjectID(rid), err_blob)

    # ------------------------------------------------------------------
    # message handling (driver side)
    # ------------------------------------------------------------------

    def _handle_msg(self, ws: _WorkerState, msg):
        kind = msg[0]
        if kind == "ready":
            # chaos plane: workers spawned after failpoints.arm() must be
            # armed too, before their first dispatch
            specs = getattr(self, "_fp_specs", None)
            if specs is not None:
                try:
                    ws.send(("fp", specs))
                except (OSError, BrokenPipeError):
                    pass
            # trace plane: workers spawned after enable_tracing() must be
            # armed before their first dispatch, like failpoints above
            tpush = getattr(self, "_trace_push", None)
            if tpush is not None:
                try:
                    ws.send(("trace", tpush))
                except (OSError, BrokenPipeError):
                    pass
            # profiling plane: same replay for enable_profiling()
            ppush = getattr(self, "_profile_push", None)
            if ppush is not None:
                try:
                    ws.send(("prof", ppush))
                except (OSError, BrokenPipeError):
                    pass
            # event plane: same replay for enable/disable_events()
            epush = getattr(self, "_event_push", None)
            if epush is not None:
                try:
                    ws.send(("events", epush))
                except (OSError, BrokenPipeError):
                    pass
            with self.lock:
                was_starting = ws.status == "starting"
                if was_starting:
                    ws.status = "idle"
                pending = ws.pending_spec
                ws.pending_spec = None
            if was_starting:
                # worker launch latency: spawn decision -> ready message
                # (the zygote-vs-exec attribution for actors_launched/s)
                try:
                    self._m_spawn_lat._observe_key(
                        _SPAWN_KEYS[ws.spawn_mode],
                        time.monotonic() - ws.spawn_ts)
                except Exception:
                    pass
            if pending is not None:
                self._dispatch_to(ws, pending)
            else:
                self._pump()
        elif kind == "done":
            self._handle_done(ws, msg[1], msg[2],
                              msg[3] if len(msg) > 3 else None)
        elif kind == "cast":
            self._handle_cast(ws, msg[1], msg[2])
        elif kind == "req":
            self._handle_req(ws, msg[1], msg[2], msg[3])

    def _handle_done(self, ws: _WorkerState, task_id_b: bytes, results,
                     phases: Optional[dict] = None):
        with self.lock:
            spec = ws.inflight_specs.pop(task_id_b, None)
        if spec is None:
            # Every dispatch path goes through _dispatch_to, which populates
            # inflight_specs — an unknown id is a duplicate or late "done"
            # and must not be re-processed against an unrelated spec
            # (double-decrementing actor inflight, re-marking objects).
            logger.warning("dropping done for unknown task %s from worker %s",
                           task_id_b.hex()[:8], ws.worker_id.hex()[:8])
            return
        failed = bool(results and results[0][1] == "e")
        # retry_exceptions (reference ``@ray.remote(retry_exceptions=...)``):
        # an APPLICATION failure resubmits the task instead of surfacing,
        # while retries last. Plain tasks only — actor calls mutate state
        # and streaming tasks already announced yields; cancelled tasks
        # must surface TaskCancelledError, never re-run.
        retrying = (failed and spec["type"] == ts.TASK
                    and spec.get("retry_exceptions")
                    and spec.get("retries_left", 0) > 0
                    and not spec.get("streaming")
                    and spec["task_id"] not in self.cancelled)
        rex = spec.get("retry_exceptions")
        if retrying and isinstance(rex, bytes):
            # reference list form (cloudpickled tuple of types, see
            # make_task_spec): retry ONLY those — anything else is
            # intentionally fatal and must surface. The shipped payload
            # wraps the user exception in TaskError; match the cause.
            try:
                err = cloudpickle.loads(results[0][2])
                cause = getattr(err, "cause", err)
                retrying = isinstance(cause, cloudpickle.loads(rex))
            except Exception:
                retrying = False
        if retrying:
            spec["retries_left"] = spec.get("retries_left", 0) - 1
        else:
            self._apply_done_results(
                results, owner="worker:" + ws.worker_id.hex()[:8])
        fire = []
        with self._stream_cv:
            self._stream_consumed.pop(task_id_b, None)
            kept = []
            for tid, need, rep in self._stream_waiters:
                if tid == task_id_b:
                    fire.append(rep)  # task over: release any blocked producer
                else:
                    kept.append((tid, need, rep))
            self._stream_waiters = kept
        for rep in fire:
            rep(True)
        start = self._task_start_ts.pop(task_id_b, None)
        if start is not None and len(self.timeline_events) < 200_000:
            name = (spec or {}).get("name") or (spec or {}).get("method") or "task"
            tid_lane = ws.worker_id.hex()[:8]
            self.timeline_events.append(
                {
                    "name": name,
                    "ph": "X",
                    "ts": start * 1e6,
                    "dur": (time.time() - start) * 1e6,
                    "pid": 1,
                    "tid": tid_lane,
                }
            )
            if phases:
                # nested lifecycle slices: Chrome-trace nests same-lane X
                # events by containment, so the worker-side phase durations
                # laid out sequentially from dispatch render as children of
                # the task slice. Sub-millisecond phases are skipped — they
                # are invisible at trace zoom and would swell the event
                # list ~5x on microbench-style task storms.
                t = start
                for ph in ("arg_fetch", "deserialize", "execute",
                           "store_result"):
                    d = phases.get(ph)
                    if not d:
                        continue
                    if d >= 1e-3:
                        self.timeline_events.append(
                            {"name": f"{name}:{ph}", "ph": "X",
                             "ts": t * 1e6, "dur": d * 1e6, "pid": 1,
                             "tid": tid_lane, "cat": "task_phase"})
                    t += d
        if spec is not None and start is not None and self._flight_enabled:
            self._record_flight(spec, ws, start, phases, failed=failed)
        with self.lock:
            if not ws.inflight_specs:
                ws.current = None
            is_create = spec is not None and spec["type"] == ts.ACTOR_CREATE
            is_method = spec is not None and spec["type"] == ts.ACTOR_METHOD
            if is_method or (is_create and not failed):
                # actors HOLD their creation resources while alive (Ray
                # parity: num_cpus/custom resources gate actor packing,
                # not just __init__); method calls acquire nothing, so
                # there is nothing to release either. Death/kill releases
                # via _on_worker_death.
                if ws.released:
                    self._acquire_forced_locked(ws.held)
            else:
                if not ws.released:
                    self._release_locked(ws.held)
                ws.held = {}
            ws.released = False
            if spec is not None and spec["type"] == ts.ACTOR_CREATE:
                info = self.gcs.get_actor(ActorID(spec["actor_id"]))
                if info is not None:
                    if failed:
                        info.state = "DEAD"
                    else:
                        info.state = "ALIVE"
                    if self.cluster is not None:
                        self.cluster.publish_actor_state(
                            spec["actor_id"], info.state)
                ws.status = "idle"
            elif spec is not None and spec["type"] == ts.ACTOR_METHOD:
                info = self.gcs.get_actor(ActorID(spec["actor_id"]))
                if info is not None:
                    info.inflight = max(0, info.inflight - 1)
                ws.status = "idle" if not ws.inflight_specs else "busy"
            else:
                ws.status = "idle"
        if spec is not None and spec["type"] == ts.ACTOR_CREATE and failed:
            self._mark_actor_dead_and_flush(
                ActorID(spec["actor_id"]), "creation task failed", results[0][2]
            )
        if retrying:
            logger.info("retrying task %s after application error "
                        "(%d retries left)", task_id_b.hex()[:8],
                        spec.get("retries_left", 0))
            self._enqueue_ready(spec)
        self._pump()

    def _apply_done_results(self, results, owner: str = "") -> None:
        """Publish one done message's results to the object directory."""
        for entry in results:
            rid, rkind, payload = entry[0], entry[1], entry[2]
            oid = ObjectID(rid)
            if owner:
                self._note_obj_meta(rid, owner)
            # refs nested in the RESULT: pin them against the return
            # object's lifetime BEFORE marking ready (a consumer must
            # never observe the outer ready while inner refs are freeable)
            if len(entry) > 3 and entry[3]:
                self._pin_result_refs(rid, entry[3])
            if rkind == "i":
                self.gcs.mark_ready(oid, inline=payload)
            elif rkind == "s":
                # payload = segment size (directory needs it so peers can
                # pick chunked vs whole-blob pulls)
                self.gcs.mark_ready(oid, size=payload or 0)
            else:
                self.gcs.mark_error(oid, payload)

    # ------------------------------------------------------------------
    # task-lifecycle flight recorder
    # ------------------------------------------------------------------

    def _phase_metrics(self):
        if self._phase_hist is None:
            from ray_tpu.util import metric_defs

            # racing first-finishers both create; registration merges, so
            # samples land in one shared store either way
            self._phase_hist = metric_defs.get("rtpu_task_phase_seconds")
            self._finished_counter = metric_defs.get(
                "rtpu_tasks_finished_total")
        return self._phase_hist

    def _record_flight(self, spec: dict, ws: _WorkerState, start_ts: float,
                       wphases: Optional[dict], failed: bool) -> None:
        """One finished task -> phase histograms + ring-buffer record.
        Driver-side phases (queue = dependency wait, lease = wait for a
        worker) come from the spec's lifecycle stamps; worker-side phases
        ride the done message. Everything here is dict/list work — no
        syscalls on the result path."""
        now = time.time()
        ph: Dict[str, float] = {}
        sub = spec.get("lc_submit")
        rdy = spec.get("lc_ready")
        if sub is not None and rdy is not None:
            ph["queue"] = max(0.0, rdy - sub)
        if rdy is not None:
            ph["lease"] = max(0.0, start_ts - rdy)
        if wphases:
            ph.update(wphases)
        ph["total"] = max(0.0, now - (sub if sub is not None else start_ts))
        try:
            hist = self._phase_metrics()
            keys = self._phase_keys
            hist.observe_many(
                (keys.get(k) or keys.setdefault(k, (("phase", k),)), v)
                for k, v in ph.items())
            self._finished_counter._inc_key(self._status_keys[failed])
        except Exception:
            pass
        # raw ids here; state.list_task_events hexes at query time (the
        # conversion is per-query, not per-task)
        self.task_ring.append({
            "task_id": spec["task_id"],
            "name": spec.get("name") or spec.get("method") or "task",
            "type": spec["type"],
            "worker_id": ws.worker_id,
            "status": "error" if failed else "ok",
            "phases": ph,
            "ts": now,
        })

    def _handle_cast(self, ws: _WorkerState, op: str, args):
        if op == "put":
            oid = ObjectID(args[0])
            # size rides the message (worker had it in hand at write time)
            size = args[2] if len(args) > 2 and args[1] is None else 0
            if len(args) > 3 and args[3]:
                # refs nested in the stored value: owner-pinned until the
                # outer object is freed
                self._pin_result_refs(args[0], args[3])
            self._note_obj_meta(
                args[0], "worker:" + ws.worker_id.hex()[:8],
                args[4] if len(args) > 4 else None)
            self.gcs.mark_ready(oid, inline=args[1], size=size)
        elif op == "submit":
            if self.cluster is not None:
                # placement may consult the GCS (dependency locality):
                # never block the worker-pipe receiver on the network
                self.cluster._io.submit(self.submit_spec, args[0])
            else:
                self.submit_spec(args[0])
        elif op == "actor_call":
            self._submit_actor_spec(args[0])
        elif op == "fn_put":
            self.gcs.register_fn(args[0], args[1])
            if self.cluster is not None:
                # publish to the global table too (worker-submitted specs
                # may spill to peers); async — this receiver thread must
                # keep demuxing, and consumers poll fetch_fn meanwhile
                self.cluster.publish_fn_async(args[0], args[1])
        elif op == "blocked":
            with self.lock:
                if not ws.released and ws.current is not None:
                    self._release_locked(ws.held)
                    ws.released = True
            self._pump()
        elif op == "unblocked":
            with self.lock:
                if ws.released:
                    self._acquire_forced_locked(ws.held)
                    ws.released = False
        elif op == "kill_actor":
            self.kill_actor(args[0], args[1])
        elif op == "cancel":
            self.cancel_task(ObjectID(args[0]),
                             args[1] if len(args) > 1 else False)
        elif op == "stream_consumed":
            self.stream_consumed(args[0], args[1],
                                 args[2] if len(args) > 2 else None)
        elif op == "refpins":
            # batched borrow transitions (r13 coalescing): list order IS
            # transition order, applied sequentially
            for oid_b, d in args[0]:
                self.worker_ref_delta(ws, oid_b, d)
        elif op == "metrics":
            # batched metric-delta push from the worker (federation): pure
            # dict merges — safe on this receiver thread
            from ray_tpu.util.metrics import federation

            wid = ws.worker_id.hex()[:8]
            federation.ingest(
                "worker:" + wid,
                {"worker_id": wid, "node_id": self.node_id.hex()[:8],
                 "component": "worker"},
                args[0])
        elif op == "spans":
            # trace plane: batched span push from the worker — pure deque
            # appends into the bounded TraceStore, safe on this thread
            try:
                self.trace_store.ingest(
                    args[0],
                    {"worker_id": ws.worker_id.hex()[:8],
                     "node_id": self.node_id.hex()[:8],
                     "component": "worker"})
            except Exception:
                pass
        elif op == "prof":
            # profiling plane: batched profile push from the worker —
            # pure deque appends into the bounded ProfileStore
            try:
                self.profile_store.ingest(
                    args[0],
                    {"worker_id": ws.worker_id.hex()[:8],
                     "node_id": self.node_id.hex()[:8],
                     "component": "worker"})
            except Exception:
                pass
        elif op == "events":
            # event plane: batched lifecycle-event push from the worker —
            # pure deque appends into the bounded EventStore
            try:
                self.event_store.ingest(
                    args[0],
                    {"worker_id": ws.worker_id.hex()[:8],
                     "node_id": self.node_id.hex()[:8],
                     "component": "worker"})
            except Exception:
                pass
        elif op == "device":
            # device plane: version-gated program-registry snapshot from
            # the worker — replace semantics keyed by worker origin
            try:
                self.device_store.ingest(
                    ws.worker_id.hex()[:8],
                    {"worker_id": ws.worker_id.hex()[:8],
                     "node_id": self.node_id.hex()[:8],
                     "component": "worker"},
                    args[0])
            except Exception:
                pass
        elif op == "stacks":
            # live stack-dump reply (`ray_tpu stack` py-spy role)
            self._stack_replies[ws.worker_id.binary()] = {
                "ts": time.monotonic(), "stacks": args[0]}
        elif op == "free":
            # full free path (directory + store + CLUSTER publication):
            # a worker-initiated free must reach holder nodes too, or the
            # streaming reducers' frees leak remote copies cluster-wide
            self.free(args[0])

    def _handle_req(self, ws: _WorkerState, req_id: int, op: str, args):
        def reply(payload, err: Optional[BaseException] = None):
            try:
                if err is not None:
                    ws.send(("reply", req_id, "err", cloudpickle.dumps(err)))
                else:
                    ws.send(("reply", req_id, "ok", payload))
            except (OSError, BrokenPipeError):
                pass

        try:
            if op == "get":
                ids, timeout = args[0], args[1]
                if len(args) > 2 and args[2]:
                    # worker-forwarded chunk-alignment hints: the pull
                    # runs HERE, so the registry must live here too
                    try:
                        from ray_tpu.cluster.adapter import \
                            hint_pull_align

                        for oid_b, hint in args[2].items():
                            stride, payload = (
                                hint if isinstance(hint, (tuple, list))
                                else (hint, 0))
                            hint_pull_align(oid_b, stride, payload)
                    except Exception:
                        pass
                self._async_get(ids, timeout, reply)
            elif op == "wait":
                ids, num_returns, timeout = args
                self._async_wait(ids, num_returns, timeout, reply)
            elif op == "stream_permit":
                tid, need = args[0], args[1]
                with self._stream_cv:
                    if (self._stream_consumed.get(tid, 0) >= need
                            or self._shutdown):
                        fire = True
                    else:
                        self._stream_waiters.append((tid, need, reply))
                        fire = False
                if fire:
                    reply(True)
            elif op == "reconstruct":
                # blocks until the producer re-ran: always off the
                # receiver thread
                def _rec(b=args[0]):
                    return self.reconstruct_object(ObjectID(b))

                def run():
                    try:
                        reply(_rec())
                    except BaseException as e:  # noqa: BLE001
                        reply(None, e)

                threading.Thread(target=run, daemon=True).start()
            elif op == "fn_get":
                def _fn_get(h=args[0]):
                    blob = self.gcs.get_fn(h)
                    if blob is None and self.cluster is not None:
                        blob = self.cluster.fetch_fn(h)
                        if blob is not None:
                            self.gcs.register_fn(h, blob)
                    return blob

                # may hit the cluster GCS: keep it off the receiver thread
                self._reply_offloaded(reply, _fn_get)
            elif op == "actor_create":
                self.submit_spec(args[0])
                reply(None)
            elif op == "name_lookup":
                # lookup_named_actor falls through to the cluster registry,
                # so workers resolve actors created on peer nodes too;
                # cluster mode offloads the network hop off this receiver
                # thread (it must keep demuxing results)
                self._reply_offloaded(
                    reply, lambda: self.lookup_named_actor(args[0]))
            elif op == "kv":
                # kv_op routes to the global GCS in cluster mode — worker
                # writes must land in the same store driver reads hit
                self._reply_offloaded(
                    reply, lambda: self.kv_op(args[0], *args[1:]))
            elif op == "actor_depths":
                reply(self.actor_queue_depths(args[0]))
            elif op == "resources":
                with self.lock:
                    reply(dict(self.avail if args[0] == "avail" else self.total))
            elif op == "nodes":
                reply(self.node_info())
            elif op == "pg_create":
                # cluster mode reserves bundles over the network: offload
                self._reply_offloaded(
                    reply,
                    lambda: self.create_placement_group(args[0], args[1]))
            elif op == "pg_remove":
                def _rm(pg_id=args[0]):
                    self.remove_placement_group(pg_id)

                self._reply_offloaded(reply, _rm)
            else:
                reply(None, RuntimeError(f"unknown op {op}"))
        except BaseException as e:  # noqa: BLE001
            reply(None, e)

    # ------------------------------------------------------------------
    # object reference pins
    # ------------------------------------------------------------------

    def _pin_delta(self, oid_b: bytes, d: int) -> None:
        if self._shutdown:
            return
        with self._ref_lock:
            self._apply_pin_locked(oid_b, d)
        self._flush_ref_casts()
        self._drain_deferred_unpins()
        self._drain_local_pin_releases()

    def _after_ref_unpins(self) -> None:
        """Post-drain hook of the deferred __del__ unpins."""
        self._flush_ref_casts()
        self._drain_local_pin_releases()

    def _drain_local_pin_releases(self) -> None:
        while True:
            try:
                # graftlint: disable=unguarded-shared-write -- deque ops are
                # GIL-atomic; the drain is deliberately lock-free (GC-safety
                # design, refqueue.py: __del__ hooks must take no locks)
                b = self._local_pin_releases.popleft()
            except IndexError:
                return
            try:
                self.store.release(ObjectID(b))
            except Exception:
                pass

    def _apply_pin_locked(self, oid_b: bytes, d: int) -> None:
        before = self._pin_total.get(oid_b, 0)
        after = before + d
        if after > 0:
            self._pin_total[oid_b] = after
        else:
            self._pin_total.pop(oid_b, None)
            if before > 0:
                # last local reference gone: queue the store-pin drop
                # (executed outside _ref_lock; view-liveness guarded)
                self._local_pin_releases.append(oid_b)
        # record the transition INSIDE the lock (pin/unpin casts must reach
        # the directory in transition order or a 1->0->1 race could leave a
        # live object unpinned remotely); the network cast itself happens
        # outside via _flush_ref_casts — rpc IO under _ref_lock widened the
        # GC self-deadlock window (advisor r3)
        if self.cluster is not None:
            if before == 0 and after > 0:
                self._cast_flusher.append((oid_b, 1))
            elif before > 0 and after <= 0:
                self._cast_flusher.append((oid_b, -1))

    def _pin_result_refs(self, outer_b: bytes, nested) -> None:
        """Pin refs nested inside a stored value against the OUTER object's
        lifetime (reference borrowed-refs-in-returned-values role): without
        this, the producer dropping its local ObjectRefs lets the global
        refcount hit zero and the free-grace sweep deletes the inner object
        before a late consumer deserializes. Released on the outer's
        'freed' publication (or never, in local mode, where no pin-driven
        freeing exists). Idempotent per (outer, inner): a lineage re-run
        re-ships the same nested list."""
        # record AND pin in ONE critical section: releasing between them
        # lets a concurrent _release_result_ref_pins (freed publication)
        # pop the set before the +1 lands, leaking a permanent pin
        with self._ref_lock:
            have = self._result_ref_pins.setdefault(outer_b, set())
            fresh = [b for b in nested if b not in have]
            have.update(fresh)
            for b in fresh:
                self._apply_pin_locked(b, 1)
        self._flush_ref_casts()
        self._drain_deferred_unpins()

    def _release_result_ref_pins(self, outer_b: bytes) -> None:
        with self._ref_lock:
            nested = self._result_ref_pins.pop(outer_b, None)
            for b in nested or ():
                self._apply_pin_locked(b, -1)
        if nested:
            self._flush_ref_casts()

    def _drain_deferred_unpins(self) -> None:
        """Apply unpins queued by ObjectRef.__del__ (which must not lock)."""
        if not self._shutdown:
            self._deferred_unpins.drain()

    def _send_pin_cast(self, item) -> None:
        oid_b, op = item
        if op > 0:
            self.cluster.pin_object(oid_b)
        else:
            self.cluster.unpin_object(oid_b)

    def _flush_ref_casts(self) -> None:
        """Ship queued pin/unpin transitions to the directory, in order."""
        if self.cluster is None:
            # graftlint: disable=unguarded-shared-write -- OrderedCastFlusher
            # is internally synchronized (atomic deque + try-lock flusher)
            self._cast_flusher.clear()
            return
        self._cast_flusher.flush()

    def _ref_janitor_loop(self) -> None:
        """Bound unpin staleness on an otherwise-idle driver: __del__ only
        queues; this drains every couple of seconds. Event.wait, not
        time.sleep: the sampling profiler cannot see C-level sleeps, so a
        time.sleep here would read as 2s of busy driver CPU per tick."""
        while not self._shutdown:
            self._janitor_wake.wait(2.0)
            try:
                self._drain_deferred_unpins()
                self._drain_local_pin_releases()
            except Exception:
                pass

    def _pin_args(self, spec: dict) -> None:
        """Pin a spec's argument objects until its first return is
        terminal — a submitted task keeps its args alive even when the
        caller dropped every ObjectRef (reference 'submitted task
        reference' semantics)."""
        deps = ts.arg_refs(spec["args"], spec["kwargs"])
        borrowed = spec.get("borrowed") or []
        if (not deps and not borrowed) or not spec["return_ids"]:
            return
        key = spec["return_ids"][0]
        with self._ref_lock:
            already = key in self._arg_pins
        if already:
            return  # resubmission (retry/reconstruction): pins survive
        dep_bytes = [d.binary() for d in deps] + list(borrowed)
        with self._ref_lock:
            self._arg_pins[key] = dep_bytes
        for b in dep_bytes:
            self._pin_delta(b, 1)

    def _release_arg_pins(self, oid: ObjectID) -> None:
        with self._ref_lock:
            deps = self._arg_pins.pop(oid.binary(), None)
        if deps:
            for b in deps:
                self._pin_delta(b, -1)

    def worker_ref_delta(self, ws, oid_b: bytes, d: int) -> None:
        """A worker reported a borrow transition (0<->1 in that process)."""
        if d > 0:
            ws.pinned[oid_b] = ws.pinned.get(oid_b, 0) + 1
        else:
            n = ws.pinned.get(oid_b, 0) - 1
            if n <= 0:
                ws.pinned.pop(oid_b, None)
            else:
                ws.pinned[oid_b] = n
        self._pin_delta(oid_b, d)

    def _drop_worker_pins(self, ws) -> None:
        pins = ws.pinned
        ws.pinned = {}
        for oid_b, n in pins.items():
            for _ in range(n):
                self._pin_delta(oid_b, -1)
        if ws.npipe is not None:
            # the native engine owns this connection's borrow table;
            # drain-and-clear it so a dead worker's pins release exactly
            # like the Python-path ws.pinned above
            try:
                native_pins = ws.npipe.drain_pins()
            except Exception:
                native_pins = []
            for oid_b, n in native_pins:
                # a positive native count surfaced exactly ONE +1
                # transition to _pin_total (0<->1 semantics): undo it once
                if n > 0:
                    self._pin_delta(oid_b, -1)

    # ------------------------------------------------------------------
    # lineage reconstruction
    # ------------------------------------------------------------------

    def _record_lineage(self, spec: dict) -> None:
        # approximate retained size: inlined arg blobs dominate
        approx = 256 + sum(
            len(e[1]) for e in list(spec["args"]) + list(spec["kwargs"].values())
            if e[0] == "v")
        with self.lock:
            for rid in spec["return_ids"]:
                self._lineage[rid] = spec
                self._lineage_sizes[rid] = approx
                self._lineage_bytes += approx
            # bounded FIFO by count AND bytes: evict oldest past either cap
            while (len(self._lineage) > self._lineage_cap
                   or self._lineage_bytes > self._lineage_max_bytes):
                old = next(iter(self._lineage))
                self._lineage.pop(old)
                self._lineage_bytes -= self._lineage_sizes.pop(old, 0)

    def reconstruct_object(self, oid: ObjectID,
                           timeout: float = 120.0) -> bool:
        """Re-execute the producer of a lost object (segment evicted or
        deleted behind the directory's back). Returns True when the object
        is terminal again.

        Deduplication is per PRODUCING TASK: concurrent callers for any of
        the task's return objects share one re-execution (per-object keys
        would let siblings of a multi-return task launch duplicate runs).
        Healthy sibling returns keep their segments — only lost ones are
        reset, and the store's idempotent put skips re-writing survivors.
        """
        b = oid.binary()
        with self.lock:
            spec = self._lineage.get(b)
            if spec is None:
                return False
            task_key = spec["task_id"]
            ev = self._reconstructing.get(task_key)
            if ev is not None:
                waiter_only = True
            else:
                ev = threading.Event()
                self._reconstructing[task_key] = ev
                waiter_only = False
        if waiter_only:
            ev.wait(timeout)
            st = self.gcs.object_state(oid)
            return st is not None and st.status in (READY, ERROR)
        try:
            logger.info("reconstructing lost object %s via task %s",
                        oid.hex()[:8], spec.get("name", "?"))
            respec = dict(spec)
            respec["retries_left"] = spec.get("max_retries", 0)
            # the original consumer is gone: a re-run producer waiting on
            # backpressure permits would park forever
            respec.pop("stream_backpressure", None)
            for rid in respec["return_ids"]:
                roid = ObjectID(rid)
                st = self.gcs.object_state(roid)
                inline = st is not None and st.inline is not None
                if not inline and not self.store.contains(roid):
                    self.gcs.reset_object(roid)
            self.submit_spec(respec)
            ready, _ = self.gcs.wait_objects([oid], 1, timeout)
            return bool(ready)
        finally:
            with self.lock:
                self._reconstructing.pop(task_key, None)
            ev.set()

    def _get_with_recovery(self, oid: ObjectID):
        try:
            return self.store.get(oid)
        except (FileNotFoundError, OSError):
            if not self.reconstruct_object(oid):
                raise
            st = self.gcs.object_state(oid)
            if st is not None and st.status == ERROR:
                raise cloudpickle.loads(st.error)
            if st is not None and st.inline is not None:
                return serialization.loads_oob(st.inline)
            return self.store.get(oid)

    def _reply_offloaded(self, reply, fn):
        """Run ``fn`` and reply — on the cluster io pool when in cluster
        mode (the call may hit the network), inline otherwise."""
        def run():
            try:
                reply(fn())
            except BaseException as e:  # noqa: BLE001
                reply(None, e)

        if self.cluster is not None:
            self.cluster._io.submit(run)
        else:
            run()

    # -- async get/wait used by worker requests ---------------------------

    def _object_payload(self, oid: ObjectID):
        st = self.gcs.object_state(oid)
        if st is None or st.status == "PENDING":
            return None
        if st.status == ERROR:
            return ("e", st.error)
        if st.inline is not None:
            return ("i", st.inline)
        return ("s", None)

    def _async_get(self, ids: List[bytes], timeout, reply):
        oids = [ObjectID(b) for b in ids]
        self._cluster_watch(oids)
        fired = threading.Event()
        timer_box = []

        def on_ready():
            if fired.is_set():
                return
            fired.set()
            for t in timer_box:
                t.cancel()
            reply([self._object_payload(o) for o in oids])

        waiter = self.gcs.add_waiter(oids, len(oids), on_ready)
        if timeout is not None:
            def on_timeout():
                if fired.is_set():
                    return
                fired.set()
                self.gcs.cancel_waiter(waiter)
                reply(None)

            t = threading.Timer(timeout, on_timeout)
            t.daemon = True
            timer_box.append(t)
            t.start()

    def _async_wait(self, ids: List[bytes], num_returns: int, timeout, reply):
        oids = [ObjectID(b) for b in ids]
        self._cluster_watch(oids)
        fired = threading.Event()
        timer_box = []

        def snapshot():
            ready, rest = [], []
            for o in oids:
                st = self.gcs.object_state(o)
                if st is not None and st.status in (READY, ERROR) and len(ready) < num_returns:
                    ready.append(o.binary())
                else:
                    rest.append(o.binary())
            return ready, rest

        def on_ready():
            if fired.is_set():
                return
            fired.set()
            for t in timer_box:
                t.cancel()
            reply(snapshot())

        waiter = self.gcs.add_waiter(oids, min(num_returns, len(oids)), on_ready)
        if timeout is not None:
            def on_timeout():
                if fired.is_set():
                    return
                fired.set()
                self.gcs.cancel_waiter(waiter)
                reply(snapshot())

            t = threading.Timer(timeout, on_timeout)
            t.daemon = True
            timer_box.append(t)
            t.start()

    # ------------------------------------------------------------------
    # resources
    # ------------------------------------------------------------------

    def _can_acquire(self, res: Dict[str, float], pg: Optional[bytes], bundle: int) -> bool:
        if pg is not None:
            pgs = self.pgs.get(pg)
            if pgs is None:
                return False
            if bundle >= 0:
                pool = pgs["bundles"].get(bundle)
                if pool is None:
                    return False  # bundle reserved on another node
                return all(pool.get(k, 0.0) >= v for k, v in res.items())
            # any-bundle: fits in some single locally-held bundle
            return any(
                all(b.get(k, 0.0) >= v for k, v in res.items())
                for b in pgs["bundles"].values()
            )
        return all(self.avail.get(k, 0.0) >= v for k, v in res.items())

    def _acquire_locked(self, res: Dict[str, float], pg: Optional[bytes], bundle: int) -> Optional[Dict[str, float]]:
        if not self._can_acquire(res, pg, bundle):
            return None
        if pg is not None:
            pgs = self.pgs[pg]
            idx = bundle
            if idx < 0:
                idx = next(
                    i
                    for i, b in sorted(pgs["bundles"].items())
                    if all(b.get(k, 0.0) >= v for k, v in res.items())
                )
            pool = pgs["bundles"][idx]
            for k, v in res.items():
                pool[k] = pool.get(k, 0.0) - v
            return {"__pg__": pg, "__bundle__": idx, **res}
        for k, v in res.items():
            self.avail[k] = self.avail.get(k, 0.0) - v
        return dict(res)

    def _release_locked(self, held: Dict[str, float]) -> None:
        if not held:
            return
        pg = held.get("__pg__")
        if pg is not None:
            pgs = self.pgs.get(pg)
            if pgs is None:
                return
            pool = pgs["bundles"][held["__bundle__"]]
            for k, v in held.items():
                if k.startswith("__"):
                    continue
                pool[k] = pool.get(k, 0.0) + v
            return
        for k, v in held.items():
            if k.startswith("__"):
                continue
            self.avail[k] = self.avail.get(k, 0.0) + v

    def _acquire_forced_locked(self, held: Dict[str, float]) -> None:
        pg = held.get("__pg__")
        if pg is not None:
            pgs = self.pgs.get(pg)
            if pgs is None:
                return
            pool = pgs["bundles"][held["__bundle__"]]
            for k, v in held.items():
                if not k.startswith("__"):
                    pool[k] = pool.get(k, 0.0) - v
            return
        for k, v in held.items():
            if not k.startswith("__"):
                self.avail[k] = self.avail.get(k, 0.0) - v

    # ------------------------------------------------------------------
    # placement groups
    # ------------------------------------------------------------------

    def create_placement_group(self, bundles: List[Dict[str, float]], strategy: str) -> bytes:
        from ray_tpu.core.ids import PlacementGroupID

        pg_id = PlacementGroupID.from_random().binary()
        if self.cluster is not None:
            # cluster mode: bundles gang-reserve ACROSS nodes via 2-phase
            # prepare/commit (raises when infeasible, nothing reserved)
            self.cluster.create_pg(pg_id, [dict(b) for b in bundles],
                                   strategy)
            return pg_id
        with self.lock:
            scratch = dict(self.avail)
            for b in bundles:
                for k, v in b.items():
                    if scratch.get(k, 0.0) < v:
                        raise ValueError(
                            f"cannot reserve bundle {b}: insufficient {k} "
                            f"(avail {scratch.get(k, 0.0)})"
                        )
                    scratch[k] -= v
            for b in bundles:
                for k, v in b.items():
                    self.avail[k] -= v
            self.pgs[pg_id] = {
                "bundles": {i: dict(b) for i, b in enumerate(bundles)},
                "totals": {i: dict(b) for i, b in enumerate(bundles)},
                "strategy": strategy,
            }
            return pg_id

    def remove_placement_group(self, pg_id: bytes) -> None:
        if self.cluster is not None:
            self.cluster.remove_pg(pg_id)
            return
        self.pg_release_local(pg_id)

    # -- cluster-facing 2-phase reservation (called by the adapter / peers)

    def pg_prepare(self, pg_id: bytes,
                   bundle_map: Dict[int, Dict[str, float]]) -> bool:
        """Phase 1: atomically reserve this node's share of a group.
        Resources leave ``avail`` now so no concurrent task or competing
        group can take them before commit."""
        with self.lock:
            if pg_id in self._pg_staged or pg_id in self.pgs:
                return False  # duplicate prepare
            need: Dict[str, float] = {}
            for b in bundle_map.values():
                for k, v in b.items():
                    need[k] = need.get(k, 0.0) + v
            if not all(self.avail.get(k, 0.0) >= v for k, v in need.items()):
                return False
            for k, v in need.items():
                self.avail[k] -= v
            self._pg_staged[pg_id] = {
                "bundles": {int(i): dict(b) for i, b in bundle_map.items()},
                "ts": time.monotonic(),
            }
        return True

    def pg_commit(self, pg_id: bytes) -> bool:
        with self.lock:
            st = self._pg_staged.pop(pg_id, None)
            if st is None:
                return False
            ent = self.pgs.setdefault(
                pg_id, {"bundles": {}, "totals": {}, "strategy": ""})
            for i, b in st["bundles"].items():
                ent["bundles"][i] = dict(b)
                ent["totals"][i] = dict(b)
        self._pump()
        return True

    def pg_abort(self, pg_id: bytes) -> None:
        with self.lock:
            st = self._pg_staged.pop(pg_id, None)
            if st is None:
                return
            for b in st["bundles"].values():
                for k, v in b.items():
                    self.avail[k] = self.avail.get(k, 0.0) + v

    def pg_release_local(self, pg_id: bytes) -> None:
        """Release every bundle of ``pg_id`` held on THIS node."""
        self.pg_abort(pg_id)  # staged-but-uncommitted share, if any
        with self.lock:
            pgs = self.pgs.pop(pg_id, None)
            if pgs is None:
                return
            for b in pgs["totals"].values():
                for k, v in b.items():
                    self.avail[k] = self.avail.get(k, 0.0) + v

    def reap_stale_pg_stages(self, max_age_s: float = 30.0) -> None:
        """Abort prepared-but-never-committed reservations (creator died
        mid-protocol) so their resources don't leak."""
        now = time.monotonic()
        with self.lock:
            stale = [pid for pid, st in self._pg_staged.items()
                     if now - st["ts"] > max_age_s]
        for pid in stale:
            self.pg_abort(pid)

    # ------------------------------------------------------------------
    # submission + dispatch
    # ------------------------------------------------------------------

    def register_fn(self, h: str, blob: bytes):
        self.gcs.register_fn(h, blob)
        if self.cluster is not None:
            self.cluster.publish_fn(h, blob)

    def submit_spec(self, spec: dict) -> List[ObjectRef]:
        # flight-recorder stamp (setdefault: retries/reconstruction and
        # forwarded specs keep the ORIGINAL submit time)
        spec.setdefault("lc_submit", time.time())
        try:
            self._m_submitted._inc_key(self._type_keys[spec["type"]])
        except Exception:
            pass
        return self._traced_submit(spec, self._submit_spec_inner)

    def _traced_submit(self, spec: dict, inner) -> List[ObjectRef]:
        """Trace the DRIVER-SIDE submit work itself (reference
        tracing_helper role; near-zero cost when disabled): the span
        brackets dependency resolution + pinning + enqueue — the
        GIL-serialized control-plane CPU the multi-client inversion
        pays — so summarize_critical_path can print it per task. A spec
        that already carries trace_ctx was stamped by the submitting
        worker; the driver-side handling becomes a CHILD span."""
        from ray_tpu.util import tracing

        if not tracing.tracing_enabled():
            return inner(spec)
        name = spec.get("name") or spec.get("method") or "task"
        parent = spec.get("trace_ctx")
        attrs = {"task_id": spec["task_id"].hex()}
        if parent:
            cm = tracing.span(f"driver.submit::{name}", attrs,
                              parent=parent)
        else:
            cm = tracing.span(f"submit::{name}", attrs)
        with cm as tp:
            if tp is not None:
                spec["trace_ctx"] = tp
            return inner(spec)

    def _submit_spec_inner(self, spec: dict) -> List[ObjectRef]:
        tid = TaskID(spec["task_id"])
        deps = ts.arg_refs(spec["args"], spec["kwargs"])
        self._pin_args(spec)
        if self.cluster is not None and self.cluster.maybe_forward_task(spec):
            # executes on a peer node; track refs locally + watch globally
            for rid in spec["return_ids"]:
                self.gcs.ensure_object(ObjectID(rid))
            return [ObjectRef(ObjectID(b), task_id=tid)
                    for b in spec["return_ids"]]
        if spec["type"] == ts.ACTOR_CREATE:
            info = ActorInfo(ActorID(spec["actor_id"]), spec)
            self.gcs.register_actor(info)
            if self.cluster is not None:
                self.cluster.publish_actor(spec["actor_id"], info.name)
        for rid in spec["return_ids"]:
            self.gcs.ensure_object(ObjectID(rid))
        if spec["type"] == ts.TASK and not spec.get("streaming"):
            self._record_lineage(spec)
        unresolved = [
            d for d in deps
            if (st := self.gcs.object_state(d)) is None or st.status == "PENDING"
        ]
        if unresolved:
            if self.cluster is not None:
                # deps may be produced on peer nodes: watch the global
                # directory so the local waiter can fire
                self.cluster.watch_many(unresolved)
            self.gcs.add_waiter(unresolved, len(unresolved), lambda: self._enqueue_ready(spec))
        else:
            self._enqueue_ready(spec)
        return [ObjectRef(ObjectID(b), task_id=tid) for b in spec["return_ids"]]

    def _submit_actor_spec(self, spec: dict) -> List[ObjectRef]:
        spec.setdefault("lc_submit", time.time())
        try:
            self._m_submitted._inc_key(self._type_keys[spec["type"]])
        except Exception:
            pass
        # same driver-side submit span as submit_spec (actor-call path)
        return self._traced_submit(spec, self._submit_actor_inner)

    def _submit_actor_inner(self, spec: dict) -> List[ObjectRef]:
        self._pin_args(spec)
        if (self.cluster is not None
                and self.gcs.get_actor(ActorID(spec["actor_id"])) is None
                and self.cluster.route_actor_call(spec)):
            # the actor lives on a peer node; refs tracked + watched there
            return [ObjectRef(ObjectID(b)) for b in spec["return_ids"]]
        for rid in spec["return_ids"]:
            self.gcs.ensure_object(ObjectID(rid))
        deps = ts.arg_refs(spec["args"], spec["kwargs"])
        unresolved = [
            d for d in deps
            if (st := self.gcs.object_state(d)) is None or st.status == "PENDING"
        ]
        if unresolved:
            if self.cluster is not None:
                self.cluster.watch_many(unresolved)
            self.gcs.add_waiter(
                unresolved, len(unresolved), lambda: self._enqueue_actor_call(spec)
            )
        else:
            self._enqueue_actor_call(spec)
        return [ObjectRef(ObjectID(b)) for b in spec["return_ids"]]

    def _enqueue_actor_call(self, spec: dict):
        info = self.gcs.get_actor(ActorID(spec["actor_id"]))
        if info is None or info.state == "DEAD":
            err = cloudpickle.dumps(ActorDiedError("actor is dead"))
            for rid in spec["return_ids"]:
                self.gcs.mark_error(ObjectID(rid), err)
            return
        spec["lc_ready"] = time.time()
        with self.lock:
            info.pending_queue.append(spec)
        self._pump()

    def _enqueue_ready(self, spec: dict):
        if spec["task_id"] in self.cancelled:
            err = cloudpickle.dumps(TaskCancelledError("task was cancelled"))
            for rid in spec["return_ids"]:
                self.gcs.mark_error(ObjectID(rid), err)
            return
        st0 = self.gcs.object_state(ObjectID(spec["return_ids"][0]))
        if st0 is not None and st0.status == ERROR:
            return  # cancelled while waiting for dependencies
        # propagate dependency errors without running the task
        err_blob = None
        for e in list(spec["args"]) + list(spec["kwargs"].values()):
            if e[0] == "r":
                st = self.gcs.object_state(ObjectID(e[1]))
                if st is not None and st.status == ERROR:
                    err_blob = st.error
                    break
        if err_blob is not None:
            for rid in spec["return_ids"]:
                self.gcs.mark_error(ObjectID(rid), err_blob)
            if spec["type"] == ts.ACTOR_CREATE:
                self._mark_actor_dead_and_flush(
                    ActorID(spec["actor_id"]), "creation args errored", err_blob
                )
            return
        spec["lc_ready"] = time.time()
        with self.lock:
            self.ready_tasks.append(spec)
        self._pump()

    def _attach_inline_args(self, spec: dict):
        def conv(e):
            if e[0] == "r":
                st = self.gcs.object_state(ObjectID(e[1]))
                if st is not None and st.inline is not None:
                    return ("ri", e[1], st.inline)
            return e

        spec["args"] = [conv(e) for e in spec["args"]]
        spec["kwargs"] = {k: conv(v) for k, v in spec["kwargs"].items()}

    def _dispatch_to(self, ws: _WorkerState, spec: dict):
        self._attach_inline_args(spec)
        try:
            self._m_dispatched._inc_key(())
        except Exception:
            pass
        with self.lock:
            ws.status = "busy"
            ws.current = spec
            ws.inflight_specs[spec["task_id"]] = spec
            ws.released = False
        self._task_start_ts[spec["task_id"]] = time.time()
        try:
            ws.send(("exec", spec))
        except (OSError, BrokenPipeError):
            self._on_worker_death(ws)

    def _pump(self):
        while True:
            dispatched = False
            with self.lock:
                if self._shutdown:
                    return
                # 1. ordinary tasks + actor creations from the ready queue
                for _ in range(len(self.ready_tasks)):
                    spec = self.ready_tasks.popleft()
                    if spec["task_id"] in self.cancelled:
                        err = cloudpickle.dumps(TaskCancelledError("task was cancelled"))
                        for rid in spec["return_ids"]:
                            self.gcs.mark_error(ObjectID(rid), err)
                        continue
                    res = spec.get("resources") or {}
                    held = self._acquire_locked(res, spec.get("pg"), spec.get("bundle_index", -1))
                    if held is None:
                        self.ready_tasks.append(spec)
                        continue
                    if spec["type"] == ts.ACTOR_CREATE:
                        # promote a prestarted idle POOL worker into the
                        # actor (reference worker_pool.h:159 prestart +
                        # dedicated-worker pop): the interpreter and
                        # jax-free imports are already warm, so actor
                        # creation skips the process cold-start entirely.
                        ws = self._claim_idle_pool_worker_locked()
                        info = self.gcs.get_actor(ActorID(spec["actor_id"]))
                        if ws is not None:
                            ws.kind = "actor"
                            ws.actor_id = spec["actor_id"]
                            if info is not None:
                                info.worker_id = ws.worker_id
                            ws.held = held
                            self._replenish_pool_locked()
                            target = (ws, spec)
                            dispatched = True
                            break
                        ws = self._spawn_worker_locked("actor")
                        ws.actor_id = spec["actor_id"]
                        if info is not None:
                            info.worker_id = ws.worker_id
                        ws.held = held
                        # worker hasn't dialed back yet; dispatch on "ready"
                        ws.pending_spec = spec
                        continue
                    ws = self._find_idle_pool_worker_locked()
                    if ws is None:
                        self._release_locked(held)
                        self.ready_tasks.append(spec)
                        continue
                    ws.held = held
                    target = (ws, spec)
                    dispatched = True
                    break
                else:
                    # 2. actor method calls (up to max_concurrency in
                    # flight per actor; >1 executes on worker threads)
                    target = None
                    for info in list(self.gcs.actors.values()):
                        if not info.pending_queue:
                            continue
                        if info.state not in ("ALIVE",):
                            continue
                        if info.inflight >= max(info.max_concurrency, 1):
                            continue
                        ws = self.workers.get(info.worker_id)
                        if ws is None or ws.status in ("starting", "dead"):
                            continue
                        if ws.status == "busy" and info.max_concurrency <= 1:
                            continue
                        spec = info.pending_queue.pop(0)
                        info.inflight += 1
                        # do NOT touch ws.held: the actor's CREATION
                        # resources stay held for its lifetime; method
                        # calls acquire nothing on top
                        target = (ws, spec)
                        dispatched = True
                        break
            if not dispatched:
                return
            self._dispatch_to(*target)

    def _claim_idle_pool_worker_locked(self) -> Optional[_WorkerState]:
        """Scan-only variant (no spawn side effects) for actor promotion.
        _find_idle_pool_worker_locked delegates here so task dispatch and
        actor promotion share ONE definition of 'idle'."""
        for w in self.workers.values():
            if w.kind == "pool" and w.status == "idle":
                return w
        return None

    def _replenish_pool_locked(self) -> None:
        """Keep the warm-pool baseline after an actor promotion consumed a
        prestarted worker, so the NEXT actor creation is warm too."""
        n_warm = sum(
            1 for w in self.workers.values()
            if w.kind == "pool" and w.status in ("starting", "idle")
        ) + self._spawning
        n_pool = sum(
            1 for w in self.workers.values()
            if w.kind == "pool" and w.status != "dead"
        ) + self._spawning
        if n_warm < self._prestart and n_pool < self.pool_cap:
            self._spawning += 1
            threading.Thread(target=self._spawn_pool_async,
                             daemon=True).start()

    def _find_idle_pool_worker_locked(self) -> Optional[_WorkerState]:
        w = self._claim_idle_pool_worker_locked()
        if w is not None:
            return w
        n_pool = (
            sum(1 for w in self.workers.values() if w.kind == "pool" and w.status != "dead")
            + self._spawning
        )
        n_starting = (
            sum(1 for w in self.workers.values() if w.kind == "pool" and w.status == "starting")
            + self._spawning
        )
        # Spawn enough workers to drain the ready backlog (bounded by caps).
        want = len(self.ready_tasks) + 1 - n_starting
        want = min(want, self.pool_cap - n_pool, self.pool_hard_cap - n_pool)
        for _ in range(max(0, want)):
            self._spawning += 1
            threading.Thread(target=self._spawn_pool_async, daemon=True).start()
        return None

    def _spawn_pool_async(self):
        try:
            self._spawn_worker("pool")
        finally:
            with self.lock:
                self._spawning -= 1

    def _spawn_worker_locked(self, kind: str) -> _WorkerState:
        # like _spawn_worker but callable with self.lock held (RLock)
        return self._spawn_worker(kind)

    # ------------------------------------------------------------------
    # public API surface (driver)
    # ------------------------------------------------------------------

    def _note_obj_meta(self, oid_b: bytes, owner: str,
                       site: Optional[str] = None) -> None:
        """Record creation metadata for `ray_tpu memory` forensics:
        owner process, birth time, and (when the profiler is armed) the
        creating call-site. Bounded FIFO; pure dict work."""
        meta = self._obj_meta
        meta[oid_b] = {"owner": owner, "ts": time.time(), "site": site}
        while len(meta) > self._obj_meta_cap:
            meta.popitem(last=False)

    def put(self, value: Any) -> ObjectRef:
        oid = ObjectID.from_random()
        from ray_tpu.core.object_ref import collect_serialized_refs

        with collect_serialized_refs() as nested:
            inline, size = self.store.put(oid, value)
        from ray_tpu.util import profiling as _prof

        self._note_obj_meta(
            oid.binary(), "driver",
            _prof.caller_site() if _prof.profiling_enabled() else None)
        # ref BEFORE publishing ready: the pin cast precedes obj_ready on
        # the same connection, so the directory never sees this entry
        # terminal-and-unpinned
        ref = ObjectRef(oid)
        if nested:
            # nested refs live as long as the outer object (the caller may
            # drop its own ObjectRefs right after this put)
            self._pin_result_refs(oid.binary(), nested)
        self.gcs.mark_ready(oid, inline=inline,
                            size=0 if inline is not None else size)
        return ref

    def put_parts(self, data: bytes, buffers) -> ObjectRef:
        oid = ObjectID.from_random()
        inline, size = self.store.put_parts(oid, data, buffers)
        ref = ObjectRef(oid)
        self.gcs.mark_ready(oid, inline=inline,
                            size=0 if inline is not None else size)
        return ref

    def _cluster_watch(self, ids: List[ObjectID]) -> None:
        """Cluster mode: objects not terminal locally may be produced on a
        peer node — watch the global directory so local waiters can fire."""
        if self.cluster is None:
            return
        pending = [
            o for o in ids
            if (st := self.gcs.object_state(o)) is None or st.status == "PENDING"
        ]
        if pending:
            self.cluster.watch_many(pending)

    def get(self, refs: List[ObjectRef], timeout: Optional[float] = None):
        ids = [r.id for r in refs]
        self._cluster_watch(ids)
        ready, rest = self.gcs.wait_objects(ids, len(ids), timeout)
        if rest:
            raise GetTimeoutError(f"get timed out after {timeout}s; {len(rest)} pending")
        out = []
        for oid in ids:
            st = self.gcs.object_state(oid)
            if st.status == ERROR:
                raise cloudpickle.loads(st.error)
            if st.inline is not None:
                out.append(serialization.loads_oob(st.inline))
            else:
                out.append(self._get_with_recovery(oid))
        return out

    def wait(self, refs, num_returns=1, timeout=None, fetch_local=True):
        ids = [r.id for r in refs]
        self._cluster_watch(ids)
        ready, rest = self.gcs.wait_objects(ids, num_returns, timeout)
        ready_set = set(ready)
        return (
            [r for r in refs if r.id in ready_set],
            [r for r in refs if r.id not in ready_set],
        )

    def submit(self, spec: dict) -> List[ObjectRef]:
        return self.submit_spec(spec)

    def create_actor(self, spec: dict):
        self.submit_spec(spec)

    def submit_actor_task(self, spec: dict) -> List[ObjectRef]:
        return self._submit_actor_spec(spec)

    def ensure_fn(self, h: str, blob: bytes):
        self.register_fn(h, blob)

    def kill_actor(self, actor_id: bytes, no_restart: bool = True):
        info = self.gcs.get_actor(ActorID(actor_id))
        if info is None:
            if self.cluster is not None:
                self.cluster.kill_remote_actor(actor_id, no_restart)
            return
        with self.lock:
            if no_restart:
                info.max_restarts = info.restarts  # exhaust restarts
            ws = self.workers.get(info.worker_id)
        if ws is not None and ws.status != "dead":
            try:
                ws.proc.terminate()
            except Exception:
                pass

    def cancel(self, ref: ObjectRef, force: bool = False):
        self.cancel_task(ref.id, force)

    def cancel_task(self, obj_id: ObjectID, force: bool = False):
        with self.lock:
            for spec in list(self.ready_tasks):
                if obj_id.binary() in spec["return_ids"]:
                    self.cancelled.add(spec["task_id"])
                    return
            # running: deliver cancellation into the worker (reference
            # execute_task_with_cancellation_handler, _raylet.pyx:2084) —
            # the worker raises TaskCancelledError in the task thread and
            # the normal done(error) path resolves the refs
            for ws in self.workers.values():
                for tid, spec in ws.inflight_specs.items():
                    if obj_id.binary() in spec["return_ids"]:
                        spec["retries_left"] = 0  # a cancelled task never retries
                        self.cancelled.add(tid)
                        if force:
                            try:
                                ws.proc.kill()
                            except Exception:
                                pass
                        else:
                            try:
                                ws.send(("cancel", tid))
                            except (OSError, BrokenPipeError):
                                pass
                        return
        # cluster mode: the task may be executing on a peer node (forwarded
        # task / routed actor call) — deliver the cancel THERE, where the
        # running worker lives (ADVICE r2: the fallback below would mark
        # the object cancelled while the remote task kept running)
        if (self.cluster is not None
                and self.cluster.cancel_remote(obj_id.binary(), force)):
            return
        err = cloudpickle.dumps(TaskCancelledError("task was cancelled"))
        st = self.gcs.object_state(obj_id)
        if st is not None and st.status == "PENDING":
            self.gcs.mark_error(obj_id, err)

    @property
    def cluster_node_id(self):
        """This node's cluster id (owner tag on streaming generators)."""
        return self.node_id.binary() if self.cluster is not None else None

    def stream_consumed(self, task_id: bytes, n: int, owner=None) -> None:
        fire = []
        advanced = False
        with self._stream_cv:
            if n > self._stream_consumed.get(task_id, 0):
                self._stream_consumed[task_id] = n
                advanced = True
            # bound the counter dict (late acks re-create entries) —
            # never evicting a stream with a parked producer
            if len(self._stream_consumed) > 10000:
                live = {tid for tid, _, _ in self._stream_waiters}
                for tid in list(self._stream_consumed):
                    if len(self._stream_consumed) <= 10000:
                        break
                    if tid not in live:
                        del self._stream_consumed[tid]
            kept = []
            for tid, need, rep in self._stream_waiters:
                if self._stream_consumed.get(tid, 0) >= need:
                    fire.append(rep)
                else:
                    kept.append((tid, need, rep))
            self._stream_waiters = kept
        if advanced and self.cluster is not None:
            # producer may be parked on a PEER node (forwarded/actor-routed
            # stream): relay the absolute count there, non-blocking. Only
            # on ADVANCE — an unconditional relay + a stale reciprocal
            # route pair would ping-pong the same ack forever.
            self.cluster.relay_stream_consumed(task_id, n, owner)
        for rep in fire:
            rep(True)

    def actor_queue_depths(self, actor_ids: List[bytes]) -> List[int]:
        """Queued + in-flight calls per actor — the TRUE load signal the
        serve router uses (reference keeps a replica-reported cache,
        replica_scheduler/common.py:218; here the scheduler's own view is
        authoritative and shared by every handle)."""
        out = []
        with self.lock:
            for b in actor_ids:
                info = self.gcs.get_actor(ActorID(b))
                out.append(0 if info is None
                           else len(info.pending_queue) + info.inflight)
        return out

    def lookup_named_actor(self, name: str):
        aid = self.gcs.lookup_named(name)
        if aid is None and self.cluster is not None:
            return self.cluster.lookup_named(name)
        return aid.binary() if aid else None

    def kv_op(self, op: str, *args):
        if self.cluster is not None:
            # cluster KV must be globally consistent across nodes
            return self.cluster.kv_op(op, *args)
        fn = {
            "put": self.gcs.kv_put,
            "get": self.gcs.kv_get,
            "del": self.gcs.kv_del,
            "keys": self.gcs.kv_keys,
        }[op]
        return fn(*args)

    def resources(self, which: str) -> Dict[str, float]:
        with self.lock:
            return dict(self.avail if which == "avail" else self.total)

    def free(self, ids: List[bytes]):
        for b in ids:
            oid = ObjectID(b)
            self.gcs.drop_object(oid)
            self._obj_meta.pop(b, None)
            self.store.delete(oid)
            if self.cluster is not None:
                self.cluster.gcs.cast("obj_drop", b)

    def node_info(self):
        if self.cluster is not None:
            nodes = self.cluster.node_info()
            if nodes:
                return nodes
        from ray_tpu.util.host_stats import host_stats

        return [
            {
                "NodeID": self.node_id.hex(),
                "Alive": True,
                "Resources": dict(self.total),
                "alive": True,
                "stats": host_stats(),  # reporter-module role
            }
        ]

    def timeline(self):
        return list(self.timeline_events)

    def collect_trace_spans(self) -> None:
        """Drain this PROCESS's span ring into the runtime's TraceStore
        with origin labels — called at query time (state.list_spans) and
        before each heartbeat ships trace deltas, so driver/daemon spans
        join their workers' pushed batches."""
        from ray_tpu.util import tracing

        batch = tracing.drain_ring()
        if not batch:
            return
        comp = "driver"
        if self.cluster is not None and not self.cluster.is_scheduler:
            comp = "raylet"
        self.trace_store.ingest(
            batch, {"node_id": self.node_id.hex()[:8], "component": comp})

    def collect_lifecycle_events(self) -> None:
        """Drain this PROCESS's event ring into the runtime's EventStore
        with origin labels — called at query time (state.list_events)
        and before each heartbeat ships event deltas, so driver/daemon
        events join their workers' pushed batches."""
        from ray_tpu.util import events

        batch = events.drain_ring()
        if not batch:
            return
        comp = "driver"
        if self.cluster is not None and not self.cluster.is_scheduler:
            comp = "raylet"
        self.event_store.ingest(
            batch, {"node_id": self.node_id.hex()[:8], "component": comp})

    def fetch_local_logs(self, target: dict,
                         tail_bytes: Optional[int] = None) -> List[dict]:
        """Resolve a log-fetch target against THIS node's session logs
        (the daemon half of the log-federation rendezvous; also the
        single-node fast path). ``target``: ``{"worker_id": <hex>}`` for
        one worker's log, or ``{"node": True}`` for every log file of
        this node's session (daemon + workers, bounded). Live workers
        whose log file was deleted under them are read through
        ``/proc/<pid>/fd`` (the known failure mode on this box). Returns
        [] when the target resolves to nothing here — the head keeps
        only non-empty replies."""
        from ray_tpu import config
        from ray_tpu.util import events as _events

        if tail_bytes is None:
            tail_bytes = int(config.get("log_tail_bytes"))
        want_node = (target.get("node_id") or "").lower()
        if want_node and not self.node_id.hex().startswith(want_node[:8]):
            return []  # a node-scoped fetch for some other node
        logs_dir = os.path.join(self.session_dir, "logs")
        want_wid = (target.get("worker_id") or "").lower()
        rows: List[tuple] = []
        if want_wid:
            w8 = want_wid[:8]
            with self.lock:
                ws = next((w for w in self.workers.values()
                           if w.worker_id.hex().startswith(w8)), None)
            path = (ws.log_path if ws is not None and ws.log_path
                    else os.path.join(logs_dir, f"worker-{w8}.log"))
            pid = getattr(ws.proc, "pid", None) if ws is not None else None
            if ws is not None or os.path.exists(path):
                rows.append((f"worker:{w8}", path, pid))
        elif target.get("node"):
            try:
                for name in sorted(os.listdir(logs_dir))[:32]:
                    if name.endswith(".log"):
                        rows.append((name, os.path.join(logs_dir, name),
                                     None))
            except OSError:
                pass
        out: List[dict] = []
        for label, path, pid in rows:
            tail = _events._read_log_tail(path, pid, int(tail_bytes))
            out.append({
                "label": label,
                "path": path,
                "node_id": self.node_id.hex()[:8],
                "bytes": len(tail),
                "tail": tail,
                "error_lines": _events.extract_error_lines(tail),
            })
        if out:
            try:
                from ray_tpu.util import metric_defs as _md

                _md.get("rtpu_log_fetches_total")._inc_key((), len(out))
                _md.get("rtpu_log_fetch_bytes_total")._inc_key(
                    (), sum(r["bytes"] for r in out))
            except Exception:
                pass
        return out

    def collect_profile_batches(self) -> None:
        """Drain this PROCESS's sampler window into the runtime's
        ProfileStore with origin labels — called at query time
        (state.profile) and before each heartbeat ships profile deltas,
        so driver/daemon samples join their workers' pushed batches."""
        from ray_tpu.util import profiling

        batches = profiling.drain_batches()
        if not batches:
            return
        comp = "driver"
        if self.cluster is not None and not self.cluster.is_scheduler:
            comp = "raylet"
        self.profile_store.ingest(
            batches,
            {"node_id": self.node_id.hex()[:8], "component": comp})

    def dump_stacks(self, timeout: float = 2.0) -> Dict[str, dict]:
        """Live python stacks of this process AND every live worker
        (`ray_tpu stack` py-spy role): push a ``stackdump`` to each
        worker, wait for the ``stacks`` reply casts, and merge with this
        process's own ``sys._current_frames()`` walk. Workers that miss
        the deadline are reported as pending."""
        from ray_tpu.util import profiling

        asked = []
        t_req = time.monotonic()
        with self.lock:
            workers = list(self.workers.values())
        for ws in workers:
            if ws.status == "dead" or ws.conn is None:
                continue
            try:
                ws.send(("stackdump",))
                asked.append(ws.worker_id.binary())
            except Exception:
                pass
        comp = "driver"
        if self.cluster is not None and not self.cluster.is_scheduler:
            comp = "raylet"
        out = {f"{comp}/{os.getpid()}": profiling.current_stacks()}
        deadline = time.monotonic() + timeout
        pending = set(asked)
        while pending and time.monotonic() < deadline:
            for wid in list(pending):
                rep = self._stack_replies.get(wid)
                if rep is not None and rep["ts"] >= t_req:
                    pending.discard(wid)
            if pending:
                profiling.idle_sleep(0.02)
        for wid in asked:
            rep = self._stack_replies.get(wid)
            label = f"worker:{wid.hex()[:8]}"
            if rep is not None and rep["ts"] >= t_req:
                out[label] = rep["stacks"]
            else:
                out[label] = {"<pending>": "no reply within timeout"}
        return out

    def shutdown(self):
        from ray_tpu.core import object_ref as _object_ref

        try:
            from ray_tpu.util.metrics import federation, unregister_collector

            federation.clear()  # drop this runtime's worker-origin samples
            if self._metrics_collector is not None:
                unregister_collector(self._metrics_collector)
        except Exception:
            pass
        try:
            from ray_tpu.util import alerts as _alerts

            _alerts.stop_watchdog()
        except Exception:
            pass
        _object_ref.clear_ref_hook()
        self.gcs.on_terminal = None
        self._log_monitor_stop.set()
        if self.cluster is not None:
            try:
                self.cluster.close()
            except Exception:
                pass
            self.cluster = None
        if self._memory_monitor is not None:
            self._memory_monitor.stop()
        with self.lock:
            self._shutdown = True
            workers = list(self.workers.values())
        for ws in workers:
            try:
                ws.send(("shutdown",))
            except Exception:
                pass
        deadline = time.monotonic() + 2.0
        for ws in workers:
            t = max(0.05, deadline - time.monotonic())
            try:
                ws.proc.wait(t)
            except Exception:
                ws.proc.terminate()
        for ws in workers:
            if ws.proc.poll() is None:
                try:
                    ws.proc.wait(0.5)
                except Exception:
                    ws.proc.kill()
        for ws in workers:
            # reclaim the native engines' threads (never from their own
            # drain thread — this is the driver's shutdown caller)
            if ws.npipe is not None:
                try:
                    ws.npipe.close()
                except Exception:
                    pass
        with self._zygote_lock:
            if self._zygote_obj is not None:
                self._zygote_obj.close()
                self._zygote_obj = None
        try:
            self._listener.close()
        except Exception:
            pass
        try:
            os.unlink(self._sock_addr)
        except OSError:
            pass
        StoreClient.cleanup_session(self.session)
        # compiled-DAG channels of this session (rings a leaked/undeleted
        # CompiledDAG left behind — e.g. a handle cache never torn down)
        import glob as _glob

        for p in _glob.glob(f"/dev/shm/rtpu-chan-{self.session}-*"):
            try:
                os.unlink(p)
            except OSError:
                pass


# ----------------------------------------------------------------------
# module-level public API
# ----------------------------------------------------------------------


def init(
    address: Optional[str] = None,
    *,
    num_cpus: Optional[int] = None,
    num_tpus: Optional[int] = None,
    resources: Optional[Dict[str, float]] = None,
    namespace: str = "default",
    ignore_reinit_error: bool = False,
    runtime_env: Optional[dict] = None,
    log_to_driver: bool = True,
    labels: Optional[Dict[str, str]] = None,
    **kwargs,
):
    """Start the runtime in this process (reference: ``ray.init``,
    ``python/ray/_private/worker.py:1214``).

    ``address="host:port"`` joins an existing cluster's GCS: this process
    becomes the head/scheduler node (tasks run locally when resources
    allow, spill to peer node daemons otherwise; see
    :mod:`ray_tpu.cluster`). The cluster authkey comes from ``**kwargs``
    (``cluster_authkey=...``) or ``RTPU_CLUSTER_AUTHKEY``.
    """
    global _runtime
    with _runtime_lock:
        if _runtime is not None:
            if ignore_reinit_error:
                return _runtime
            raise RuntimeError("ray_tpu.init() already called (use ignore_reinit_error=True)")
        worker_env = {}
        if runtime_env and "env_vars" in runtime_env:
            worker_env.update(runtime_env["env_vars"])
        rt = DriverRuntime(
            num_cpus=num_cpus,
            num_tpus=num_tpus,
            resources=resources,
            namespace=namespace,
            worker_env=worker_env,
            log_to_driver=log_to_driver,
            labels=labels,
        )
        if address and address not in ("auto", "local"):
            from ray_tpu.cluster.adapter import ClusterAdapter

            authkey = kwargs.get("cluster_authkey") or os.environ.get(
                "RTPU_CLUSTER_AUTHKEY", "")
            if not authkey:
                raise ValueError(
                    "joining a cluster requires cluster_authkey=... or "
                    "RTPU_CLUSTER_AUTHKEY")
            adapter = ClusterAdapter(address, authkey.encode(),
                                     is_scheduler=True)
            adapter.attach(rt)
        _runtime = rt
        atexit.register(_atexit_shutdown)
        try:
            from ray_tpu.usage_stats import write_usage_report

            write_usage_report(rt)
        except Exception:
            pass
        return rt


def _atexit_shutdown():
    global _runtime
    rt = _runtime
    if rt is not None and rt.is_driver:
        try:
            rt.shutdown()
        except Exception:
            pass
        _runtime = None


def shutdown():
    global _runtime
    with _runtime_lock:
        rt = _runtime
        if rt is None:
            return
        if rt.is_driver:
            rt.shutdown()
        _runtime = None


def is_initialized() -> bool:
    return _runtime is not None


def put(value: Any) -> ObjectRef:
    return _get_runtime().put(value)


def get(refs, timeout: Optional[float] = None):
    rt = _get_runtime()
    if isinstance(refs, ObjectRef):
        return rt.get([refs], timeout)[0]
    if not isinstance(refs, list):
        raise TypeError("get() takes an ObjectRef or list of ObjectRefs")
    if not refs:
        return []
    return rt.get(refs, timeout)


def wait(refs, *, num_returns: int = 1, timeout: Optional[float] = None, fetch_local: bool = True):
    if not isinstance(refs, list):
        raise TypeError("wait() takes a list of ObjectRefs")
    if num_returns > len(refs):
        raise ValueError("num_returns > len(refs)")
    return _get_runtime().wait(refs, num_returns, timeout, fetch_local)


def kill(actor, *, no_restart: bool = True):
    from ray_tpu.core.actor import ActorHandle

    if not isinstance(actor, ActorHandle):
        raise TypeError("kill() expects an ActorHandle")
    _get_runtime().kill_actor(actor._actor_id.binary(), no_restart)


def cancel(ref: ObjectRef, *, force: bool = False, recursive: bool = True):
    _get_runtime().cancel(ref, force)


def get_actor(name: str, namespace: Optional[str] = None):
    from ray_tpu.core.actor import ActorHandle

    aid = _get_runtime().lookup_named_actor(name)
    if aid is None:
        raise ValueError(f"no actor named {name!r}")
    return ActorHandle(ActorID(aid))


def free(refs) -> None:
    """Eagerly delete objects from the store + directory (reference
    ``ray.internal.free`` role). For owners that KNOW an object is fully
    consumed — the streaming exchange drops partition blocks this way so a
    shuffle's intermediates never accumulate. Unlike dropping ObjectRefs,
    this reclaims the segment immediately; lineage reconstruction of a
    freed object is impossible, so never free values a consumer may still
    fetch."""
    if isinstance(refs, ObjectRef):
        refs = [refs]
    refs = list(refs)  # a generator must not be exhausted by validation
    if not all(isinstance(r, ObjectRef) for r in refs):
        raise TypeError("free() takes an ObjectRef or list of ObjectRefs")
    if refs:
        _get_runtime().free([r.id.binary() for r in refs])


def object_store_memory() -> Dict[str, int]:
    """Local object-store usage (public API so libraries never reach into
    store internals): {"used_bytes", "capacity_bytes", "spilled_bytes"}."""
    from ray_tpu import config

    rt = _get_runtime()
    return {"used_bytes": int(rt.store.store_bytes()),
            "capacity_bytes": int(config.get("store_capacity")),
            "spilled_bytes": int(rt.store.spill_dir_bytes())}


def available_resources() -> Dict[str, float]:
    return _get_runtime().resources("avail")


def cluster_resources() -> Dict[str, float]:
    return _get_runtime().resources("total")


def nodes():
    return _get_runtime().node_info()


def timeline(filename: Optional[str] = None):
    events = _get_runtime().timeline()
    if filename:
        import json

        with open(filename, "w") as f:
            json.dump(events, f)
    return events


def remote(*args, **options):
    """``@remote`` decorator for functions and classes (reference:
    ``python/ray/_private/worker.py:3212``)."""
    from ray_tpu.core.actor import ActorClass
    from ray_tpu.core.remote_function import RemoteFunction
    import inspect

    def make(target, opts):
        if inspect.isclass(target):
            return ActorClass(target, opts)
        return RemoteFunction(target, opts)

    if len(args) == 1 and callable(args[0]) and not options:
        return make(args[0], {})
    if args:
        raise TypeError("@remote options must be keyword arguments")

    def deco(target):
        return make(target, options)

    return deco


def method(**options):
    """``@ray.method`` analog: annotate actor methods (num_returns...)."""

    def deco(fn):
        fn._rtpu_method_options = options
        return fn

    return deco
