"""Task/actor specs and argument encoding.

Role analog: reference ``src/ray/common/task/task_spec.h`` +
``python/ray/remote_function.py`` arg handling. Specs are plain dicts so
they pickle fast over control-channel pipes.

Argument encodings:
  ("v", blob)            — inline serialized value
  ("r", id_bytes)        — ObjectRef; resolved via the store at exec time
  ("ri", id_bytes, blob) — ObjectRef whose value was inline in the directory;
                           the scheduler attaches the blob at dispatch
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

from ray_tpu.core import serialization
from ray_tpu.core.ids import ObjectID, TaskID, ActorID
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.object_store import INLINE_THRESHOLD

TASK = "task"
ACTOR_CREATE = "actor_create"
ACTOR_METHOD = "actor_method"


def fn_digest(blob: bytes) -> str:
    return hashlib.sha256(blob).hexdigest()[:24]


def streaming_return_id(task_id: bytes, index: int) -> bytes:
    """Deterministic ObjectID for the ``index``-th yield of a streaming
    task — producer and consumers derive it independently (reference:
    ``ObjectID::ForDynamicReturn`` role, ``_raylet.pyx:273``)."""
    return hashlib.sha256(task_id + b":stream:" +
                          index.to_bytes(8, "little")).digest()[:16]


def pickle_fn(fn) -> bytes:
    return cloudpickle.dumps(fn)


def encode_args(args, kwargs, runtime) -> Tuple[list, dict, list]:
    """Encode call args. Oversized inline values are promoted to store
    objects (mirrors the reference: large args are implicitly ``ray.put``).
    Each value is serialized exactly once. The third element lists refs
    NESTED inside inline values (e.g. ``f.remote([ref])``) — the submitter
    pins those until the task completes, closing the window between the
    caller dropping its ObjectRef and the worker deserializing its borrow
    (reference: borrowed references in serialized arguments)."""
    from ray_tpu.core.object_ref import collect_serialized_refs

    nested: List[bytes] = []

    def enc(a: Any):
        if isinstance(a, ObjectRef):
            return ("r", a.id.binary())
        with collect_serialized_refs() as got:
            data, buffers = serialization.serialize(a)
        nested.extend(got)
        size = serialization.serialized_size(data, buffers)
        if size >= INLINE_THRESHOLD:
            ref = runtime.put_parts(data, buffers)
            return ("r", ref.id.binary())
        out = bytearray(size)
        serialization.write_into(memoryview(out), data, buffers)
        return ("v", bytes(out))

    return ([enc(a) for a in args],
            {k: enc(v) for k, v in (kwargs or {}).items()},
            nested)


def arg_refs(enc_args: list, enc_kwargs: dict) -> List[ObjectID]:
    out = []
    for e in list(enc_args) + list(enc_kwargs.values()):
        if e[0] == "r":
            out.append(ObjectID(e[1]))
    return out


def make_task_spec(
    fn_hash: str,
    enc_args: list,
    enc_kwargs: dict,
    num_returns: int,
    resources: Dict[str, float],
    name: str = "",
    max_retries: int = 0,
    placement_group_id: Optional[bytes] = None,
    bundle_index: int = -1,
    scheduling_strategy: Any = None,
    runtime_env: Optional[dict] = None,
    retry_exceptions: Any = False,
) -> dict:
    task_id = TaskID.from_random()
    if isinstance(retry_exceptions, (list, tuple)):
        # list form (retry only these exception types): cloudpickle the
        # tuple so the spec stays plain-pickle-safe on every transport
        # (control pipe, cluster RPC) even for __main__-defined types;
        # an empty list means "never retry" and must stay falsy
        retry_exceptions = (cloudpickle.dumps(tuple(retry_exceptions))
                            if retry_exceptions else False)
    return {
        "type": TASK,
        "retry_exceptions": retry_exceptions,
        "runtime_env": runtime_env,
        "task_id": task_id.binary(),
        "fn_hash": fn_hash,
        "name": name,
        "args": enc_args,
        "kwargs": enc_kwargs,
        "return_ids": [ObjectID.from_random().binary() for _ in range(num_returns)],
        "resources": resources,
        "max_retries": max_retries,
        "pg": placement_group_id,
        "bundle_index": bundle_index,
        "retries_left": max_retries,
    }


def make_actor_create_spec(
    cls_hash: str,
    enc_args: list,
    enc_kwargs: dict,
    resources: Dict[str, float],
    actor_name: str = "",
    max_restarts: int = 0,
    max_concurrency: int = 1,
    placement_group_id: Optional[bytes] = None,
    bundle_index: int = -1,
    runtime_env: Optional[dict] = None,
) -> dict:
    actor_id = ActorID.from_random()
    return {
        "type": ACTOR_CREATE,
        "runtime_env": runtime_env,
        "task_id": TaskID.from_random().binary(),
        "actor_id": actor_id.binary(),
        "fn_hash": cls_hash,
        "name": actor_name,
        "args": enc_args,
        "kwargs": enc_kwargs,
        "return_ids": [ObjectID.from_random().binary()],
        "resources": resources,
        "max_restarts": max_restarts,
        "max_concurrency": max_concurrency,
        "pg": placement_group_id,
        "bundle_index": bundle_index,
    }


def make_task_template(
    fn_hash: str,
    num_returns: int,
    resources: Dict[str, float],
    name: str = "",
    max_retries: int = 0,
    placement_group_id: Optional[bytes] = None,
    bundle_index: int = -1,
    runtime_env: Optional[dict] = None,
    retry_exceptions: Any = False,
    streaming: bool = False,
    stream_backpressure: int = 0,
    strategy: Any = None,
) -> dict:
    """Submit fast-path (r13): the per-(function, option-set) INVARIANT
    part of a task spec, computed once and shallow-copied per call —
    repeated submissions pay only arg encoding + fresh ids. The
    ``retry_exceptions`` list form is cloudpickled here, once, instead of
    per submission."""
    if isinstance(retry_exceptions, (list, tuple)):
        retry_exceptions = (cloudpickle.dumps(tuple(retry_exceptions))
                            if retry_exceptions else False)
    tmpl = {
        "type": TASK,
        "retry_exceptions": retry_exceptions,
        "runtime_env": runtime_env,
        "fn_hash": fn_hash,
        "name": name,
        "resources": resources,
        "max_retries": max_retries,
        "pg": placement_group_id,
        "bundle_index": bundle_index,
        "_num_returns": 1 if streaming else int(num_returns),
    }
    if streaming:
        tmpl["streaming"] = True
        if stream_backpressure:
            tmpl["stream_backpressure"] = int(stream_backpressure)
    if strategy is not None:
        tmpl["strategy"] = strategy
    return tmpl


def spec_from_template(tmpl: dict, enc_args: list, enc_kwargs: dict) -> dict:
    """Instantiate one submission from a cached template: fresh ids +
    this call's encoded args on a shallow copy."""
    spec = dict(tmpl)
    n = spec.pop("_num_returns")
    spec["task_id"] = TaskID.from_random().binary()
    spec["args"] = enc_args
    spec["kwargs"] = enc_kwargs
    spec["return_ids"] = [ObjectID.from_random().binary()
                          for _ in range(n)]
    spec["retries_left"] = spec.get("max_retries", 0)
    return spec


def make_actor_method_template(
    actor_id: bytes,
    method_name: str,
    num_returns: int = 1,
    streaming: bool = False,
    stream_backpressure: int = 0,
) -> dict:
    """Actor-call twin of :func:`make_task_template`."""
    tmpl = {
        "type": ACTOR_METHOD,
        "actor_id": actor_id,
        "method": method_name,
        "resources": {},
        "_num_returns": 1 if streaming else int(num_returns),
    }
    if streaming:
        tmpl["streaming"] = True
        if stream_backpressure:
            tmpl["stream_backpressure"] = int(stream_backpressure)
    return tmpl


def make_actor_method_spec(
    actor_id: bytes,
    method_name: str,
    enc_args: list,
    enc_kwargs: dict,
    num_returns: int = 1,
) -> dict:
    return {
        "type": ACTOR_METHOD,
        "task_id": TaskID.from_random().binary(),
        "actor_id": actor_id,
        "method": method_name,
        "args": enc_args,
        "kwargs": enc_kwargs,
        "return_ids": [ObjectID.from_random().binary() for _ in range(num_returns)],
        "resources": {},
    }
