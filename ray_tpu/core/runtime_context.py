"""Runtime context: who am I, where am I running.

Role analog: reference ``python/ray/runtime_context.py``.
"""

from __future__ import annotations

from typing import Optional


class RuntimeContext:
    def __init__(self, rt):
        self._rt = rt

    @property
    def was_current_actor_reconstructed(self) -> bool:
        return False

    def get_node_id(self) -> str:
        if self._rt.is_driver:
            return self._rt.node_id.hex()
        # workers inherit their spawning node runtime's id via env (set
        # at spawn) — the disaggregated-serving transfer plane keys
        # channel-vs-store on node identity (ISSUE 13)
        import os

        return os.environ.get("RTPU_NODE_ID", "node")

    def get_job_id(self) -> str:
        return "job"

    def get_session_id(self) -> str:
        """The runtime session id (shared by the driver and its workers;
        a remote node's workers carry their daemon's session). Public
        surface: shm artifacts named ``rtpu-chan-<session>-*`` are swept
        by that session's runtime shutdown, so anything creating
        channels outside dag/ (e.g. the serve KV-transfer plane) must
        embed it."""
        return self._rt.session

    def get_worker_id(self) -> str:
        if self._rt.is_driver:
            return "driver"
        return self._rt.worker_id.hex()

    def get_task_id(self) -> Optional[str]:
        if self._rt.is_driver:
            return None
        tid = self._rt.current_task_id
        return tid.hex() if tid else None

    def get_actor_id(self) -> Optional[str]:
        if self._rt.is_driver:
            return None
        aid = self._rt.current_actor_id
        return aid.hex() if aid else None

    def get_actor_name(self) -> Optional[str]:
        return None

    def get_assigned_resources(self):
        return {}


def get_runtime_context() -> RuntimeContext:
    from ray_tpu.core.runtime import _get_runtime

    return RuntimeContext(_get_runtime())
