"""Global control state: object directory, KV store, function/actor tables.

Role analog: reference GCS server (``src/ray/gcs/gcs_server``): InternalKV
(``gcs_kv_manager.h``), function table (``gcs_function_manager.h``), actor
table (``gcs_actor_manager.h``), plus the object directory the reference
keeps per-owner (``ownership_based_object_directory.h``). Single-node
round 1: in-process state guarded by locks; the narrow method surface is the
seam where a networked control plane slots in for multi-node.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from ray_tpu.core.ids import ActorID, ObjectID

PENDING = "PENDING"
READY = "READY"
ERROR = "ERROR"


class ObjectState:
    __slots__ = ("status", "inline", "error", "size")

    def __init__(self):
        self.status = PENDING
        self.inline: Optional[bytes] = None  # blob if stored inline
        self.error: Optional[bytes] = None  # serialized TaskError
        self.size = 0


class ActorInfo:
    __slots__ = (
        "actor_id", "name", "worker_id", "state", "create_spec",
        "max_restarts", "restarts", "pending_queue",
        "death_cause", "max_concurrency", "inflight",
    )

    def __init__(self, actor_id: ActorID, create_spec: dict):
        self.actor_id = actor_id
        self.name = create_spec.get("name") or ""
        self.worker_id = None
        self.state = "PENDING"  # PENDING | ALIVE | RESTARTING | DEAD
        self.create_spec = create_spec
        self.max_restarts = create_spec.get("max_restarts", 0)
        self.restarts = 0
        self.pending_queue: List[dict] = []
        self.death_cause = ""
        self.max_concurrency = create_spec.get("max_concurrency", 1)
        self.inflight = 0


class _Waiter:
    __slots__ = ("ids", "num_needed", "callback", "fired", "include_errors")

    def __init__(self, ids, num_needed, callback):
        self.ids: Set[ObjectID] = set(ids)
        self.num_needed = num_needed
        self.callback = callback
        self.fired = False


class Gcs:
    def __init__(self):
        self.lock = threading.RLock()
        self.kv: Dict[str, Dict[str, bytes]] = {}  # namespace -> {key: val}
        self.functions: Dict[str, bytes] = {}
        self.objects: Dict[ObjectID, ObjectState] = {}
        self.actors: Dict[ActorID, ActorInfo] = {}
        self.named_actors: Dict[str, ActorID] = {}
        self._obj_waiters: Dict[ObjectID, List[_Waiter]] = {}
        self._cv = threading.Condition(self.lock)
        # terminal-event log for blocking waits: each waiter replays only
        # the events since its last wake instead of rescanning its whole
        # id set per wake (the rescan was O(n^2) — 4M hash lookups for a
        # 2000-task get). Appended only while waiters exist; compacted to
        # the minimum live cursor so one stuck waiter cannot make the log
        # grow with every completion system-wide. _term_base is the global
        # sequence number of _term_events[0].
        self._term_events: List[ObjectID] = []
        self._term_base = 0
        self._wait_cursors: Dict[int, int] = {}  # waiter token -> seq
        self._wait_token = 0
        self._wait_count = 0
        # Cluster-mode hooks (set by the cluster adapter): called AFTER an
        # object turns terminal locally so the global directory learns about
        # it. Must be non-blocking (they cast over a socket).
        self.on_object_ready: Optional[Callable[[ObjectID, Optional[bytes], int], None]] = None
        self.on_object_error: Optional[Callable[[ObjectID, bytes], None]] = None
        # Fired on EVERY terminal transition (local or delivered), the one
        # choke point all completion paths share — the runtime releases
        # task-argument reference pins here.
        self.on_terminal: Optional[Callable[[ObjectID], None]] = None

    def _compact_term_events_locked(self) -> None:
        if len(self._term_events) < 4096 or not self._wait_cursors:
            return
        low = min(self._wait_cursors.values())
        drop = low - self._term_base
        if drop > 0:
            del self._term_events[:drop]
            self._term_base = low

    # -- function table ---------------------------------------------------

    def register_fn(self, h: str, blob: bytes) -> None:
        with self.lock:
            self.functions.setdefault(h, blob)

    def get_fn(self, h: str) -> Optional[bytes]:
        with self.lock:
            return self.functions.get(h)

    # -- KV ---------------------------------------------------------------

    def kv_put(self, key: str, value: bytes, namespace: str = "default", overwrite: bool = True) -> bool:
        with self.lock:
            ns = self.kv.setdefault(namespace, {})
            if not overwrite and key in ns:
                return False
            ns[key] = value
            return True

    def kv_get(self, key: str, namespace: str = "default") -> Optional[bytes]:
        with self.lock:
            return self.kv.get(namespace, {}).get(key)

    def kv_del(self, key: str, namespace: str = "default") -> bool:
        with self.lock:
            return self.kv.get(namespace, {}).pop(key, None) is not None

    def kv_keys(self, prefix: str = "", namespace: str = "default") -> List[str]:
        with self.lock:
            return [k for k in self.kv.get(namespace, {}) if k.startswith(prefix)]

    # -- object directory -------------------------------------------------

    def ensure_object(self, obj_id: ObjectID) -> ObjectState:
        with self.lock:
            st = self.objects.get(obj_id)
            if st is None:
                st = ObjectState()
                self.objects[obj_id] = st
            return st

    def mark_ready(self, obj_id: ObjectID, inline: Optional[bytes] = None,
                   size: int = 0, _local_only: bool = False) -> None:
        with self.lock:
            st = self.ensure_object(obj_id)
            if st.status == ERROR:
                return  # terminal states never downgrade (e.g. cancellation)
            st.status = READY
            st.inline = inline
            st.size = size or (len(inline) if inline else 0)
            if self._wait_count:
                self._term_events.append(obj_id)
            self._fire_waiters(obj_id)
            self._cv.notify_all()
        if self.on_object_ready is not None and not _local_only:
            self.on_object_ready(obj_id, inline, st.size)
        if self.on_terminal is not None:
            self.on_terminal(obj_id)

    def mark_error(self, obj_id: ObjectID, err_blob: bytes,
                   _local_only: bool = False) -> None:
        with self.lock:
            st = self.ensure_object(obj_id)
            st.status = ERROR
            st.error = err_blob
            if self._wait_count:
                self._term_events.append(obj_id)
            self._fire_waiters(obj_id)
            self._cv.notify_all()
        if self.on_object_error is not None and not _local_only:
            self.on_object_error(obj_id, err_blob)
        if self.on_terminal is not None:
            self.on_terminal(obj_id)

    def object_state(self, obj_id: ObjectID) -> Optional[ObjectState]:
        with self.lock:
            return self.objects.get(obj_id)

    def drop_object(self, obj_id: ObjectID) -> None:
        with self.lock:
            self.objects.pop(obj_id, None)

    def reset_object(self, obj_id: ObjectID) -> None:
        """Back to PENDING for lineage re-execution of a lost object."""
        with self.lock:
            st = self.ensure_object(obj_id)
            st.status = PENDING
            st.inline = None
            st.error = None

    def _fire_waiters(self, obj_id: ObjectID) -> None:
        # caller holds lock
        waiters = self._obj_waiters.pop(obj_id, [])
        for w in waiters:
            if w.fired:
                continue
            w.ids.discard(obj_id)
            w.num_needed -= 1
            if w.num_needed <= 0:
                w.fired = True
                for other in w.ids:
                    lst = self._obj_waiters.get(other)
                    if lst and w in lst:
                        lst.remove(w)
                cb = w.callback
                threading.Thread(target=cb, daemon=True).start()

    def add_waiter(self, ids: List[ObjectID], num_needed: int, callback: Callable[[], None]):
        """Invoke ``callback`` (on a fresh thread) once ``num_needed`` of
        ``ids`` are terminal (READY or ERROR). Fires immediately if already
        satisfied. Returns the waiter (or None if fired) so callers with a
        timeout can ``cancel_waiter`` it."""
        with self.lock:
            pending = []
            done = 0
            for i in ids:
                st = self.objects.get(i)
                if st is not None and st.status in (READY, ERROR):
                    done += 1
                else:
                    self.ensure_object(i)
                    pending.append(i)
            if done >= num_needed:
                threading.Thread(target=callback, daemon=True).start()
                return None
            w = _Waiter(pending, num_needed - done, callback)
            for i in pending:
                self._obj_waiters.setdefault(i, []).append(w)
            return w

    def cancel_waiter(self, w) -> None:
        if w is None:
            return
        with self.lock:
            if w.fired:
                return
            w.fired = True
            for i in w.ids:
                lst = self._obj_waiters.get(i)
                if lst and w in lst:
                    lst.remove(w)
                    if not lst:
                        del self._obj_waiters[i]

    def wait_objects(
        self, ids: List[ObjectID], num_returns: int, timeout: Optional[float]
    ) -> Tuple[List[ObjectID], List[ObjectID]]:
        """Blocking wait (driver-side fast path).

        One full scan up front, then each wake replays only the terminal
        events logged since the previous wake — total work O(ids +
        completions), not O(ids x wakes). ``ready`` preserves the caller's
        id order for the initial scan and completion order after (matches
        the reference's wait semantics)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            ready = []
            pending: Dict[ObjectID, int] = {}  # id -> multiplicity
            for i in ids:
                st = self.objects.get(i)
                if st is not None and st.status in (READY, ERROR):
                    ready.append(i)
                else:
                    pending[i] = pending.get(i, 0) + 1
            self._wait_count += 1
            self._wait_token += 1
            token = self._wait_token
            cursor = self._term_base + len(self._term_events)
            self._wait_cursors[token] = cursor
            try:
                while True:
                    if len(ready) >= num_returns:
                        ready = ready[:num_returns]
                        rs = set(ready)
                        return ready, [i for i in ids if i not in rs]
                    if deadline is not None:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            rs = set(ready)
                            return ready, [i for i in ids if i not in rs]
                        self._cv.wait(remaining)
                    else:
                        self._cv.wait(5.0)
                    evs = self._term_events
                    end = self._term_base + len(evs)
                    for k in range(cursor - self._term_base,
                                   len(evs)):
                        oid = evs[k]
                        n = pending.pop(oid, 0)
                        if n:
                            ready.extend([oid] * n)
                    cursor = end
                    self._wait_cursors[token] = cursor
                    self._compact_term_events_locked()
            finally:
                self._wait_count -= 1
                self._wait_cursors.pop(token, None)
                if self._wait_count == 0:
                    self._term_events.clear()
                    self._term_base = 0

    # -- actor table ------------------------------------------------------

    def register_actor(self, info: ActorInfo) -> None:
        with self.lock:
            self.actors[info.actor_id] = info
            if info.name:
                if info.name in self.named_actors:
                    raise ValueError(f"actor name {info.name!r} already taken")
                self.named_actors[info.name] = info.actor_id

    def get_actor(self, actor_id: ActorID) -> Optional[ActorInfo]:
        with self.lock:
            return self.actors.get(actor_id)

    def lookup_named(self, name: str) -> Optional[ActorID]:
        with self.lock:
            return self.named_actors.get(name)

    # -- state-API accessors (reference GcsTaskManager/table dumps) -------

    def all_actors(self) -> List["ActorInfo"]:
        with self.lock:
            return list(self.actors.values())

    def all_objects(self):
        with self.lock:
            return list(self.objects.items())

    def mark_actor_dead(self, actor_id: ActorID, cause: str) -> None:
        with self.lock:
            info = self.actors.get(actor_id)
            if info:
                info.state = "DEAD"
                info.death_cause = cause
                if info.name:
                    self.named_actors.pop(info.name, None)
