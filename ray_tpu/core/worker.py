"""Worker process: task execution loop + worker-side runtime client.

Role analog: reference worker main loop (``python/ray/_private/workers/
default_worker.py`` + ``_raylet.pyx:2251 task_execution_handler``). One
worker executes one task at a time; while executing, nested API calls
(``get``/``put``/``remote``/actor calls) flow over the same control pipe to
the driver as request/reply or one-way casts.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

import cloudpickle

from ray_tpu.core import serialization, task_spec as ts
from ray_tpu.core.exceptions import (
    ActorDiedError,
    GetTimeoutError,
    TaskError,
)
from ray_tpu.core.ids import ActorID, ObjectID, TaskID, WorkerID
from ray_tpu.core.object_ref import ObjectRef, collect_serialized_refs
from ray_tpu.core.object_store import INLINE_THRESHOLD, StoreClient

# sentinel for request() timeouts (None is a legitimate reply payload)
_TIMEOUT = object()


class WorkerRuntime:
    """Runtime interface bound inside a worker process (see runtime.py for
    the driver-side twin; both expose the same narrow surface)."""

    is_driver = False

    def __init__(self, conn, session: str, worker_id: bytes):
        import queue

        self.conn = conn
        self.session = session
        self.worker_id = WorkerID(worker_id)
        self.store = StoreClient(session)
        self.fn_cache: Dict[str, Any] = {}
        self.registered_fns: set = set()
        self.actors: Dict[bytes, Any] = {}
        self.actor_concurrency: Dict[bytes, int] = {}
        self._actor_pools: Dict[bytes, Any] = {}  # ThreadPoolExecutor
        # async actors: one persistent event loop per actor — concurrent
        # calls are coroutines on THAT loop, interleaving at awaits
        # (reference fiber semantics, src/ray/core_worker/fiber.h)
        self._actor_loops: Dict[bytes, Any] = {}
        # cooperative cancel: task_id -> thread ident / asyncio future
        self._running_threads: Dict[bytes, int] = {}
        self._running_futs: Dict[bytes, Any] = {}
        self._running_lock = threading.Lock()
        # chunked-pull alignment hints (oid -> (stride, payload_bytes)):
        # the pull runs in the HOSTING runtime (driver/daemon), so get()
        # forwards these on the wire — a worker-local registry would
        # never be seen by the process that actually fetches (ISSUE 13)
        self._pull_aligns: Dict[bytes, tuple] = {}
        self._req_counter = itertools.count()
        self._send_lock = threading.Lock()
        # Control-message coalescing (r13, ROADMAP item 1): fire-and-forget
        # casts buffer here and ship as ONE framed batch — flushed by a
        # Nagle-style window thread (RTPU_PIPE_COALESCE_US) or piggybacked
        # onto the next latency-sensitive send (done/req/ready), whichever
        # comes first. This is what turns the multi-client shape's ~5 pipe
        # messages/task (submit cast + refpin transitions + get machinery)
        # into ~2 frames/task of driver-side receive work.
        from collections import deque as _cast_deque

        self._cast_q: "_cast_deque" = _cast_deque()
        # packed refpin transitions awaiting the same Nagle flush (the
        # r13 pickled path buffered them inside _cast_q; the r14 packed
        # path must keep that cadence or every 0<->1 transition pays its
        # own frame + syscall)
        self._refpin_buf: list = []
        self._cast_q_lock = threading.Lock()
        self._flush_ev = threading.Event()
        self._flusher_started = False
        self._coalesce_s: Optional[float] = None
        # serializes the rate-limited telemetry pushes: they run from the
        # main loop AND from compiled-DAG exec loops (see push_telemetry)
        self._push_lock = threading.Lock()
        # Borrowed-reference tracking (reference reference_count.h:61
        # "borrower" role): live ObjectRef instances in THIS worker pin the
        # object at the driver (which aggregates into node-level pins at
        # the cluster directory). Only 0<->1 transitions cross the pipe.
        self._refs_lock = threading.Lock()
        self._ref_counts: Dict[bytes, int] = {}
        # GC-safety (advisor r3): the __del__ hook may fire at any
        # allocation point, including on a thread already holding
        # _refs_lock or _send_lock — it must take no locks and do no IO.
        # It only appends (deque.append is atomic); normal code paths
        # drain. Pin casts are queued under _refs_lock (order-preserving)
        # and shipped outside it. Shared machinery: core/refqueue.py.
        from ray_tpu.core.refqueue import DeferredDrops, OrderedCastFlusher

        # batch mode: one "refpins" cast per drain instead of one pipe
        # message per 0<->1 transition (r13 control-message coalescing).
        # With the native driver engine (r14) the batch ships as a PACKED
        # binary frame the driver's C++ receiver applies off the GIL.
        self._ref_casts = OrderedCastFlusher(self._ship_refpins, batch=True)
        self._refpin_packed: Optional[bool] = None
        # store pins to drop once outside _refs_lock (see
        # _apply_ref_drop_locked); deque: append/popleft are atomic
        from collections import deque as _deque

        self._pending_pin_releases: "_deque" = _deque()
        self._deferred_ref_drops = DeferredDrops(
            self._refs_lock, self._apply_ref_drop_locked,
            self._after_ref_drops)
        from ray_tpu.core import object_ref as _object_ref

        _object_ref.set_ref_hook(self._ref_added,
                                 self._deferred_ref_drops.append)
        # Demuxed transport: exactly ONE thread reads the pipe and routes
        # replies to the issuing thread. This lets ANY thread in the worker
        # (the task thread, a train-session thread, a user thread) make
        # runtime calls (get/put/remote) without racing the main loop for
        # messages.
        self._exec_queue: "queue.Queue" = queue.Queue()
        self._reply_lock = threading.Lock()
        self._replies: Dict[int, Any] = {}
        self._reply_events: Dict[int, threading.Event] = {}
        self._recv_started = False
        # context of the currently running task — thread-local because
        # concurrent actors (max_concurrency > 1) execute methods on pool
        # threads and must not see each other's ids
        self._task_ctx = threading.local()
        # metrics federation (sender side): this process's registry —
        # built-ins below plus any user metrics tasks create — is pushed
        # to the driver as batched DELTAS over the existing pipe, never
        # per-call; see _maybe_push_metrics
        self._metrics_exporter = None
        self._metrics_last_push = 0.0
        self._metrics_interval: Optional[float] = None
        self._wmetrics = None
        # trace plane (sender side): finished spans accumulate in this
        # process's bounded ring and ride the pipe as batched casts,
        # rate-limited like the metric delta push
        self._trace_last_push = 0.0
        self._trace_interval: Optional[float] = None
        # profiling plane (sender side): the sampler's aggregated window
        # rides the pipe as batched casts on the same cadence pattern
        self._profile_last_push = 0.0
        self._profile_interval: Optional[float] = None
        # event plane (sender side): lifecycle events ride the pipe as
        # batched casts on the same cadence pattern (events are rare —
        # the interval only bounds the batching delay)
        self._event_last_push = 0.0
        self._event_interval: Optional[float] = None
        # device plane (sender side): compiled-program registry snapshots
        # ride the pipe as casts, version-gated — nothing ships unless a
        # compile/retrace bumped the registry since the last push
        self._device_last_push = 0.0
        self._device_interval: Optional[float] = None
        self._device_version_shipped = 0
        try:
            from ray_tpu import config as _cfg

            self._flight_enabled = bool(_cfg.get("flight_recorder"))
        except Exception:
            self._flight_enabled = True

    @property
    def labels(self) -> Dict[str, str]:
        """This node's labels (propagated by the spawner via env)."""
        from ray_tpu.util.labels import parse_labels

        return parse_labels(os.environ.get("RTPU_NODE_LABELS", ""))

    @property
    def current_task_id(self) -> Optional[TaskID]:
        return getattr(self._task_ctx, "task_id", None)

    @current_task_id.setter
    def current_task_id(self, value: Optional[TaskID]) -> None:
        self._task_ctx.task_id = value

    @property
    def current_actor_id(self) -> Optional[ActorID]:
        return getattr(self._task_ctx, "actor_id", None)

    @current_actor_id.setter
    def current_actor_id(self, value: Optional[ActorID]) -> None:
        self._task_ctx.actor_id = value

    # -- transport --------------------------------------------------------

    def _dropped(self, msg) -> bool:
        """THE chaos filter for worker->driver messages — every egress
        path (deferred cast, piggyback, urgent) funnels each message
        through this single ``worker.pipe.send`` site."""
        from ray_tpu.util import failpoints

        return failpoints.hit("worker.pipe.send", msg[0])

    def _coalesce_window(self) -> float:
        if self._coalesce_s is None:
            try:
                from ray_tpu import config as _cfg

                self._coalesce_s = max(
                    0.0, int(_cfg.get("pipe_coalesce_us")) / 1e6)
            except Exception:
                self._coalesce_s = 0.0
        return self._coalesce_s

    def _send_frame(self, msg=None) -> None:
        """Ship pending casts (+ optionally ``msg``) as ONE frame.
        Drain happens under the send lock, so frame order matches global
        issue order — a cast enqueued before a done/req can never be
        observed after it. Buffered packed refpins go out FIRST (a +1
        borrow must reach the driver before the done that releases the
        matching arg pin), in their own binary frame."""
        import struct as _struct

        with self._send_lock:
            with self._cast_q_lock:
                if self._cast_q:
                    batch = list(self._cast_q)
                    self._cast_q.clear()
                else:
                    batch = []
                pins = self._refpin_buf
                if pins:
                    self._refpin_buf = []
            if pins:
                self.conn.send_bytes(b"RTP1" + b"".join(
                    _struct.pack("<16sb", oid_b, d) for oid_b, d in pins))
            if msg is not None:
                batch.append(msg)
            if not batch:
                return
            self.conn.send(batch[0] if len(batch) == 1
                           else ("batch", batch))

    def _send(self, msg):
        """Latency-sensitive send (done/req/ready/reply): goes out NOW,
        piggybacking any buffered casts in the same frame."""
        if self._dropped(msg):
            return  # chaos: drop this worker->driver control message
        self._send_frame(msg)

    def cast(self, op: str, *args):
        """Fire-and-forget cast: buffered for the coalescing window (or
        the next urgent send), then shipped in a batch frame."""
        msg = ("cast", op, args)
        if self._dropped(msg):
            return
        if self._coalesce_window() <= 0:
            self._send_frame(msg)
            return
        with self._cast_q_lock:
            self._cast_q.append(msg)
        if not self._flusher_started:
            self._start_cast_flusher()
        self._flush_ev.set()

    def _start_cast_flusher(self) -> None:
        with self._cast_q_lock:
            if self._flusher_started:
                return
            self._flusher_started = True
        t = threading.Thread(target=self._cast_flusher_loop, daemon=True,
                             name="rtpu_cast_flusher")
        t.start()

    def _cast_flusher_loop(self) -> None:
        """The Nagle window: after the first buffered cast, wait
        ``RTPU_PIPE_COALESCE_US`` for more to accumulate, then flush them
        as one frame (unless an urgent send piggybacked them first)."""
        from ray_tpu.util import profiling

        while True:
            self._flush_ev.wait()
            self._flush_ev.clear()
            profiling.idle_sleep(self._coalesce_window())
            try:
                self._send_frame()
            except (OSError, BrokenPipeError):
                return  # pipe gone: the recv loop exits the process

    def _ship_refpins(self, items) -> None:
        """Ship one drained batch of borrow transitions. Packed wire form
        ("RTP1" + (id[16] + i8)*) when the native-pipe plane is on — the
        driver applies it without touching the interpreter (its Python
        fallback reader parses the same frame); else the r13 pickled
        ``refpins`` cast. Either way the transitions ride the SAME Nagle
        cadence as ordinary casts (a frame per 0<->1 transition would
        triple the multi-client frames/task)."""
        if self._refpin_packed is None:
            try:
                from ray_tpu import config as _cfg

                self._refpin_packed = bool(_cfg.get("native_pipe"))
            except Exception:
                self._refpin_packed = False
        if not self._refpin_packed:
            self.cast("refpins", items)
            return
        # the ONE worker->driver chaos filter covers this egress too
        if self._dropped(("cast", "refpins", (items,))):
            return
        with self._cast_q_lock:
            self._refpin_buf.extend(items)
        if self._coalesce_window() <= 0:
            self._send_frame()
            return
        if not self._flusher_started:
            self._start_cast_flusher()
        self._flush_ev.set()

    def _ref_added(self, oid_b: bytes) -> None:
        with self._refs_lock:
            before = self._ref_counts.get(oid_b, 0)
            self._ref_counts[oid_b] = before + 1
            if before == 0:
                self._ref_casts.append((oid_b, 1))
        self._ref_casts.flush()
        self._drain_ref_drops()

    def _apply_ref_drop_locked(self, b: bytes) -> None:
        n = self._ref_counts.get(b, 0) - 1
        if n > 0:
            self._ref_counts[b] = n
        else:
            self._ref_counts.pop(b, None)
            if n == 0:
                self._ref_casts.append((b, -1))
                # local refcount hit zero: this process's store pin must
                # drop too (release() keeps it if zero-copy views are
                # still alive), or a free()d arena object stays kDeleting
                # forever on our reader ref and its memory never returns
                self._pending_pin_releases.append(b)

    def _after_ref_drops(self) -> None:
        self._ref_casts.flush()
        while True:
            try:
                # graftlint: disable=unguarded-shared-write -- deque ops are
                # GIL-atomic; drain is deliberately lock-free (refqueue.py:
                # __del__ hooks must take no locks)
                b = self._pending_pin_releases.popleft()
            except IndexError:
                return
            try:
                self.store.release(ObjectID(b))
            except Exception:
                pass

    def _drain_ref_drops(self) -> None:
        """Apply ref drops queued by ObjectRef.__del__ (which cannot lock)."""
        self._deferred_ref_drops.drain()

    def _start_receiver(self):
        if self._recv_started:
            return
        self._recv_started = True
        t = threading.Thread(target=self._recv_loop, daemon=True,
                             name="rtpu_worker_recv")
        t.start()

    def _recv_loop(self):
        import pickle as _pickle

        while True:
            try:
                buf = self.conn.recv_bytes()
            except (EOFError, OSError):
                os._exit(0)
            if buf[:4] == b"RTB1":
                # native-coalesced driver frame: magic + u32be count +
                # (u32be len + pickle)* — the GIL-free sender packs every
                # message queued during the previous write into one frame
                n = int.from_bytes(buf[4:8], "big")
                off = 8
                for _ in range(n):
                    ln = int.from_bytes(buf[off:off + 4], "big")
                    off += 4
                    self._dispatch_recv(_pickle.loads(buf[off:off + ln]))
                    off += ln
                continue
            # no "batch" unwrap here: driver->worker coalescing is the
            # native RTB1 frame above — only the worker->driver direction
            # ships ("batch", [...]) tuples (pipe-protocol-sync)
            self._dispatch_recv(_pickle.loads(buf))

    def _dispatch_recv(self, msg):
        kind = msg[0]
        if kind == "exec":
            self._exec_queue.put(msg[1])
        elif kind == "cancel":
            self._deliver_cancel(msg[1])
        elif kind == "reply":
            req_id = msg[1]
            with self._reply_lock:
                ev = self._reply_events.pop(req_id, None)
                if ev is not None:   # drop replies nobody awaits
                    self._replies[req_id] = (msg[2], msg[3])
            if ev is not None:
                ev.set()
        elif kind == "fp":
            # chaos plane: driver-pushed failpoint arm/disarm
            from ray_tpu.util import failpoints

            if msg[1] is None:
                failpoints.clear()
            else:
                try:
                    failpoints.apply_spec(msg[1])
                except ValueError:
                    pass
        elif kind == "trace":
            # trace plane: driver-pushed mid-session arm/disarm —
            # workers spawned before enable_tracing() learn here
            from ray_tpu.util import tracing

            if msg[1] is not None:
                tracing.apply_remote(msg[1])
                if not msg[1].get("enabled"):
                    # disarm: ship the ring's tail NOW — the push
                    # loop stops looking once tracing is off, and
                    # the last interval's spans (the end of the
                    # traced workload) must not strand here
                    self._push_spans_now()
        elif kind == "prof":
            # profiling plane: driver-pushed mid-session arm/disarm —
            # apply_remote starts/stops this process's sampler
            from ray_tpu.util import profiling

            if msg[1] is not None:
                profiling.apply_remote(msg[1])
                if not msg[1].get("enabled"):
                    # disarm: ship the table's tail NOW (the push
                    # loop stops looking once profiling is off)
                    self._push_profile_now()
        elif kind == "events":
            # event plane: driver-pushed mid-session arm/disarm —
            # workers spawned before an enable/disable_events() flip
            # learn here
            from ray_tpu.util import events

            if msg[1] is not None:
                events.apply_remote(msg[1])
                if not msg[1].get("enabled"):
                    # disarm: ship the ring's tail NOW (the push
                    # loop stops looking once events are off)
                    self._push_events_now()
        elif kind == "stackdump":
            # live stack request (`ray_tpu stack` py-spy role): walk
            # sys._current_frames on THIS receiver thread (pure
            # frame-graph reads, no locks) and cast the reply back
            from ray_tpu.util import profiling

            try:
                self.cast("stacks", profiling.current_stacks())
            except Exception:
                pass
        elif kind == "shutdown":
            os._exit(0)

    def request(self, op: str, *args, timeout: Optional[float] = None):
        """Request/reply over the pipe. Returns the payload, or the
        ``_TIMEOUT`` sentinel when ``timeout`` expires first."""
        import time as _time

        req_id = next(self._req_counter)
        ev = threading.Event()
        with self._reply_lock:
            self._reply_events[req_id] = ev
        deadline = None if timeout is None else _time.monotonic() + timeout
        try:
            self._send(("req", req_id, op, args))
            # polled wait, not a bare ev.wait(): an injected cancellation
            # (PyThreadState_SetAsyncExc) can only be delivered while this
            # thread executes bytecode — a C-level block would pin a
            # cancelled task forever (e.g. a backpressured producer whose
            # consumer went away)
            while not ev.wait(0.5):
                if deadline is not None and _time.monotonic() > deadline:
                    with self._reply_lock:
                        self._reply_events.pop(req_id, None)
                        self._replies.pop(req_id, None)
                    return _TIMEOUT
        except BaseException:
            # interrupted (cancel injection): a late reply must not leak
            # into self._replies forever
            with self._reply_lock:
                self._reply_events.pop(req_id, None)
                self._replies.pop(req_id, None)
            raise
        with self._reply_lock:
            status, payload = self._replies.pop(req_id)
        if status == "err":
            raise cloudpickle.loads(payload)
        return payload

    # -- object API -------------------------------------------------------

    def put(self, value: Any) -> ObjectRef:
        obj_id = ObjectID.from_random()
        # refs nested inside the value transfer to the stored object's
        # lifetime (owner pins them until the outer object is freed) — a
        # borrower dropping its local refs must not strand the consumer
        # (advisor r3: results/puts previously leaked this pin)
        with collect_serialized_refs() as nested:
            inline, size = self.store.put(obj_id, value)
        # creation call-site for `ray_tpu memory` forensics rides the
        # existing cast, captured only while the profiler is armed
        from ray_tpu.util import profiling

        site = (profiling.caller_site()
                if profiling.profiling_enabled() else None)
        if site is None:
            self.cast("put", obj_id.binary(), inline, size,
                      list(nested) or None)
        else:
            self.cast("put", obj_id.binary(), inline, size,
                      list(nested) or None, site)
        return ObjectRef(obj_id)

    def put_parts(self, data: bytes, buffers) -> ObjectRef:
        obj_id = ObjectID.from_random()
        inline, size = self.store.put_parts(obj_id, data, buffers)
        self.cast("put", obj_id.binary(), inline, size)
        return ObjectRef(obj_id)

    def hint_pull_align(self, oid_b: bytes, stride: int,
                        payload_bytes: int = 0) -> None:
        """Register a chunk-alignment (stride, payload-size) hint for
        ``oid_b``'s next get (consumed by the hosting runtime's chunked
        cross-node pull — records start after the serialized header)."""
        if stride > 1 and len(self._pull_aligns) < 4096:
            self._pull_aligns[bytes(oid_b)] = (int(stride),
                                               int(payload_bytes))

    def get(self, refs: List[ObjectRef], timeout: Optional[float] = None):
        ids = [r.id.binary() for r in refs]
        # pop-with-default: two task threads getting the same hinted
        # ref must not race a bare pop into a KeyError
        aligns = {i: h for i in ids
                  if (h := self._pull_aligns.pop(i, None)) is not None}
        self.cast("blocked")
        try:
            if aligns:
                results = self.request("get", ids, timeout, aligns)
            else:
                results = self.request("get", ids, timeout)
        finally:
            self.cast("unblocked")
        if results is None:
            raise GetTimeoutError(f"get timed out after {timeout}s on {refs}")
        out = []
        for (kind, payload), r in zip(results, refs):
            if kind == "i":
                out.append(serialization.loads_oob(payload))
            elif kind == "s":
                out.append(self._store_get_with_recovery(r.id))
            else:
                raise cloudpickle.loads(payload)
        return out

    def wait(self, refs, num_returns, timeout, fetch_local=True):
        ids = [r.id.binary() for r in refs]
        self.cast("blocked")
        try:
            ready, rest = self.request("wait", ids, num_returns, timeout)
        finally:
            self.cast("unblocked")
        by_id = {r.id.binary(): r for r in refs}
        return [by_id[i] for i in ready], [by_id[i] for i in rest]

    # -- task/actor submission -------------------------------------------

    def ensure_fn(self, h: str, blob: bytes):
        if h not in self.registered_fns:
            self.cast("fn_put", h, blob)
            self.registered_fns.add(h)

    def _stamp_trace(self, spec: dict, kind: str) -> None:
        """Nested submissions join the ENCLOSING task's trace: the spec
        carries this worker's active span context so the driver-side
        handling and the eventual execute span parent here, not in a
        fresh trace (reference tracing_helper nested-call propagation)."""
        from ray_tpu.util import tracing

        if not tracing.tracing_enabled():
            return
        name = spec.get("name") or spec.get("method") or "task"
        with tracing.span(f"submit::{name}",
                          {"task_id": spec["task_id"].hex(),
                           "nested": True}) as tp:
            spec["trace_ctx"] = tp

    def submit(self, spec: dict) -> List[ObjectRef]:
        self._stamp_trace(spec, "task")
        self.cast("submit", spec)
        tid = TaskID(spec["task_id"])
        return [ObjectRef(ObjectID(b), task_id=tid) for b in spec["return_ids"]]

    def create_actor(self, spec: dict):
        self.request("actor_create", spec)

    def submit_actor_task(self, spec: dict) -> List[ObjectRef]:
        self._stamp_trace(spec, "actor_call")
        self.cast("actor_call", spec)
        return [ObjectRef(ObjectID(b)) for b in spec["return_ids"]]

    def kill_actor(self, actor_id: bytes, no_restart: bool = True):
        self.cast("kill_actor", actor_id, no_restart)

    def cancel(self, ref: ObjectRef, force: bool = False):
        self.cast("cancel", ref.id.binary(), force)

    def lookup_named_actor(self, name: str):
        return self.request("name_lookup", name)

    def actor_queue_depths(self, actor_ids):
        return self.request("actor_depths", actor_ids)

    def create_placement_group(self, bundles, strategy: str) -> bytes:
        return self.request("pg_create", bundles, strategy)

    def remove_placement_group(self, pg_id: bytes):
        self.request("pg_remove", pg_id)

    def kv_op(self, op: str, *args):
        return self.request("kv", op, *args)

    def resources(self, which: str) -> Dict[str, float]:
        return self.request("resources", which)

    def node_info(self):
        return self.request("nodes")

    def free(self, ids: List[bytes]):
        # the caller asserts the objects are fully consumed: drop OUR store
        # pin first (view-liveness guarded), then let the driver delete —
        # otherwise the arena entry waits on this process's reader ref,
        # which leaks outright if this worker is killed before idle-drain
        for b in ids:
            try:
                self.store.release(ObjectID(b))
            except Exception:
                pass
        self.cast("free", ids)

    # -- cooperative cancellation ----------------------------------------

    def _deliver_cancel(self, task_id: bytes):
        """Interrupt the task if it is running HERE (reference
        ``execute_task_with_cancellation_handler``, ``_raylet.pyx:2084``).

        Sync tasks get ``TaskCancelledError`` injected into their thread
        via ``PyThreadState_SetAsyncExc`` (lands at the next bytecode
        boundary — blocking syscalls finish first); async actor calls get
        their asyncio future cancelled, which interrupts at the next
        await."""
        from ray_tpu.core.exceptions import TaskCancelledError

        with self._running_lock:
            fut = self._running_futs.get(task_id)
            # re-read under the lock at injection time: if the task already
            # finished, its entry is gone and we must NOT inject into a
            # thread that has moved on (main loop / another task) — a small
            # check->inject window remains, which main_loop's cancel guard
            # absorbs
            tident = self._running_threads.get(task_id)
            if fut is not None:
                fut.cancel()
                return
            if tident is None:
                return
            import ctypes

            ctypes.pythonapi.PyThreadState_SetAsyncExc(
                ctypes.c_ulong(tident), ctypes.py_object(TaskCancelledError))

    # -- execution --------------------------------------------------------

    def _resolve_fn(self, h: str):
        fn = self.fn_cache.get(h)
        if fn is None:
            blob = self.request("fn_get", h)
            if blob is None:
                raise RuntimeError(f"function {h} not found in GCS")
            fn = cloudpickle.loads(blob)
            self.fn_cache[h] = fn
            self.registered_fns.add(h)
        return fn

    def _decode_arg(self, e, timings: Optional[Dict[str, float]] = None):
        """Decode one spec argument. ``timings`` (flight recorder)
        accumulates inline/deserialize time under "deserialize" and
        store reads of ref args — fetch + load together, the store get
        returns the object — under "arg_fetch"."""
        kind = e[0]
        t0 = time.perf_counter() if timings is not None else 0.0
        if kind == "v":
            out, tkey = serialization.loads_oob(e[1]), "deserialize"
        elif kind == "ri":
            out, tkey = serialization.loads_oob(e[2]), "deserialize"
        elif kind == "r":
            out = self._store_get_with_recovery(ObjectID(e[1]))
            tkey = "arg_fetch"
        elif kind == "re":
            raise cloudpickle.loads(e[1])
        else:
            raise ValueError(f"bad arg encoding {kind}")
        if timings is not None:
            timings[tkey] = (timings.get(tkey, 0.0)
                             + time.perf_counter() - t0)
        return out

    def _store_get_with_recovery(self, oid: ObjectID):
        """Store read with lineage recovery: a missing segment (evicted /
        deleted behind the directory) asks the driver to re-execute the
        producer, then retries (reference object_recovery_manager.h:41)."""
        try:
            return self.store.get(oid)
        except (FileNotFoundError, OSError):
            # release our resource slot while the producer re-executes —
            # on a saturated pool the reconstruction task needs it
            self.cast("blocked")
            try:
                ok = self.request("reconstruct", oid.binary())
            finally:
                self.cast("unblocked")
            if not ok:
                raise
            return self.store.get(oid)

    def _encode_results(self, spec: dict, value: Any):
        rids = spec["return_ids"]
        if len(rids) == 1:
            values = [value]
        else:
            values = list(value)
            if len(values) != len(rids):
                raise ValueError(
                    f"task declared num_returns={len(rids)} but returned {len(values)}"
                )
        results = []
        for rid_b, v in zip(rids, values):
            oid = ObjectID(rid_b)
            # collect refs nested in the RESULT (not just args): the owner
            # pins them against the return object's lifetime, so a consumer
            # deserializing after this worker's local refs are GC'd still
            # finds them live (advisor r3, reference borrowed-refs-in-
            # returned-values semantics)
            with collect_serialized_refs() as nested:
                inline, size = self.store.put(oid, v)
            if inline is not None:
                entry = (rid_b, "i", inline)
            else:
                # payload = segment size: the runtime records it in the
                # directory so peers can plan chunked pulls (re-statting
                # on the demux thread would tax every result)
                entry = (rid_b, "s", size)
            if nested:
                entry = entry + (list(nested),)
            results.append(entry)
        return results

    def _apply_runtime_env(self, spec: dict):
        """Apply a per-task/actor runtime_env (reference
        ``python/ray/runtime_env``: env_vars, working_dir, py_modules,
        pip site dirs — conda/containers stay unsupported, the image is
        fixed). Returns an undo closure; actor creation applies
        permanently (the process is dedicated). A failure mid-apply
        (bad working_dir, failed pip install) rolls back everything
        applied so far — a partial env must never leak into later
        tasks."""
        renv = spec.get("runtime_env")
        if not renv:
            return lambda: None
        saved_env = {}
        saved_cwd = None
        path_entries = []
        try:
            for k, v in (renv.get("env_vars") or {}).items():
                saved_env[k] = os.environ.get(k)
                os.environ[k] = str(v)
            wd = renv.get("working_dir")
            if wd:
                saved_cwd = os.getcwd()
                os.chdir(wd)
                import sys

                sys.path.insert(0, wd)
                path_entries.append(wd)
            uris = renv.get("py_modules_uris")
            if uris:
                import sys

                from ray_tpu.runtime_env import (_PKG_NAMESPACE,
                                                 materialize_py_modules)

                for entry in materialize_py_modules(
                        uris,
                        lambda u: self.kv_op("get", u, _PKG_NAMESPACE)):
                    sys.path.insert(0, entry)
                    path_entries.append(entry)
            pip_env = renv.get("pip_env")
            if pip_env:
                import sys

                from ray_tpu.runtime_env import ensure_pip_env

                # first use on this node builds the env
                # (flock-serialized); later uses hit the .ready cache.
                # The site dir takes import PRECEDENCE for the task's
                # duration and is fully undone after (module eviction
                # below included).
                entry = ensure_pip_env(pip_env)
                sys.path.insert(0, entry)
                path_entries.append(entry)
        except BaseException:
            self._undo_runtime_env(saved_env, saved_cwd, path_entries)
            raise
        if spec["type"] == ts.ACTOR_CREATE:
            return lambda: None  # permanent for the actor's lifetime

        return lambda: self._undo_runtime_env(saved_env, saved_cwd,
                                              path_entries)

    @staticmethod
    def _undo_runtime_env(saved_env, saved_cwd, path_entries) -> None:
        """Revert an applied (possibly PARTIAL) runtime_env — the one
        definition used by both the post-task undo and the mid-apply
        failure rollback."""
        import sys

        for k, old in saved_env.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
        if saved_cwd is not None:
            os.chdir(saved_cwd)
        for entry in path_entries:
            if entry in sys.path:
                sys.path.remove(entry)
        if path_entries:
            # evict modules loaded from the removed entries, or they
            # would leak into later tasks without this runtime_env
            doomed = [
                name for name, mod in list(sys.modules.items())
                if getattr(mod, "__file__", None)
                and any(mod.__file__.startswith(e + os.sep)
                        for e in path_entries)
            ]
            for name in doomed:
                del sys.modules[name]

    def _stream_results(self, spec: dict, value):
        """Drain a streaming task's generator: each yield becomes an object
        under a deterministic id announced immediately (consumers overlap
        with production); the declared return id is the end sentinel and
        resolves to the item count.

        With ``stream_backpressure`` = N, production pauses while N yields
        are unconsumed (reference ``generator_waiter.cc``): the driver
        tracks consumption from the ObjectRefGenerator and releases
        permits."""
        bp = spec.get("stream_backpressure")
        count = 0
        for item in value:
            if bp and count >= bp:
                bp = self._await_stream_permit(spec, count, bp)
            self._emit_stream_item(spec, count, item)
            count += 1
        return self._encode_results(spec, count)

    def _await_stream_permit(self, spec: dict, count: int, bp: int):
        """Permit to produce item ``count``: at most ``bp`` outstanding.
        Releases our resource slot while parked — a consumer draining
        slowly must not starve the pool. The timeout is a deadlock valve
        (e.g. consumer acks lost to a dead node): proceed unthrottled
        rather than park a worker forever. Returns bp, or None when pacing
        was abandoned."""
        self.cast("blocked")
        try:
            out = self.request("stream_permit", spec["task_id"],
                               count + 1 - bp, timeout=300.0)
        finally:
            self.cast("unblocked")
        return None if out is _TIMEOUT else bp

    def _emit_stream_item(self, spec: dict, count: int, item) -> None:
        oid = ObjectID(ts.streaming_return_id(spec["task_id"], count))
        with collect_serialized_refs() as nested:
            inline, size = self.store.put(oid, item)
        self.cast("put", oid.binary(), inline, size, list(nested) or None)

    def stream_consumed(self, task_id: bytes, n: int, owner=None) -> None:
        self.cast("stream_consumed", task_id, n, owner)

    @property
    def cluster_node_id(self):
        return None  # workers tag no owner; their node runtime routes

    def _make_actor_loop(self, actor_id: bytes):
        import asyncio

        loop = asyncio.new_event_loop()
        threading.Thread(target=loop.run_forever, daemon=True,
                         name="rtpu_actor_loop").start()
        self._actor_loops[actor_id] = loop
        return loop

    def _schedule_async(self, spec: dict, coro, undo_env):
        """Schedule an async actor call on the actor's persistent loop and
        return immediately — the main loop keeps dispatching, so concurrent
        calls interleave at awaits. The done message is sent from the
        future's callback."""
        import asyncio

        loop = self._actor_loops[spec["actor_id"]]
        fut = asyncio.run_coroutine_threadsafe(coro, loop)
        tid = spec["task_id"]
        with self._running_lock:
            self._running_futs[tid] = fut

        def on_done(f):
            with self._running_lock:
                self._running_futs.pop(tid, None)
            try:
                try:
                    value = f.result()
                except BaseException as e:  # noqa: BLE001
                    self._send_error(spec, e)
                    return
                results = self._encode_results(spec, value)
                self._send(("done", tid, results))
            except BaseException as e:  # noqa: BLE001
                self._send_error(spec, e)
            finally:
                undo_env()
                self._note_task_metrics({})  # async calls count too

        fut.add_done_callback(on_done)

    def _schedule_async_stream(self, spec: dict, agen, undo_env):
        """``num_returns="streaming"`` on an ASYNC actor method: drain the
        async generator on the actor's persistent loop, announcing each
        yield through the same put path as the sync stream — concurrent
        calls keep interleaving at awaits (ADVICE r2: a sync ``for`` over
        an async generator raised TypeError). Backpressure permits are
        awaited off-loop so the actor loop never blocks."""
        import asyncio

        async def drain():
            bp = spec.get("stream_backpressure")
            count = 0
            aloop = asyncio.get_running_loop()
            async for item in agen:
                if bp and count >= bp:
                    bp = await aloop.run_in_executor(
                        None, self._await_stream_permit, spec, count, bp)
                self._emit_stream_item(spec, count, item)
                count += 1
            return count

        # the sentinel return id resolves to the item count, exactly like
        # a plain async call resolves to its value
        self._schedule_async(spec, drain(), undo_env)

    def _send_error(self, spec: dict, e: BaseException):
        from concurrent.futures import CancelledError

        from ray_tpu.core.exceptions import TaskCancelledError

        desc = f"{spec['type']} {spec.get('name') or spec.get('method', '')}"
        if isinstance(e, (CancelledError, TaskCancelledError)):
            # cancellation travels as a bare TaskCancelledError so callers
            # see ONE exception type regardless of when the cancel landed
            # (queued / running / force all match the queued path)
            err = TaskCancelledError("task was cancelled")
        elif isinstance(e, TaskError):
            err = e
        else:
            err = TaskError(
                e, "".join(traceback.format_exception(type(e), e,
                                                      e.__traceback__)),
                desc)
        blob = cloudpickle.dumps(err)
        results = [(rid, "e", blob) for rid in spec["return_ids"]]
        self._send(("done", spec["task_id"], results))

    def execute(self, spec: dict):
        from ray_tpu.util import tracing

        if tracing.tracing_enabled():
            name = spec.get("name") or spec.get("method") or "task"
            with tracing.span(f"execute::{name}",
                              {"task_id": spec["task_id"].hex(),
                               "worker_id": self.worker_id.hex()},
                              parent=spec.get("trace_ctx")):
                return self._execute_inner(spec)
        return self._execute_inner(spec)

    def _execute_inner(self, spec: dict):
        ttype = spec["type"]
        self.current_task_id = TaskID(spec["task_id"])
        undo_env = lambda: None  # noqa: E731
        tid_b = spec["task_id"]
        with self._running_lock:
            self._running_threads[tid_b] = threading.get_ident()
        # computed BEFORE decoding (it reads only the encoded spec): a
        # mid-decode failure must still release the pins the args decoded
        # so far already took
        arg_oids = ts.arg_refs(spec["args"], spec["kwargs"])
        # flight-recorder phase durations; ride the done message so the
        # driver's recorder sees worker-side phases without extra traffic
        # (None when disabled: no timing calls, no extra message payload)
        phases: Optional[Dict[str, float]] = (
            {} if self._flight_enabled else None)

        def enc(v, streaming=False):
            if phases is None:
                return (self._stream_results(spec, v) if streaming
                        else self._encode_results(spec, v))
            t2 = time.perf_counter()
            if streaming:
                # the generator drain IS the execution (produce + store
                # interleave); no separate store_result phase
                r = self._stream_results(spec, v)
                phases["execute"] = time.perf_counter() - t_exec
            else:
                phases["execute"] = t2 - t_exec
                r = self._encode_results(spec, v)
                phases["store_result"] = time.perf_counter() - t2
            return r

        from ray_tpu.util import failpoints

        try:
            # inside the try: a bad runtime_env (missing working_dir...)
            # must fail THIS task, not crash the worker process
            failpoints.hit("worker.exec",
                           spec.get("name") or spec.get("method"))
            undo_env = self._apply_runtime_env(spec)
            args = [self._decode_arg(a, phases) for a in spec["args"]]
            kwargs = {k: self._decode_arg(v, phases)
                      for k, v in spec["kwargs"].items()}
            t_exec = time.perf_counter()
            if ttype == ts.TASK:
                fn = self._resolve_fn(spec["fn_hash"])
                value = fn(*args, **kwargs)
                results = enc(value, streaming=bool(spec.get("streaming")))
            elif ttype == ts.ACTOR_CREATE:
                cls = self._resolve_fn(spec["fn_hash"])
                self.current_actor_id = ActorID(spec["actor_id"])
                instance = cls(*args, **kwargs)
                self.actors[spec["actor_id"]] = instance
                self.actor_concurrency[spec["actor_id"]] = int(
                    spec.get("max_concurrency", 1))
                if _has_async_methods(cls):
                    self._make_actor_loop(spec["actor_id"])
                results = enc(None)
            elif ttype == ts.ACTOR_METHOD:
                instance = self.actors.get(spec["actor_id"])
                if instance is None:
                    raise ActorDiedError("actor instance not found in this worker")
                self.current_actor_id = ActorID(spec["actor_id"])
                if spec["method"] == "__rtpu_call__":
                    # run an arbitrary function against the instance
                    # (reference ``actor.__ray_call__`` analog; the
                    # compiled-DAG exec loop rides this).
                    fn, *rest = args
                    value = fn(instance, *rest, **kwargs)
                else:
                    method = getattr(instance, spec["method"])
                    value = method(*args, **kwargs)
                import inspect as _inspect

                if _inspect.isasyncgen(value):
                    if (spec.get("streaming")
                            and spec["actor_id"] in self._actor_loops):
                        self._schedule_async_stream(spec, value, undo_env)
                        undo_env = lambda: None  # noqa: E731 — owned by cb
                        return

                    # non-streaming call: drain the async generator to a
                    # list. On an async actor this becomes a coroutine and
                    # flows into the persistent-loop branch below — running
                    # it inline here would freeze the dispatch thread (and
                    # deadlock if the generator awaits another method of
                    # the same actor).
                    async def _collect(g=value):
                        return [x async for x in g]

                    if spec["actor_id"] in self._actor_loops:
                        value = _collect()
                    else:
                        import asyncio

                        value = asyncio.run(_collect())

                if _iscoroutine(value):
                    if spec["actor_id"] in self._actor_loops:
                        # async actor: schedule on the persistent loop and
                        # return — done is sent by the future callback
                        self._schedule_async(spec, value, undo_env)
                        undo_env = lambda: None  # noqa: E731 — owned by cb
                        return
                    # sync actor that returned a coroutine: run it out
                    import asyncio

                    value = asyncio.run(value)
                results = enc(value, streaming=bool(spec.get("streaming")))
            else:
                raise ValueError(f"unknown task type {ttype}")
            failpoints.hit("worker.exec.before_result",
                           spec.get("name") or spec.get("method"))
            if phases is None:
                self._send(("done", spec["task_id"], results))
            else:
                self._send(("done", spec["task_id"], results, phases))
            self._note_task_metrics(phases or {})
        except BaseException as e:  # noqa: BLE001 — remote errors must not kill the worker
            self._send_error(spec, e)
            self._note_task_metrics(phases or {})  # errored tasks count too
        finally:
            undo_env()
            # Drop the store pins _decode_arg's gets took: no ObjectRef
            # tracks them, so without this a free()d arg object stays
            # kDeleting on our reader ref and its arena memory never
            # returns. The frame's own locals are view-holders — clear
            # them first or the liveness guard below always fires.
            # release() keeps the pin whenever OTHER live zero-copy views
            # still reference the segment (baseline guard), so a
            # task/actor that stashed a view of its arg stays safe.
            args = kwargs = value = results = None  # noqa: F841
            for _oid in arg_oids:
                try:
                    self.store.release(_oid)
                except Exception:
                    pass
            with self._running_lock:
                self._running_threads.pop(tid_b, None)
                # Absorb a cancel injected but not yet DELIVERED: a pending
                # async exc landing after this frame returns would kill an
                # unrelated frame (e.g. actor thread-pool internals,
                # permanently shrinking the pool). Clearing under the same
                # lock the injector holds closes the window: once the entry
                # is gone no new injection can target this thread.
                import ctypes

                ctypes.pythonapi.PyThreadState_SetAsyncExc(
                    ctypes.c_ulong(threading.get_ident()),
                    ctypes.c_void_p(0))
            self.current_task_id = None

    # -- metrics federation (sender side) --------------------------------

    def _note_task_metrics(self, phases: Dict[str, float]) -> None:
        """Worker-local built-ins: executed-task counter + exec-time
        histogram. These live in THIS process's registry and reach the
        head /metrics via the federated delta push, labeled with this
        worker's id."""
        try:
            if self._wmetrics is None:
                from ray_tpu.util import metric_defs

                self._wmetrics = {
                    "tasks": metric_defs.get("rtpu_worker_tasks_total"),
                    "exec": metric_defs.get(
                        "rtpu_worker_task_exec_seconds"),
                }
            self._wmetrics["tasks"].inc()
            if "execute" in phases:
                self._wmetrics["exec"].observe(phases["execute"])
        except Exception:
            pass

    def _maybe_push_metrics(self) -> None:
        """Push metric-registry DELTAS to the driver over the existing
        pipe, rate-limited (default 2s) — the federation hop for worker
        processes. Between pushes the hot path pays one monotonic-clock
        read; nothing is sent when no metric changed."""
        if self._metrics_interval is None:
            try:
                from ray_tpu import config as _cfg

                self._metrics_interval = (
                    float(_cfg.get("metrics_push_interval_s"))
                    if _cfg.get("metrics_federation") else 0.0)
            except Exception:
                self._metrics_interval = 0.0
        if self._metrics_interval <= 0:
            return
        now = time.monotonic()
        if now - self._metrics_last_push < self._metrics_interval:
            return
        self._metrics_last_push = now
        try:
            from ray_tpu.util import metrics as _metrics

            if self._metrics_exporter is None:
                self._metrics_exporter = _metrics.DeltaExporter()
            records = self._metrics_exporter.collect()
            if records:
                self.cast("metrics", records)
        except Exception:
            pass

    def _maybe_push_spans(self) -> None:
        """Drain this process's span ring to the driver as a batched cast
        (the trace-plane hop for worker processes; driver ingests into its
        TraceStore with this worker's origin labels). One dict get when
        tracing is disabled; rate-limited otherwise."""
        from ray_tpu.util import tracing

        if not tracing.tracing_enabled():
            return
        now = time.monotonic()
        if self._trace_interval is None:
            try:
                from ray_tpu import config as _cfg

                self._trace_interval = float(
                    _cfg.get("trace_push_interval_s"))
            except Exception:
                self._trace_interval = 1.0
        if now - self._trace_last_push < self._trace_interval:
            return
        self._trace_last_push = now
        self._push_spans_now()

    def _push_spans_now(self) -> None:
        """Drain the ring and ship it as one cast — THE span-push hop,
        shared by the rate-limited loop and the disarm-time tail flush."""
        from ray_tpu.util import tracing

        try:
            batch = tracing.drain_ring()
            if batch:
                self.cast("spans", batch)
                tracing.note_push()
        except Exception:
            pass

    def _maybe_push_profile(self) -> None:
        """Drain this process's profile table to the driver as a batched
        cast, rate-limited (the profile twin of _maybe_push_spans). One
        dict get when profiling is disarmed; also the lazy start point
        for the sampler in env-armed workers (zygote children restart
        theirs here after fork)."""
        from ray_tpu.util import profiling

        if not profiling.profiling_enabled():
            return
        profiling.ensure_sampler()
        now = time.monotonic()
        if self._profile_interval is None:
            try:
                from ray_tpu import config as _cfg

                self._profile_interval = float(
                    _cfg.get("profile_push_interval_s"))
            except Exception:
                self._profile_interval = 1.0
        if now - self._profile_last_push < self._profile_interval:
            return
        self._profile_last_push = now
        self._push_profile_now()

    def _push_profile_now(self) -> None:
        """Drain the table and ship it as one cast — THE profile-push
        hop, shared by the rate-limited loop and the disarm tail flush."""
        from ray_tpu.util import profiling

        try:
            batches = profiling.drain_batches()
            if batches:
                self.cast("prof", batches)
                profiling.note_push()
        except Exception:
            pass

    def _maybe_push_events(self) -> None:
        """Drain this process's lifecycle-event ring to the driver as a
        batched cast, rate-limited (the event twin of
        _maybe_push_spans). One dict get when the plane is killed."""
        from ray_tpu.util import events

        if not events.events_enabled():
            return
        now = time.monotonic()
        if self._event_interval is None:
            try:
                from ray_tpu import config as _cfg

                self._event_interval = float(
                    _cfg.get("event_push_interval_s"))
            except Exception:
                self._event_interval = 1.0
        if now - self._event_last_push < self._event_interval:
            return
        self._event_last_push = now
        self._push_events_now()

    def _push_events_now(self) -> None:
        """Drain the ring and ship it as one cast — THE event-push hop,
        shared by the rate-limited loop and the disarm tail flush."""
        from ray_tpu.util import events

        try:
            batch = events.drain_ring()
            if batch:
                self.cast("events", batch)
                events.note_push()
        except Exception:
            pass

    def _maybe_push_device(self) -> None:
        """Ship this process's compiled-program registry snapshot to the
        driver, rate-limited AND version-gated: zygote workers that never
        import jax keep an empty registry at version 0 and never ship
        anything (the ``"jax" in sys.modules`` guard inside snapshot()
        also keeps the census from importing jax here)."""
        from ray_tpu.util import device_plane

        if not device_plane.device_plane_enabled():
            return
        now = time.monotonic()
        if self._device_interval is None:
            try:
                from ray_tpu import config as _cfg

                self._device_interval = float(
                    _cfg.get("device_push_interval_s"))
            except Exception:
                self._device_interval = 2.0
        if now - self._device_last_push < self._device_interval:
            return
        self._device_last_push = now
        try:
            snap = device_plane.snapshot(
                min_version=self._device_version_shipped)
            if snap is not None:
                self._device_version_shipped = snap["version"]
                self.cast("device", snap)
        except Exception:
            pass

    def push_telemetry(self) -> None:
        """Rate-limited metric/span/profile/event pushes, callable from
        ANY thread: the main loop's idle ticks, and compiled-DAG exec
        loops — whose occupying ``__rtpu_call__`` starves a
        concurrency-1 actor's main loop, so without this hook a DAG
        actor's spans/metrics would strand in its rings until teardown."""
        with self._push_lock:
            self._maybe_push_metrics()
            self._maybe_push_spans()
            self._maybe_push_profile()
            self._maybe_push_events()
            self._maybe_push_device()

    def main_loop(self):
        self._start_receiver()
        self._send(("ready",))
        import queue as _queue

        while True:
            try:
                spec = self._exec_queue.get(timeout=2.0)
            except _queue.Empty:
                # idle: bounded staleness for __del__-deferred ref drops
                self._drain_ref_drops()
                self.push_telemetry()
                continue
            self._drain_ref_drops()
            self.push_telemetry()
            conc = (self.actor_concurrency.get(spec.get("actor_id", b""), 1)
                    if spec["type"] == ts.ACTOR_METHOD else 1)
            if (spec["type"] == ts.ACTOR_METHOD
                    and spec.get("actor_id") in self._actor_loops):
                # async actor: execute() schedules the coroutine on the
                # actor's persistent loop and returns immediately — no
                # thread pool needed for interleaving
                self._execute_guarded(spec)
            elif conc > 1:
                # concurrent actor: run the call on the actor's thread
                # pool so the main loop keeps draining dispatches
                aid = spec["actor_id"]
                pool = self._actor_pools.get(aid)
                if pool is None:
                    from concurrent.futures import ThreadPoolExecutor

                    pool = ThreadPoolExecutor(
                        max_workers=conc,
                        thread_name_prefix="rtpu_actor")
                    self._actor_pools[aid] = pool
                pool.submit(self._execute_guarded, spec)
            else:
                self._execute_guarded(spec)

    def _execute_guarded(self, spec: dict):
        """execute() plus a guard for a cancel injection that lands after
        the task's except/finally (the SetAsyncExc check->inject window):
        the stray TaskCancelledError must not kill the dispatch thread."""
        from ray_tpu.core.exceptions import TaskCancelledError

        try:
            self.execute(spec)
        except TaskCancelledError:
            pass


def _iscoroutine(value) -> bool:
    import inspect

    return inspect.iscoroutine(value)


def _has_async_methods(cls) -> bool:
    import inspect

    return any(
        inspect.iscoroutinefunction(m := getattr(cls, name, None))
        or inspect.isasyncgenfunction(m)
        for name in dir(cls) if not name.startswith("_")
    )


def worker_entry(conn, session: str, worker_id: bytes):
    os.environ["RTPU_WORKER"] = "1"
    from ray_tpu.util.tpu_info import honor_jax_platform_env

    honor_jax_platform_env(only_if_imported=True)
    import ray_tpu.core.runtime as rt

    w = WorkerRuntime(conn, session, worker_id)
    rt._set_runtime(w)
    try:
        w.main_loop()
    except KeyboardInterrupt:
        os._exit(0)


def _main():
    """Worker executable: ``python -m ray_tpu.core.worker --addr ...``.

    Workers are separate executables that dial back to the driver over a
    unix socket (reference: raylet execs ``default_worker.py``) — NOT
    multiprocessing children, so a driver script without an
    ``if __name__ == "__main__"`` guard can never fork-bomb.
    """
    import argparse
    import faulthandler
    import signal
    from multiprocessing.connection import Client

    # `ray_tpu stack` analog of `ray stack` (py-spy role): SIGUSR1 dumps
    # every thread's python stack into the worker's log file. The spawner
    # pre-sets SIGUSR1 to SIG_IGN across exec (ignored dispositions
    # survive), so a stray signal during the multi-second interpreter
    # boot cannot kill the worker before this register runs.
    faulthandler.register(signal.SIGUSR1, all_threads=True)

    ap = argparse.ArgumentParser()
    ap.add_argument("--addr", required=True)
    ap.add_argument("--session", required=True)
    ap.add_argument("--worker-id", required=True)
    args = ap.parse_args()

    # Retry transient connect failures: a spawn burst can momentarily
    # fill the driver listener's accept backlog, and unix sockets fail
    # with EAGAIN instead of blocking — crashing here would kill the
    # actor this worker was spawned for.
    deadline = time.monotonic() + 10.0
    while True:
        try:
            conn = Client(args.addr, family="AF_UNIX",
                          authkey=args.session.encode())
            break
        except (BlockingIOError, ConnectionRefusedError, OSError):
            if time.monotonic() >= deadline:
                raise
            time.sleep(0.05)
    wid = bytes.fromhex(args.worker_id)
    conn.send(("hello", wid))
    worker_entry(conn, args.session, wid)


if __name__ == "__main__":
    _main()
