"""ObjectRef — a future for a value in the object store.

Role analog: reference ``python/ray/includes/object_ref.pxi:36``.
"""

from __future__ import annotations

from ray_tpu.core.ids import ObjectID

# Process-local reference hook (reference ReferenceCounter,
# ``src/ray/core_worker/reference_count.h:61`` role): every live ObjectRef
# instance counts as one local reference. The driver runtime / worker
# installs (on_add, on_del); processes that never handle refs (GCS) keep
# the no-op default. Distributed liveness: local 0<->1 transitions become
# node-level pins at the cluster directory.
_ref_hook = None


def set_ref_hook(on_add, on_del) -> None:
    global _ref_hook
    _ref_hook = (on_add, on_del)


def clear_ref_hook() -> None:
    global _ref_hook
    _ref_hook = None


# Serialization-time ref collection: while a collector list is active on
# this thread, every ObjectRef that gets pickled records its id. Task-arg
# encoding uses this to pin refs NESTED inside inline values (the
# reference's "borrowed references in serialized arguments").
import threading as _threading

_collect = _threading.local()


class collect_serialized_refs:
    def __enter__(self):
        self.prev = getattr(_collect, "refs", None)
        _collect.refs = []
        return _collect.refs

    def __exit__(self, *exc):
        _collect.refs = self.prev
        return False


class ObjectRef:
    __slots__ = ("id", "owner", "_task_id", "_counted")

    def __init__(self, object_id: ObjectID, owner: str = "", task_id=None):
        self.id = object_id
        self.owner = owner
        self._task_id = task_id
        self._counted = False
        hook = _ref_hook
        if hook is not None:
            try:
                hook[0](object_id.binary())
                self._counted = True
            except Exception:
                pass

    def __del__(self):
        if self._counted:
            hook = _ref_hook
            if hook is not None:
                try:
                    hook[1](self.id.binary())
                except Exception:
                    pass

    def binary(self) -> bytes:
        return self.id.binary()

    def hex(self) -> str:
        return self.id.hex()

    def task_id(self):
        return self._task_id

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ObjectRef({self.id.hex()})"

    def __reduce__(self):
        refs = getattr(_collect, "refs", None)
        if refs is not None:
            refs.append(self.id.binary())
        return (ObjectRef, (self.id, self.owner, self._task_id))

    # ``await ref`` support inside async actors / drivers.
    def __await__(self):
        import asyncio

        loop = asyncio.get_event_loop()

        def _get():
            from ray_tpu.core.runtime import get

            return get(self)

        return loop.run_in_executor(None, _get).__await__()


class ObjectRefGenerator:
    """Iterator over the yields of a ``num_returns="streaming"`` task.

    Role analog: reference ``ObjectRefGenerator`` (``_raylet.pyx:273``).
    Each ``__next__`` returns the next item's :class:`ObjectRef` as soon as
    the producer yields it — consumers overlap with the still-running
    producer. The task's declared return object is the END SENTINEL: it
    resolves to the total item count when the generator completes (or to
    the task's error).

    Item ids are derived deterministically from the task id
    (:func:`ray_tpu.core.task_spec.streaming_return_id`).
    """

    def __init__(self, task_id: bytes, sentinel: "ObjectRef",
                 backpressured: bool = False,
                 owner: "Optional[bytes]" = None):
        self._task_id = task_id
        self._sentinel = sentinel
        self._index = 0
        self._count = None  # known once the sentinel resolves
        self._bp = backpressured
        # node that submitted the stream: a consumer on a THIRD node routes
        # its acks there (the owner holds the producer's forward route)
        self._owner = owner
        self._handed_off = False  # serialized to another consumer

    def __iter__(self):
        return self

    def __next__(self) -> "ObjectRef":
        while True:
            out = self._advance(timeout=None)
            if out is not None:
                return out

    def _advance(self, timeout):
        """One consumption attempt, the SHARED core of the blocking
        (``__next__``, timeout=None) and polling (``try_next``, timeout=0)
        paths: returns the next item's ref, ``None`` when not ready within
        ``timeout``, raises ``StopIteration`` at stream end."""
        from ray_tpu.core.runtime import _get_runtime

        rt = _get_runtime()
        item = self.next_item_ref()
        if self._count is None:
            ready, _ = rt.wait([item, self._sentinel], num_returns=1,
                               timeout=timeout)
            if item in ready:
                self._index += 1
                self._ack(rt)
                return item
            if self._sentinel in ready:
                # completion (count) or task error
                self._count = rt.get([self._sentinel], timeout=0)[0]
            else:
                return None
        if self._index >= self._count:
            raise StopIteration
        # count known -> the item was definitely produced
        self._index += 1
        self._ack(rt)
        return item

    def _ack(self, rt) -> None:
        """Report consumption so a backpressured producer may continue.

        Skipped entirely for unthrottled streams (no per-item IPC on the
        hot path) and once the producer finished (nobody is waiting)."""
        if not self._bp or self._count is not None:
            return
        try:
            rt.stream_consumed(self._task_id, self._index,
                               owner=self._owner)
        except Exception:
            pass

    def next_item_ref(self) -> "ObjectRef":
        """The ref the NEXT ``__next__``/``try_next`` would return, without
        consuming it. Waitable: ``ray_tpu.wait([g.next_item_ref(), ...])``
        wakes a scheduler the moment any stream has a ready item (the
        per-operator data executor's idle wait). Past the end it is the
        never-resolving ref after the last item — pair with
        :meth:`completed` when waiting."""
        from ray_tpu.core import task_spec as ts
        from ray_tpu.core.ids import ObjectID

        return ObjectRef(ObjectID(ts.streaming_return_id(self._task_id,
                                                         self._index)))

    def try_next(self):
        """Non-blocking :meth:`__next__`: the next item's ref if the
        producer has yielded it, ``None`` if not yet, ``StopIteration``
        raised when the stream is exhausted. Lets a scheduler poll many
        streams without parking on any one (reference
        ``streaming_executor_state`` polls op outqueues the same way)."""
        return self._advance(timeout=0)

    def close(self) -> None:
        """Abandon the stream: release any backpressured producer (it runs
        to completion; surplus items are dropped with the task)."""
        try:
            from ray_tpu.core.runtime import _get_runtime

            _get_runtime().stream_consumed(self._task_id, 1 << 60,
                                           owner=self._owner)
        except Exception:
            pass

    def __del__(self):
        # release a parked producer ONLY when this was the sole consumer:
        # a serialized copy (handed to another task) owns consumption now
        if self._bp and self._count is None and not self._handed_off:
            self.close()

    def __len__(self):
        if self._count is None:
            raise TypeError("generator still running; length unknown")
        return self._count

    def completed(self) -> "ObjectRef":
        """The end-sentinel ref (resolves to the item count)."""
        return self._sentinel

    def __reduce__(self):
        self._handed_off = True
        return (ObjectRefGenerator,
                (self._task_id, self._sentinel, self._bp, self._owner))
