"""ObjectRef — a future for a value in the object store.

Role analog: reference ``python/ray/includes/object_ref.pxi:36``.
"""

from __future__ import annotations

from ray_tpu.core.ids import ObjectID


class ObjectRef:
    __slots__ = ("id", "owner", "_task_id")

    def __init__(self, object_id: ObjectID, owner: str = "", task_id=None):
        self.id = object_id
        self.owner = owner
        self._task_id = task_id

    def binary(self) -> bytes:
        return self.id.binary()

    def hex(self) -> str:
        return self.id.hex()

    def task_id(self):
        return self._task_id

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ObjectRef({self.id.hex()})"

    def __reduce__(self):
        return (ObjectRef, (self.id, self.owner, self._task_id))

    # ``await ref`` support inside async actors / drivers.
    def __await__(self):
        import asyncio

        loop = asyncio.get_event_loop()

        def _get():
            from ray_tpu.core.runtime import get

            return get(self)

        return loop.run_in_executor(None, _get).__await__()
