"""Binary IDs for tasks, objects, actors, nodes, placement groups.

Role analog: reference ``src/ray/common/id.h`` (28-byte binary IDs). We use
16 random bytes — uniqueness within a cluster lifetime is all the runtime
needs, and shorter ids keep message payloads small.
"""

from __future__ import annotations

import os
import threading

_ID_LEN = 16

_local = threading.local()

#: per-thread buffered entropy: os.urandom is a getrandom(2) syscall, and
#: on the containers this runs in it profiles at 20-80 µs — two calls per
#: submitted task made it one of the largest driver-CPU line items (r14).
#: A 4 KiB refill amortizes the syscall over 256 ids; thread-local state
#: needs no lock. fork safety: workers are separate executables (never
#: os.fork of the driver mid-run), and the zygote fork-server itself
#: never generates ids, so no child can inherit a partially-used pool.
_POOL_LEN = _ID_LEN * 256


def _rand_bytes() -> bytes:
    buf = getattr(_local, "pool", None)
    off = getattr(_local, "pool_off", 0)
    if buf is None or off >= _POOL_LEN:
        buf = _local.pool = os.urandom(_POOL_LEN)
        off = 0
    _local.pool_off = off + _ID_LEN
    return buf[off:off + _ID_LEN]


class BaseID:
    __slots__ = ("_bytes", "_hash")

    def __init__(self, id_bytes: bytes):
        if not isinstance(id_bytes, bytes) or len(id_bytes) != _ID_LEN:
            raise ValueError(f"expected {_ID_LEN} raw bytes, got {id_bytes!r}")
        self._bytes = id_bytes
        self._hash = None

    @classmethod
    def from_random(cls):
        return cls(_rand_bytes())

    @classmethod
    def from_hex(cls, h: str):
        return cls(bytes.fromhex(h))

    @classmethod
    def nil(cls):
        return cls(b"\x00" * _ID_LEN)

    def is_nil(self) -> bool:
        return self._bytes == b"\x00" * _ID_LEN

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self):
        # cached: ids key hot dicts (directory, wait sets) and the tuple
        # hash showed up as 3s of a 2000-task profile
        h = self._hash
        if h is None:
            h = self._hash = hash((type(self).__name__, self._bytes))
        return h

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class ObjectID(BaseID):
    pass


class TaskID(BaseID):
    pass


class ActorID(BaseID):
    pass


class NodeID(BaseID):
    pass


class PlacementGroupID(BaseID):
    pass


class WorkerID(BaseID):
    pass


class JobID(BaseID):
    pass
