"""Zygote worker spawner: pre-warmed fork server for worker processes.

Role analog: the reference raylet's ``WorkerPool`` (``worker_pool.h:159``)
keeps worker *processes* warm (prestart); on a 64-core box a cold
``python`` exec is cheap enough that Ray doesn't need more. On this box the
interpreter + worker imports cost ~0.15s of CPU per worker, capping cold
actor/task bursts at ~13 spawns/s on 2 vCPUs. The zygote amortizes that
cost once: ONE clean process is exec'd at init (``python -S``, skipping
the jax-importing sitecustomize), pre-imports ``ray_tpu.core.worker``, and
then forks a child per spawn request (~5 ms).

Safety properties that make the fork clean (unlike forking the driver,
which is forbidden — it is threaded and jax-laden):

- the zygote is SINGLE-THREADED at every fork (requests are served from a
  select() loop; child reaping is WNOHANG polling, not a reaper thread);
  this includes the observability planes: the zygote never arms the
  tracing or profiling modules (a sampler thread here would make every
  fork unsafe), and ``util/profiling.py``'s at-fork hook resets the
  child's sampler handle so an armed worker restarts its own sampler
  from its main loop after the fork;
- it never imports jax or user code, so no locks, no CUDA/TPU handles;
- each child closes the zygote's control fds, redirects stdio to its own
  log file, and then runs the exact same ``worker.main`` that an exec'd
  worker runs — it still dials back over the unix socket, so the
  worker-transport architecture is unchanged (workers are NOT
  multiprocessing children of the driver; driver scripts without a
  ``__main__`` guard keep working).

Protocol (json lines): driver -> zygote stdin ``{"wid", "addr",
"session", "log"}``; zygote -> driver stdout ``{"event": "spawned",
"wid", "pid"}`` and ``{"event": "exit", "wid", "pid", "status"}``.
"""

from __future__ import annotations

import json
import os
import select
import signal
import sys


def zygote_main() -> None:
    # Pre-import the worker module (and transitively the runtime/store
    # client machinery) BEFORE serving: every forked child inherits the
    # warm module cache.
    import ray_tpu.core.worker as worker_mod

    signal.signal(signal.SIGUSR1, signal.SIG_IGN)
    # our children must not become zombies of init if we die first; but
    # while we live, WE are their parent and must reap them
    children = {}  # pid -> wid
    stdin_fd = sys.stdin.fileno()
    out = sys.stdout
    buf = b""

    def emit(obj) -> None:
        out.write(json.dumps(obj) + "\n")
        out.flush()

    emit({"event": "ready", "pid": os.getpid()})
    while True:
        try:
            ready, _, _ = select.select([stdin_fd], [], [], 0.2)
        except InterruptedError:
            ready = []
        # reap exited children (WNOHANG poll keeps us single-threaded)
        while children:
            try:
                pid, status = os.waitpid(-1, os.WNOHANG)
            except ChildProcessError:
                break
            if pid == 0:
                break
            wid = children.pop(pid, None)
            code = (os.waitstatus_to_exitcode(status)
                    if hasattr(os, "waitstatus_to_exitcode") else status)
            emit({"event": "exit", "wid": wid, "pid": pid, "status": code})
        if not ready:
            continue
        chunk = os.read(stdin_fd, 65536)
        if not chunk:
            # driver closed our stdin: shut down; children keep running
            # (the driver owns their lifecycle via signals)
            return
        buf += chunk
        while b"\n" in buf:
            line, buf = buf.split(b"\n", 1)
            if not line.strip():
                continue
            try:
                req = json.loads(line)
            except json.JSONDecodeError:
                continue
            pid = os.fork()
            if pid == 0:
                _child_exec(worker_mod, req)  # never returns
            children[pid] = req["wid"]
            emit({"event": "spawned", "wid": req["wid"], "pid": pid})


def _child_exec(worker_mod, req: dict) -> None:
    """Forked child: detach from the zygote's fds and run the worker."""
    try:
        os.setpgid(0, 0)  # own process group: driver kill signals are exact
    except OSError:
        pass
    signal.signal(signal.SIGUSR1, signal.SIG_IGN)  # until worker registers
    try:
        log_fd = os.open(req["log"],
                         os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        devnull = os.open(os.devnull, os.O_RDONLY)
        os.dup2(devnull, 0)
        os.dup2(log_fd, 1)
        os.dup2(log_fd, 2)
        if log_fd > 2:
            os.close(log_fd)
        if devnull > 2:
            os.close(devnull)
        sys.argv = ["ray_tpu.core.worker",
                    "--addr", req["addr"],
                    "--session", req["session"],
                    "--worker-id", req["wid"]]
        worker_mod._main()
        os._exit(0)
    except SystemExit as e:
        os._exit(int(e.code or 0))
    except BaseException:
        import traceback

        traceback.print_exc()
        os._exit(1)


if __name__ == "__main__":
    zygote_main()
