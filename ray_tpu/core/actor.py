"""ActorClass / ActorHandle / ActorMethod.

Role analog: reference ``python/ray/actor.py`` (``ActorClass :566``,
``ActorHandle :1226``, ``ActorMethod :116``). Each actor is a dedicated
worker process; method calls are dispatched in submission order by the
driver (the reference's sequential actor submit queue,
``src/ray/core_worker/transport/sequential_actor_submit_queue.cc``).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ray_tpu.core import task_spec as ts
from ray_tpu.core.ids import ActorID
from ray_tpu.core.remote_function import _normalize_resources, _pg_options


class ActorMethod:
    def __init__(self, actor_id: ActorID, method_name: str,
                 options: Optional[Dict] = None, tmpl_cache: Optional[Dict] = None):
        self._actor_id = actor_id
        self._method_name = method_name
        self._options = dict(options or {})
        # submit fast-path (r13): the handle-owned template cache —
        # ActorMethod objects are born per attribute access, so the
        # invariant spec parts cache on the HANDLE, keyed by the
        # spec-shaping options (a changed option set is a different key,
        # never a stale template)
        self._tmpl_cache = tmpl_cache if tmpl_cache is not None else {}

    def options(self, **new_options):
        return ActorMethod(self._actor_id, self._method_name,
                           {**self._options, **new_options},
                           self._tmpl_cache)

    def _template(self) -> Dict:
        num_returns = self._options.get("num_returns", 1)
        streaming = num_returns in ("streaming", "dynamic")
        bp = self._options.get("_generator_backpressure_num_objects")
        key = (self._method_name, num_returns, bp)
        tmpl = self._tmpl_cache.get(key)
        if tmpl is None:
            tmpl = ts.make_actor_method_template(
                self._actor_id.binary(),
                self._method_name,
                num_returns=1 if streaming else int(num_returns),
                streaming=streaming,
                stream_backpressure=int(bp) if streaming and bp else 0,
            )
            self._tmpl_cache[key] = tmpl
        return tmpl

    def remote(self, *args, **kwargs):
        from ray_tpu.core.runtime import _get_runtime

        rt = _get_runtime()
        enc_args, enc_kwargs, nested_refs = ts.encode_args(args, kwargs, rt)
        spec = ts.spec_from_template(self._template(), enc_args, enc_kwargs)
        if nested_refs:
            spec["borrowed"] = nested_refs
        if spec.get("streaming"):
            from ray_tpu.core.object_ref import ObjectRefGenerator

            refs = rt.submit_actor_task(spec)
            return ObjectRefGenerator(
                spec["task_id"], refs[0],
                backpressured=bool(spec.get("stream_backpressure")),
                owner=getattr(rt, "cluster_node_id", None))
        refs = rt.submit_actor_task(spec)
        return refs[0] if self._options.get("num_returns", 1) == 1 else refs

    def __call__(self, *a, **kw):
        raise TypeError(
            f"actor method {self._method_name} cannot be called directly; use .remote()"
        )

    def bind(self, *args, **kwargs):
        """Build a lazy DAG node for this method (reference
        ``actor.method.bind``, ``python/ray/dag/class_node.py``)."""
        from ray_tpu.dag.dag_node import ClassMethodNode

        return ClassMethodNode(
            ActorHandle(self._actor_id,
                        {self._method_name: dict(self._options)}),
            self._method_name, args, kwargs)


class ActorHandle:
    def __init__(self, actor_id: ActorID, method_options: Optional[Dict[str, Dict]] = None):
        object.__setattr__(self, "_actor_id", actor_id)
        object.__setattr__(self, "_method_options", method_options or {})
        # per-handle spec-template cache shared by every ActorMethod this
        # handle hands out (r13 submit fast-path)
        object.__setattr__(self, "_tmpl_cache", {})

    def __getattr__(self, name: str):
        if name.startswith("_") and name != "__rtpu_call__":
            raise AttributeError(name)
        return ActorMethod(self._actor_id, name,
                           self._method_options.get(name), self._tmpl_cache)

    def __repr__(self):
        return f"ActorHandle({self._actor_id.hex()})"

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._method_options))

    def __hash__(self):
        return hash(self._actor_id)

    def __eq__(self, other):
        return isinstance(other, ActorHandle) and other._actor_id == self._actor_id


class ActorClass:
    def __init__(self, cls, options: Dict[str, Any]):
        self._cls = cls
        self._options = dict(options or {})
        self._cls_blob = ts.pickle_fn(cls)
        self._cls_hash = ts.fn_digest(self._cls_blob)
        self.__name__ = getattr(cls, "__name__", "Actor")
        # collect @ray_tpu.method options declared on the class
        self._method_options = {
            n: getattr(m, "_rtpu_method_options")
            for n, m in vars(cls).items()
            if callable(m) and hasattr(m, "_rtpu_method_options")
        }

    def __call__(self, *a, **kw):
        raise TypeError(
            f"actor class {self.__name__} cannot be instantiated directly; "
            f"use {self.__name__}.remote()"
        )

    def options(self, **new_options):
        ac = ActorClass.__new__(ActorClass)
        ac._cls = self._cls
        ac._options = {**self._options, **new_options}
        ac._cls_blob = self._cls_blob
        ac._cls_hash = self._cls_hash
        ac.__name__ = self.__name__
        ac._method_options = self._method_options
        return ac

    def remote(self, *args, **kwargs) -> ActorHandle:
        from ray_tpu.core.runtime import _get_runtime

        rt = _get_runtime()
        rt.ensure_fn(self._cls_hash, self._cls_blob)
        enc_args, enc_kwargs, nested_refs = ts.encode_args(args, kwargs, rt)
        pg, bundle_index = _pg_options(self._options)
        renv = self._options.get("runtime_env")
        if renv:
            # no-ops without py_modules; raises loudly on pip/conda/etc
            from ray_tpu.runtime_env import package_runtime_env

            renv = package_runtime_env(renv, rt)
            self._options = {**self._options, "runtime_env": renv}
        spec = ts.make_actor_create_spec(
            self._cls_hash,
            enc_args,
            enc_kwargs,
            resources=_normalize_resources(self._options, default_cpu=0.0),
            actor_name=self._options.get("name", ""),
            max_restarts=int(self._options.get("max_restarts", 0)),
            max_concurrency=int(self._options.get(
                "max_concurrency", self._default_concurrency())),
            placement_group_id=pg,
            bundle_index=bundle_index,
            runtime_env=self._options.get("runtime_env"),
        )
        if nested_refs:
            spec["borrowed"] = nested_refs
        from ray_tpu.core.remote_function import _strategy_spec

        strat = _strategy_spec(self._options)
        if strat is not None:
            spec["strategy"] = strat
        rt.create_actor(spec)
        return ActorHandle(ActorID(spec["actor_id"]), self._method_options)

    def _default_concurrency(self) -> int:
        """Async actors (any ``async def`` method) default to many
        concurrent calls — they interleave on one event loop, so the limit
        is a queue-depth guard, not a thread count (reference default 1000
        for async actors)."""
        import inspect

        has_async = any(
            inspect.iscoroutinefunction(getattr(self._cls, n, None))
            or inspect.isasyncgenfunction(getattr(self._cls, n, None))
            for n in dir(self._cls) if not n.startswith("_"))
        return 100 if has_async else 1

    def __reduce__(self):
        return (_rebuild_actor_class, (self._cls_blob, self._options))


def _rebuild_actor_class(cls_blob: bytes, options: Dict[str, Any]) -> ActorClass:
    import cloudpickle

    ac = ActorClass.__new__(ActorClass)
    ac._cls = cloudpickle.loads(cls_blob)
    ac._options = options
    ac._cls_blob = cls_blob
    ac._cls_hash = ts.fn_digest(cls_blob)
    ac.__name__ = getattr(ac._cls, "__name__", "Actor")
    ac._method_options = {
        n: getattr(m, "_rtpu_method_options")
        for n, m in vars(ac._cls).items()
        if callable(m) and hasattr(m, "_rtpu_method_options")
    }
    return ac
