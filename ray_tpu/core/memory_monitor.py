"""MemoryMonitor: host-RAM pressure detection + worker-killing policy.

Role analog: ``src/ray/common/memory_monitor.h:52`` plus the raylet's
retriable-first worker-killing policies (``worker_killing_policy*.h``). A
background thread samples /proc/meminfo; past the usage threshold it asks
the runtime to kill the most recently started retriable task's worker
(RetriableFIFO-lite: retriable first, newest first — the victim retries
from lineage, so work is delayed, not lost).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional


def system_memory() -> dict:
    """{'total': bytes, 'available': bytes, 'used_fraction': float}."""
    info = {}
    with open("/proc/meminfo") as f:
        for line in f:
            parts = line.split()
            if parts[0] in ("MemTotal:", "MemAvailable:"):
                info[parts[0][:-1]] = int(parts[1]) * 1024
    total = info.get("MemTotal", 1)
    avail = info.get("MemAvailable", total)
    return {
        "total": total,
        "available": avail,
        "used_fraction": 1.0 - avail / total,
    }


class MemoryMonitor:
    def __init__(
        self,
        usage_threshold: float = 0.95,
        poll_interval_s: float = 1.0,
        on_pressure: Optional[Callable[[dict], None]] = None,
    ):
        self.usage_threshold = usage_threshold
        self.poll_interval_s = poll_interval_s
        self.on_pressure = on_pressure
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.num_pressure_events = 0

    def check(self) -> bool:
        """One sample; fires the callback if over threshold."""
        mem = system_memory()
        if mem["used_fraction"] >= self.usage_threshold:
            self.num_pressure_events += 1
            if self.on_pressure is not None:
                self.on_pressure(mem)
            return True
        return False

    def start(self) -> "MemoryMonitor":
        def loop():
            while not self._stop.wait(self.poll_interval_s):
                try:
                    self.check()
                except Exception:
                    pass

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="rtpu_memory_monitor")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()


def kill_retriable_policy(runtime) -> Callable[[dict], None]:
    """Build the default pressure handler for a DriverRuntime: kill the
    newest busy pool worker whose task has retries left."""

    def handler(mem: dict) -> None:
        import logging

        # Select AND terminate under the runtime lock: dropping it between
        # the two would let the worker finish its retriable task and pick
        # up a non-retriable one before the SIGTERM lands.
        with runtime.lock:
            candidates = [
                ws for ws in runtime.workers.values()
                if ws.kind == "pool" and ws.status == "busy"
                and ws.current and ws.current.get("retries_left", 0) > 0
            ]
            # newest TASK first (retriable FIFO): losing the least work
            victim = max(
                candidates,
                key=lambda w: runtime._task_start_ts.get(
                    w.current["task_id"], 0.0),
                default=None)
            if victim is not None:
                try:
                    victim.proc.terminate()
                except Exception:
                    victim = None
        if victim is None:
            logging.getLogger(__name__).warning(
                "memory pressure (%.0f%% used) but no retriable task to "
                "kill", mem["used_fraction"] * 100)
            return
        logging.getLogger(__name__).warning(
            "memory pressure (%.0f%% used): killed retriable task on "
            "worker %s", mem["used_fraction"] * 100,
            victim.worker_id.hex()[:8])

    return handler
