"""Object serialization: pickle protocol 5 with out-of-band buffers.

Role analog: reference ``python/ray/_private/serialization.py``
(``SerializationContext``, msgpack + cloudpickle + zero-copy numpy readers).

Layout written into an object-store buffer::

    u64 magic | u64 n_buffers | u64 pickle_len | [u64 buf_len]*n  |
    pickle bytes | padding-to-64 | buf0 | padding-to-64 | buf1 | ...

Large contiguous payloads (numpy arrays, bytes) travel out-of-band so that
``get`` can reconstruct them as zero-copy views over shared memory. JAX
arrays are device-resident; they are converted to numpy on ``put`` (host
round-trip) — device-to-device transfer without a host hop is the job of the
device channel layer (``ray_tpu.channel``), not the object store.
"""

from __future__ import annotations

import pickle
import struct
from typing import Any, List, Optional, Tuple

import cloudpickle

MAGIC = 0x52415954505500  # "RAYTPU"
_ALIGN = 64
_HDR = struct.Struct("<QQQ")


def _pad(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


def _to_host(value: Any) -> Any:
    # jax.Array → numpy before pickling; imported lazily so the core runtime
    # does not depend on jax.
    t = type(value)
    mod = t.__module__
    if mod.startswith("jaxlib") or mod.startswith("jax"):
        import numpy as np

        try:
            return np.asarray(value)
        except Exception:
            return value
    return value


def serialize(value: Any) -> Tuple[bytes, List[pickle.PickleBuffer]]:
    """Returns (pickle_bytes, out_of_band_buffers)."""
    buffers: List[pickle.PickleBuffer] = []
    value = _to_host(value)

    def cb(buf: pickle.PickleBuffer):
        # Only send large buffers out-of-band; small ones inline pickle.
        if buf.raw().nbytes >= 512:
            buffers.append(buf)
            return False  # out-of-band
        return True  # serialize in-band

    # cloudpickle, not plain pickle: plain pickle serializes __main__-defined
    # functions/classes BY REFERENCE (succeeds locally, AttributeError in the
    # worker whose __main__ is the worker entrypoint). cloudpickle serializes
    # those by value and delegates everything else to the C pickler, so the
    # data path cost is unchanged (reference: Ray's SerializationContext is
    # cloudpickle-based too, python/ray/_private/serialization.py:111).
    try:
        data = cloudpickle.dumps(value, protocol=5, buffer_callback=cb)
    except Exception:
        buffers.clear()
        data = pickle.dumps(value, protocol=5, buffer_callback=cb)
    return data, buffers


def serialized_size(data: bytes, buffers: List[pickle.PickleBuffer]) -> int:
    n = len(buffers)
    off = _HDR.size + 8 * n
    off = _pad(off + len(data))
    for b in buffers:
        off = _pad(off + b.raw().nbytes)
    return off


#: lazy state for the native multi-threaded copy path (r14 data plane):
#: slices at or above RTPU_STORE_PARALLEL_COPY_BYTES go through
#: _native.parallel_copy (N slicing threads, GIL released), targeting the
#: measured single-thread memcpy ceiling in aggregate. 0 threshold or a
#: missing .so disables it; the fallback is the plain slice assignment.
_pcopy_min: Optional[int] = None
_pcopy_threads = 0
_pcopy_fn = None
_pcopy_metrics = None


def _parallel_copy_setup():
    global _pcopy_min, _pcopy_threads, _pcopy_fn
    from ray_tpu import config

    _pcopy_min = int(config.get("store_parallel_copy_bytes"))
    _pcopy_threads = int(config.get("store_copy_threads"))
    if _pcopy_min > 0:
        try:
            from ray_tpu import _native

            if _native.pipe_engine_available():
                _pcopy_fn = _native.parallel_copy
            else:
                _pcopy_min = 0
        except Exception:
            _pcopy_min = 0
    return _pcopy_min


def _blit(mv: memoryview, off: int, raw) -> None:
    """One serialized-buffer copy into the store segment; large slices
    ride the native multi-threaded memcpy."""
    nb = raw.nbytes
    src = raw.cast("B") if raw.format != "B" or raw.ndim != 1 else raw
    limit = _pcopy_min if _pcopy_min is not None else _parallel_copy_setup()
    if limit and nb >= limit:
        global _pcopy_metrics
        try:
            import time as _time

            t0 = _time.perf_counter()
            _pcopy_fn(mv[off:off + nb], src, _pcopy_threads)
            dt = _time.perf_counter() - t0
            if _pcopy_metrics is None:
                from ray_tpu.util import metric_defs as _md

                _pcopy_metrics = (
                    _md.get(
                        "rtpu_object_store_parallel_copy_bytes_total"),
                    _md.get("rtpu_object_store_parallel_copy_seconds"))
            _pcopy_metrics[0].inc(nb)
            _pcopy_metrics[1].observe(dt)
            return
        except Exception:
            pass  # any native hiccup falls back to the plain copy
    mv[off:off + nb] = src


def write_into(mv: memoryview, data: bytes, buffers: List[pickle.PickleBuffer]) -> int:
    """Writes the serialized object into ``mv``; returns bytes written."""
    n = len(buffers)
    _HDR.pack_into(mv, 0, MAGIC, n, len(data))
    off = _HDR.size
    for b in buffers:
        struct.pack_into("<Q", mv, off, b.raw().nbytes)
        off += 8
    mv[off : off + len(data)] = data
    off = _pad(off + len(data))
    for b in buffers:
        raw = b.raw()
        nb = raw.nbytes
        _blit(mv, off, raw)
        off = _pad(off + nb)
    return off


def iter_serialized_blocks(data: bytes, buffers: List[pickle.PickleBuffer],
                           block_size: int):
    """Yield the exact ``write_into`` layout as successive bytes chunks of
    ``block_size`` (last may be short) WITHOUT materializing the whole
    object — the spill-write path streams these through the codec, so a
    multi-GB spill's peak extra heap is one block, not the object
    (the restore side has honored that bound all along)."""
    n = len(buffers)
    head = bytearray(_HDR.size + 8 * n)
    _HDR.pack_into(head, 0, MAGIC, n, len(data))
    off = _HDR.size
    for b in buffers:
        struct.pack_into("<Q", head, off, b.raw().nbytes)
        off += 8
    pos = len(head) + len(data)

    def pieces():
        yield memoryview(head)
        yield memoryview(data)
        p = pos
        yield memoryview(b"\x00" * (_pad(p) - p))
        p = _pad(p)
        for b in buffers:
            raw = b.raw()
            yield (raw.cast("B")
                   if raw.format != "B" or raw.ndim != 1 else raw)
            p += raw.nbytes
            yield memoryview(b"\x00" * (_pad(p) - p))
            p = _pad(p)

    block = bytearray()
    for mv in pieces():
        o = 0
        ln = len(mv)
        while o < ln:
            take = min(block_size - len(block), ln - o)
            block += mv[o:o + take]
            o += take
            if len(block) == block_size:
                yield bytes(block)
                block.clear()
    if block:
        yield bytes(block)


def read_from(mv: memoryview) -> Any:
    """Reconstructs an object from a store buffer.

    Out-of-band buffers are zero-copy views into ``mv`` — the caller must
    keep the backing segment alive as long as the value (the object store
    client pins segments per ref).
    """
    magic, n, plen = _HDR.unpack_from(mv, 0)
    if magic != MAGIC:
        raise ValueError("corrupt object buffer (bad magic)")
    off = _HDR.size
    sizes = []
    for _ in range(n):
        (sz,) = struct.unpack_from("<Q", mv, off)
        sizes.append(sz)
        off += 8
    data = bytes(mv[off : off + plen])
    off = _pad(off + plen)
    bufs = []
    for sz in sizes:
        bufs.append(mv[off : off + sz])
        off = _pad(off + sz)
    return pickle.loads(data, buffers=bufs)


def dumps_oob(value: Any) -> bytes:
    """One-shot serialize to a contiguous bytes blob (for pipe transport)."""
    data, buffers = serialize(value)
    size = serialized_size(data, buffers)
    out = bytearray(size)
    write_into(memoryview(out), data, buffers)
    return bytes(out)


def loads_oob(blob: bytes) -> Any:
    return read_from(memoryview(blob))
