"""Object serialization: pickle protocol 5 with out-of-band buffers.

Role analog: reference ``python/ray/_private/serialization.py``
(``SerializationContext``, msgpack + cloudpickle + zero-copy numpy readers).

Layout written into an object-store buffer::

    u64 magic | u64 n_buffers | u64 pickle_len | [u64 buf_len]*n  |
    pickle bytes | padding-to-64 | buf0 | padding-to-64 | buf1 | ...

Large contiguous payloads (numpy arrays, bytes) travel out-of-band so that
``get`` can reconstruct them as zero-copy views over shared memory. JAX
arrays are device-resident; they are converted to numpy on ``put`` (host
round-trip) — device-to-device transfer without a host hop is the job of the
device channel layer (``ray_tpu.channel``), not the object store.
"""

from __future__ import annotations

import pickle
import struct
from typing import Any, List, Tuple

import cloudpickle

MAGIC = 0x52415954505500  # "RAYTPU"
_ALIGN = 64
_HDR = struct.Struct("<QQQ")


def _pad(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


def _to_host(value: Any) -> Any:
    # jax.Array → numpy before pickling; imported lazily so the core runtime
    # does not depend on jax.
    t = type(value)
    mod = t.__module__
    if mod.startswith("jaxlib") or mod.startswith("jax"):
        import numpy as np

        try:
            return np.asarray(value)
        except Exception:
            return value
    return value


def serialize(value: Any) -> Tuple[bytes, List[pickle.PickleBuffer]]:
    """Returns (pickle_bytes, out_of_band_buffers)."""
    buffers: List[pickle.PickleBuffer] = []
    value = _to_host(value)

    def cb(buf: pickle.PickleBuffer):
        # Only send large buffers out-of-band; small ones inline pickle.
        if buf.raw().nbytes >= 512:
            buffers.append(buf)
            return False  # out-of-band
        return True  # serialize in-band

    # cloudpickle, not plain pickle: plain pickle serializes __main__-defined
    # functions/classes BY REFERENCE (succeeds locally, AttributeError in the
    # worker whose __main__ is the worker entrypoint). cloudpickle serializes
    # those by value and delegates everything else to the C pickler, so the
    # data path cost is unchanged (reference: Ray's SerializationContext is
    # cloudpickle-based too, python/ray/_private/serialization.py:111).
    try:
        data = cloudpickle.dumps(value, protocol=5, buffer_callback=cb)
    except Exception:
        buffers.clear()
        data = pickle.dumps(value, protocol=5, buffer_callback=cb)
    return data, buffers


def serialized_size(data: bytes, buffers: List[pickle.PickleBuffer]) -> int:
    n = len(buffers)
    off = _HDR.size + 8 * n
    off = _pad(off + len(data))
    for b in buffers:
        off = _pad(off + b.raw().nbytes)
    return off


def write_into(mv: memoryview, data: bytes, buffers: List[pickle.PickleBuffer]) -> int:
    """Writes the serialized object into ``mv``; returns bytes written."""
    n = len(buffers)
    _HDR.pack_into(mv, 0, MAGIC, n, len(data))
    off = _HDR.size
    for b in buffers:
        struct.pack_into("<Q", mv, off, b.raw().nbytes)
        off += 8
    mv[off : off + len(data)] = data
    off = _pad(off + len(data))
    for b in buffers:
        raw = b.raw()
        nb = raw.nbytes
        mv[off : off + nb] = raw.cast("B") if raw.format != "B" or raw.ndim != 1 else raw
        off = _pad(off + nb)
    return off


def read_from(mv: memoryview) -> Any:
    """Reconstructs an object from a store buffer.

    Out-of-band buffers are zero-copy views into ``mv`` — the caller must
    keep the backing segment alive as long as the value (the object store
    client pins segments per ref).
    """
    magic, n, plen = _HDR.unpack_from(mv, 0)
    if magic != MAGIC:
        raise ValueError("corrupt object buffer (bad magic)")
    off = _HDR.size
    sizes = []
    for _ in range(n):
        (sz,) = struct.unpack_from("<Q", mv, off)
        sizes.append(sz)
        off += 8
    data = bytes(mv[off : off + plen])
    off = _pad(off + plen)
    bufs = []
    for sz in sizes:
        bufs.append(mv[off : off + sz])
        off = _pad(off + sz)
    return pickle.loads(data, buffers=bufs)


def dumps_oob(value: Any) -> bytes:
    """One-shot serialize to a contiguous bytes blob (for pipe transport)."""
    data, buffers = serialize(value)
    size = serialized_size(data, buffers)
    out = bytearray(size)
    write_into(memoryview(out), data, buffers)
    return bytes(out)


def loads_oob(blob: bytes) -> Any:
    return read_from(memoryview(blob))
