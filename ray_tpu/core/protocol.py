"""The checked-in wire-protocol catalog (ISSUE 15).

Every message vocabulary the runtime speaks across a process boundary,
in one place. This module is pure data — stdlib-only, importable from
anywhere (including the jax-free graftlint engine, which *parses* it
rather than importing it so a lint run never triggers the ray_tpu
package import).

graftlint's ``protocol`` rule family extracts the actual vocabulary from
the senders and dispatch arms in the tree and fails on any drift from
this catalog — a send without a handler, a handler without a sender, or
an op missing here. The catalog is therefore the review surface for
wire-protocol changes: a new cast/RPC/topic lands as a diff hunk in THIS
file alongside its sender and handler, the same way a new failpoint
lands in util/failpoints.py's Sites block.

Framing note: the vocabularies below ride the framed pickle pipe
(``native/pipe.cc``: raw-pickle | ``RTB1`` batch | ``RTP1`` packed
refpin frames) between driver and workers, and the length-prefixed
RPC plane (``cluster/rpc.py``) for GCS and peer traffic. The binary
frame magics are part of the native plane's contract, tested by
``native/pipe_stress.cc`` and tests/test_native_pipe.py.
"""

from __future__ import annotations

# ---------------------------------------------------------------------------
# worker <-> driver pipe (core/worker.py <-> core/runtime.py)
# ---------------------------------------------------------------------------

#: top-level frame kinds a worker ships to the driver
#: (``Runtime._reader_loop`` / ``_native_reader_loop`` / ``_handle_msg``
#: dispatch; ``hello`` is consumed by ``_accept_loop`` before the reader
#: starts; ``batch`` wraps a coalesced list of the others)
PIPE_WORKER_MSGS = frozenset({
    "hello", "ready", "done", "cast", "req", "batch",
})

#: top-level message kinds the driver ships to a worker
#: (``Worker._dispatch_recv`` arms)
PIPE_DRIVER_MSGS = frozenset({
    "exec", "cancel", "reply", "fp", "trace", "prof", "events",
    "stackdump", "shutdown",
})

#: fire-and-forget worker->driver casts: ``("cast", op, args)``
#: (``Worker.cast`` senders -> ``Runtime._handle_cast`` arms)
PIPE_CASTS = frozenset({
    "put", "submit", "actor_call", "fn_put", "blocked", "unblocked",
    "kill_actor", "cancel", "stream_consumed", "refpins", "metrics",
    "spans", "prof", "stacks", "free", "events", "device",
})

#: request/reply worker->driver ops: ``("req", req_id, op, args)``
#: (``Worker.request`` senders -> ``Runtime._handle_req`` arms)
PIPE_REQS = frozenset({
    "get", "wait", "stream_permit", "reconstruct", "fn_get",
    "actor_create", "name_lookup", "kv", "actor_depths", "resources",
    "nodes", "pg_create", "pg_remove",
})

# ---------------------------------------------------------------------------
# GCS RPC (cluster/gcs_server.py ``rpc_*`` methods)
# ---------------------------------------------------------------------------

GCS_RPC = frozenset({
    # node lifecycle
    "node_register", "node_heartbeat", "node_list", "node_drain",
    # object directory
    "obj_ready", "obj_error", "obj_pin", "obj_unpin", "obj_info",
    "obj_state", "obj_list", "obj_drop", "obj_forget_location",
    # observability planes
    "task_events", "task_events_get", "trace_events", "trace_events_get",
    "profile_events", "profile_events_get", "stack_request",
    "stack_reply", "stack_collect", "metrics_get",
    "lifecycle_events", "lifecycle_events_get", "log_request",
    "log_reply", "log_collect", "device_report", "device_report_get",
    # kv + function store
    "kv_put", "kv_get", "kv_del", "kv_keys", "fn_put", "fn_get",
    # actors
    "actor_register", "actor_update", "actor_get", "actor_lookup",
    "actor_list",
    # placement groups
    "pg_register", "pg_get", "pg_update_assignment", "pg_remove",
    "pg_list",
    # pubsub + chaos + liveness
    "subscribe", "publish", "ping", "fp_arm", "fp_disarm",
})

#: dynamic dispatch prefixes: ``gcs.call("kv_" + op, ...)`` in
#: cluster/adapter.py reaches every ``kv_*`` method without a literal
#: sender per method — catalog entries matching a prefix here are exempt
#: from the literal-sender completeness check
GCS_RPC_DYNAMIC_PREFIXES = ("kv_",)

# ---------------------------------------------------------------------------
# peer (node-daemon <-> node-daemon) RPC (cluster/adapter.py
# ``_serve_peer`` arms)
# ---------------------------------------------------------------------------

PEER_RPC = frozenset({
    "submit_spec", "submit_actor_spec", "pull_object", "pull_chunk",
    "bcast_fetch", "stream_consumed", "kill_actor", "cancel_task",
    "pg_prepare", "pg_commit", "pg_abort", "pg_release", "ping",
})

# ---------------------------------------------------------------------------
# pubsub topics (published via ``GcsServer._publish`` / the ``publish``
# RPC; subscribed in cluster/adapter.py)
# ---------------------------------------------------------------------------

PUBSUB_CHANNELS = frozenset({
    "nodes", "objects", "pgs", "failpoints", "tracing", "profiling",
    "events",
})
