"""TPU topology detection and visibility control.

Behavior modeled on the reference's ``python/ray/_private/accelerators/
tpu.py:75`` (``TPUAcceleratorManager``): chip autodetection via
``/dev/accel*`` or ``/dev/vfio`` (:100-120), ``TPU_VISIBLE_CHIPS`` +
``TPU_CHIPS_PER_HOST_BOUNDS`` + ``TPU_HOST_BOUNDS`` for 1/2/4-chip subsets
(:157-196), pod-type detection from GKE env vars or the GCE metadata server
(:198-229), and pod-slice head resources (:335-398). All environment probes
go through an injectable provider so pod logic is unit-testable on CPU
(mirrors the reference's mock strategy in ``tests/accelerators/test_tpu.py``).
"""

from __future__ import annotations

import glob
import logging
import os
import re
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)

TPU_RESOURCE_NAME = "TPU"
NOSET_TPU_VISIBLE_CHIPS_ENV = "RTPU_EXPERIMENTAL_NOSET_TPU_VISIBLE_CHIPS"
TPU_VISIBLE_CHIPS_ENV = "TPU_VISIBLE_CHIPS"
TPU_CHIPS_PER_HOST_BOUNDS_ENV = "TPU_CHIPS_PER_HOST_BOUNDS"
TPU_HOST_BOUNDS_ENV = "TPU_HOST_BOUNDS"

# Valid chip-subset sizes per host (reference tpu.py:13).
TPU_VALID_CHIP_OPTIONS = (1, 2, 4)

_BOUNDS_FOR_CHIPS = {1: "1,1,1", 2: "1,2,1", 4: "2,2,1"}
_SINGLE_HOST_BOUNDS = "1,1,1"

GKE_TPU_ACCELERATOR_ENV = "TPU_ACCELERATOR_TYPE"
GKE_TPU_WORKER_ID_ENV = "TPU_WORKER_ID"
GCE_METADATA_URL = "http://metadata.google.internal/computeMetadata/v1/instance/attributes"


class TpuTopologyProvider:
    """Injectable environment probe (fake it in tests)."""

    def list_accel_devices(self) -> List[str]:
        return glob.glob("/dev/accel*") or glob.glob("/dev/vfio/*")

    def jax_local_chip_count(self) -> int:
        # Only trust a live jax backend if the process ALREADY initialized
        # one — calling jax.devices() here would cold-start the TPU runtime
        # (tens of seconds) as a side effect of ray_tpu.init().
        import sys

        xb = sys.modules.get("jax._src.xla_bridge")
        if xb is None or not getattr(xb, "_backends", None):
            return 0
        try:
            import jax

            devs = [d for d in jax.devices() if "tpu" in d.platform.lower() or "TPU" in str(d)]
            return len(devs)
        except Exception:
            return 0

    def gke_accelerator_type(self) -> Optional[str]:
        return os.environ.get(GKE_TPU_ACCELERATOR_ENV)

    def gce_metadata(self, key: str) -> Optional[str]:
        try:
            import urllib.request

            req = urllib.request.Request(
                f"{GCE_METADATA_URL}/{key}", headers={"Metadata-Flavor": "Google"}
            )
            with urllib.request.urlopen(req, timeout=1) as resp:
                return resp.read().decode()
        except Exception:
            return None

    def worker_id(self) -> int:
        wid = os.environ.get(GKE_TPU_WORKER_ID_ENV)
        if wid is not None:
            return int(wid)
        v = self.gce_metadata("agent-worker-number")
        return int(v) if v is not None else 0


_default_provider = TpuTopologyProvider()


def detect_num_tpu_chips(provider: Optional[TpuTopologyProvider] = None) -> int:
    """Number of TPU chips attached to this host (0 if none)."""
    p = provider or _default_provider
    visible = os.environ.get(TPU_VISIBLE_CHIPS_ENV)
    if visible is not None:
        return len([c for c in visible.split(",") if c])
    n = len(p.list_accel_devices())
    if n:
        return n
    return p.jax_local_chip_count()


def is_valid_chip_count(n: int) -> bool:
    return n in TPU_VALID_CHIP_OPTIONS


class TPUAcceleratorManager:
    """Accelerator plugin for TPU (reference ABC:
    ``_private/accelerators/accelerator.py``)."""

    def __init__(self, provider: Optional[TpuTopologyProvider] = None):
        self.provider = provider or _default_provider

    @staticmethod
    def get_resource_name() -> str:
        return TPU_RESOURCE_NAME

    def get_current_node_num_accelerators(self) -> int:
        return detect_num_tpu_chips(self.provider)

    def get_current_node_accelerator_type(self) -> Optional[str]:
        """Pod type like ``v5litepod-16`` (reference tpu.py:198-229)."""
        accel = self.provider.gke_accelerator_type()
        if accel is None:
            accel = self.provider.gce_metadata("accelerator-type")
        if accel is None:
            return None
        accel = accel.strip()
        if self._is_valid_pod_type(accel):
            return accel
        return None

    @staticmethod
    def _is_valid_pod_type(s: str) -> bool:
        return re.fullmatch(r"v\d+[a-z]*(pod)?-\d+", s) is not None

    def set_current_process_visible_accelerator_ids(self, ids: List[str]) -> None:
        """Restrict this process to a chip subset via env vars
        (reference tpu.py:157-196)."""
        if os.environ.get(NOSET_TPU_VISIBLE_CHIPS_ENV):
            return
        n = len(ids)
        if not is_valid_chip_count(n):
            logger.warning(
                "TPU chip subset size %d invalid (must be one of %s); "
                "not setting visibility env vars",
                n,
                TPU_VALID_CHIP_OPTIONS,
            )
            return
        os.environ[TPU_VISIBLE_CHIPS_ENV] = ",".join(str(i) for i in ids)
        if n in (1, 2):
            os.environ[TPU_CHIPS_PER_HOST_BOUNDS_ENV] = _BOUNDS_FOR_CHIPS[n]
            os.environ[TPU_HOST_BOUNDS_ENV] = _SINGLE_HOST_BOUNDS
        elif n == 4:
            # A whole host's worth of chips: clear subset bounds.
            os.environ[TPU_CHIPS_PER_HOST_BOUNDS_ENV] = _BOUNDS_FOR_CHIPS[4]
            os.environ[TPU_HOST_BOUNDS_ENV] = _SINGLE_HOST_BOUNDS

    def get_current_pod_name(self) -> Optional[str]:
        """Unique name of the TPU pod slice this host belongs to."""
        name = os.environ.get("TPU_NAME")
        if name is None:
            name = self.provider.gce_metadata("instance-id")
        return name

    def get_current_pod_worker_count(self) -> Optional[int]:
        """Hosts in this pod slice (reference tpu.py:274-287):
        v2-v4: 8 cores per host → chips = cores/2, 4 chips/host;
        v5e/v5p/v6e+: count directly in chips, 4 (v5e) or 8 chips/host."""
        pod_type = self.get_current_node_accelerator_type()
        if pod_type is None:
            return None
        gen, size = self._parse_pod_type(pod_type)
        if gen is None:
            return None
        if gen in ("v2", "v3", "v4"):
            chips = size // 2  # size counts TensorCores
            return max(1, chips // 4)
        # v5e and later: size counts chips. v5litepod (v5e) = 4 chips/host;
        # v5p/v6e = 8 chips/host (note: v5e pod types are spelled
        # "v5litepod-N", so gen is "v5" with "lite" in the pod type).
        chips_per_host = 4 if "lite" in pod_type else 8
        return max(1, size // chips_per_host)

    @staticmethod
    def _parse_pod_type(pod_type: str):
        m = re.fullmatch(r"(v\d+[a-z]*?)(?:pod|litepod)?-(\d+)", pod_type)
        if not m:
            return None, None
        return m.group(1), int(m.group(2))

    def get_extra_resources(self) -> Dict[str, float]:
        """Pod-slice resources (reference tpu.py:335-398): every host in a
        slice carries ``{pod_name: 1}``; worker 0 additionally carries
        ``{"TPU-<pod_type>-head": 1}`` so a driver can target the head and
        fan one task out per host."""
        out: Dict[str, float] = {}
        pod_type = self.get_current_node_accelerator_type()
        pod_name = self.get_current_pod_name()
        if pod_name:
            out[pod_name] = 1.0
        if pod_type and self.provider.worker_id() == 0:
            out[pod_head_resource(pod_type)] = 1.0
        return out


def pod_head_resource(pod_type: str) -> str:
    """The head-marker resource name for a pod type (single source of the
    string both the advertiser and schedulers target)."""
    return f"TPU-{pod_type}-head"
