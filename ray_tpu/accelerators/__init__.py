from ray_tpu.accelerators.tpu import TPUAcceleratorManager

__all__ = ["TPUAcceleratorManager"]
