"""Ring attention: sequence-parallel attention over an ICI ring.

Absent from the reference (SURVEY §5: no ring attention / context parallel
anywhere in Ray) — this is the TPU-native design for long context: shard the
sequence dim over the ``sp`` mesh axis, keep Q local, and rotate K/V blocks
around the ring with ``lax.ppermute`` (ICI neighbor transfers), accumulating
attention with the online-softmax update so each step is a flash-attention
block step. Communication overlaps compute: XLA schedules the permute of
step i+1 concurrently with the attention of step i.

Used inside ``shard_map`` (or a pjit program with manual axes). Inputs are
the *local* shards ``[B, L/sp, H, D]``.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import Array, lax

from ray_tpu.ops.attention import NEG_INF, _attend_block, _repeat_kv
from ray_tpu.parallel.ops import ring_permute


def ring_attention(
    q: Array,
    k: Array,
    v: Array,
    *,
    axis: str = "sp",
    causal: bool = True,
    scale: Optional[float] = None,
    softcap: float = 0.0,
) -> Array:
    """Sequence-parallel attention; call inside shard_map over ``axis``.

    q/k/v: local shards [B, Lloc, H(k), D] where global L = Lloc * sp.
    Returns the local output shard [B, Lloc, H, D].
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    k = _repeat_kv(k, q.shape[2])
    v = _repeat_kv(v, q.shape[2])

    sp = lax.axis_size(axis)
    my = lax.axis_index(axis)
    b, lloc, h, d = q.shape

    qf = q.astype(jnp.float32)
    q_pos = my * lloc + jnp.arange(lloc)  # global positions of local queries

    def attend(kb, vb, i, m, l, o):
        # After i forward shifts we hold the block that originated on device
        # (my - i) mod sp; mask by global positions.
        mask = None
        if causal:
            src = (my - i) % sp
            k_pos = src * lloc + jnp.arange(lloc)
            mask = q_pos[:, None] >= k_pos[None, :]
        return _attend_block(
            qf, kb.astype(jnp.float32), vb.astype(jnp.float32), m, l, o,
            mask, scale, softcap
        )

    def step(carry, i):
        kb, vb, m, l, o = carry
        m, l, o = attend(kb, vb, i, m, l, o)
        kb = ring_permute(kb, axis)
        vb = ring_permute(vb, axis)
        return (kb, vb, m, l, o), None

    m0 = jnp.full((b, h, lloc), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, lloc), jnp.float32)
    o0 = jnp.zeros((b, lloc, h, d), jnp.float32)
    # First ring step runs outside the scan so the carry enters already
    # sp-varying (the accumulators depend on axis_index); the last step runs
    # outside too, so the scan body's trailing permute is never wasted.
    m, l, o = attend(k, v, 0, m0, l0, o0)
    if sp > 1:
        kb = ring_permute(k, axis)
        vb = ring_permute(v, axis)
        (kb, vb, m, l, o), _ = lax.scan(
            step, (kb, vb, m, l, o), jnp.arange(1, sp - 1)
        )
        m, l, o = attend(kb, vb, sp - 1, m, l, o)
    l = jnp.where(l == 0.0, 1.0, l)  # rows with no visible keys
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def sliding_window_attention_sp(
    q: Array,
    k: Array,
    v: Array,
    *,
    axis: str = "sp",
    window: int,
    scale: Optional[float] = None,
    q_block: int = 512,
    kv_block: int = 512,
    softcap: float = 0.0,
) -> Array:
    """Sequence-parallel SLIDING-WINDOW attention via halo exchange.

    Call inside ``shard_map`` over ``axis``. Because a window that fits in
    one shard (``window <= Lloc``) only ever reaches into the PREVIOUS
    shard's keys, one ``ppermute`` of the neighbor shard replaces the full
    ring rotation ring attention needs — O(1) communication steps instead
    of O(sp), the whole point of SWA at long context. Runs through the
    positional memory-efficient custom VJP (O(L) residuals), so it is
    safe to differentiate in a scanned-layer model.

    A window wider than one shard (``window > Lloc``) needs keys from
    ``H = ceil(window / Lloc)`` previous shards: the halo is gathered by
    H chained ppermutes (hop j carries shard ``i-j``'s keys), still
    O(window / Lloc) communication — independent of sp, vs ring
    attention's O(sp) rotation of the full sequence.

    Shard 0's halo arrives from the LAST shards (ppermute wraps); their
    keys get negative global positions and are masked, never attended.
    H clamps to sp-1 (every other shard exactly once): the band mask
    enforces the window from positions, so ANY window is handled —
    at H = sp-1 the traffic degenerates to all-gather shape and ring
    attention becomes the better schedule, but results stay exact.
    """
    from ray_tpu.ops.attention import _mha_pos

    scale = scale if scale is not None else q.shape[-1] ** -0.5
    sp = lax.axis_size(axis)
    my = lax.axis_index(axis)
    b, lloc, h, d = q.shape

    # ceil: previous shards the band reaches, capped at all-of-them
    hops = min(-(-window // lloc), sp - 1)

    perm = [(i, (i + 1) % sp) for i in range(sp)]
    halos_k, halos_v = [], []      # hop j (1-based) = shard i-j's k/v
    hk, hv = k, v
    for _ in range(hops):
        hk = lax.ppermute(hk, axis, perm)
        hv = lax.ppermute(hv, axis, perm)
        halos_k.append(hk)
        halos_v.append(hv)
    # farthest hop first so concatenated positions ascend
    k_all = jnp.concatenate(halos_k[::-1] + [k], axis=1)
    v_all = jnp.concatenate(halos_v[::-1] + [v], axis=1)

    start = my * lloc
    qpos = (start + jnp.arange(lloc)).astype(jnp.float32)
    kpos = ((start - hops * lloc)
            + jnp.arange((hops + 1) * lloc)).astype(jnp.float32)

    bq = min(q_block, lloc)
    bk = min(kv_block, lloc)  # divides both Lloc and (hops+1)*Lloc
    if lloc % bq or lloc % bk:
        bq = bk = lloc
    # pos_delta = qpos[0] - kpos[0] = hops*Lloc (STATIC): keeps the
    # windowed live-kv-block slicing so the band costs O(Lloc*window),
    # not dense
    return _mha_pos(q, k_all, v_all, qpos, kpos, scale, bq, bk, window,
                    hops * lloc, softcap)
