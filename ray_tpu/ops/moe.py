"""Mixture-of-experts: top-k routing + GShard-style dense dispatch.

Absent from the reference (SURVEY §2.4: expert parallel = "absent"). The
TPU-native formulation keeps everything as static-shape einsums so the MXU
does the dispatch: tokens are routed into a [experts, capacity] buffer with
one-hot dispatch/combine tensors (Switch/GShard style) rather than gather/
scatter, and expert parallelism is one ``all_to_all`` over the ``ep`` mesh
axis when the expert dim is sharded.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.parallel.sharding import constrain


class RouterOutput(NamedTuple):
    dispatch: jax.Array      # [tokens, experts, capacity] one-hot-ish f32
    combine: jax.Array       # [tokens, experts, capacity] weights
    aux_loss: jax.Array      # load-balancing loss (scalar)


def top_k_router(
    logits: jax.Array,
    *,
    num_experts: int,
    k: int = 2,
    capacity_factor: float = 1.25,
) -> RouterOutput:
    """Route tokens to top-k experts with a fixed per-expert capacity.

    ``logits``: [tokens, experts]. Tokens over capacity are dropped (their
    combine weight is zero) — standard Switch behavior, keeps shapes static.
    """
    tokens = logits.shape[0]
    capacity = max(1, int(capacity_factor * tokens * k / num_experts))

    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    # load-balancing aux loss (Switch eq. 4): E * sum_e f_e * p_e
    _, top_idx = jax.lax.top_k(probs, k)  # [tokens, k]

    dispatch = jnp.zeros((tokens, num_experts, capacity), jnp.float32)
    combine = jnp.zeros((tokens, num_experts, capacity), jnp.float32)
    # Fill choices sequentially so earlier-choice tokens win capacity slots.
    position_in_expert = jnp.zeros((num_experts,), jnp.int32)
    for choice in range(k):
        idx = top_idx[:, choice]                       # [tokens]
        onehot = jax.nn.one_hot(idx, num_experts)      # [tokens, experts]
        # position of each token within its expert's queue for this choice
        pos = jnp.cumsum(onehot, axis=0) - 1 + position_in_expert[None, :]
        position_in_expert = position_in_expert + jnp.sum(
            onehot, axis=0
        ).astype(jnp.int32)
        pos_tok = jnp.sum(pos * onehot, axis=1).astype(jnp.int32)  # [tokens]
        in_cap = pos_tok < capacity
        gate = jnp.sum(probs * onehot, axis=1) * in_cap            # [tokens]
        slot = jax.nn.one_hot(pos_tok, capacity) * in_cap[:, None]
        dispatch = dispatch + onehot[:, :, None] * slot[:, None, :]
        combine = combine + gate[:, None, None] * onehot[:, :, None] * slot[:, None, :]

    frac_routed = jnp.mean(
        jax.nn.one_hot(top_idx[:, 0], num_experts), axis=0
    )
    mean_prob = jnp.mean(probs, axis=0)
    aux_loss = num_experts * jnp.sum(frac_routed * mean_prob)
    return RouterOutput(dispatch, combine, aux_loss)


def moe_layer_dense(
    x: jax.Array,
    router_w: jax.Array,
    w_gate: jax.Array,
    w_up: jax.Array,
    w_down: jax.Array,
    *,
    k: int = 2,
    capacity_factor: float = 1.25,
) -> Tuple[jax.Array, jax.Array]:
    """MoE SwiGLU block. x: [B, L, D]; expert weights: [E, D, F] / [E, F, D].

    Returns (output [B, L, D], aux_loss). Einsum-only dispatch — with the E
    dim sharded on the ``ep`` mesh axis, XLA inserts the all_to_all pair.
    """
    b, l, d = x.shape
    e = w_gate.shape[0]
    # Pin the flattened token dim to "tokens" = (dp, fsdp, sp). Without
    # this, the combine output inherits D:fsdp from w_down and the caller's
    # activation-layout constraint forces the SPMD partitioner into an
    # involuntary full rematerialization (MULTICHIP_r02). The layout
    # matches (batch, seq) exactly when sp == 1 or the per-device batch
    # block is 1; otherwise entry/exit cost one all-to-all — still far
    # cheaper than replicating the tensor, and of a piece with the
    # all-to-alls MoE dispatch does anyway under real expert parallelism.
    xt = constrain(x.reshape(b * l, d), ("tokens", None))
    logits = xt.astype(jnp.float32) @ router_w.astype(jnp.float32)  # [T, E]
    route = top_k_router(logits, num_experts=e, k=k, capacity_factor=capacity_factor)
    # [T, E, C] x [T, D] -> [E, C, D]
    expert_in = jnp.einsum("tec,td->ecd", route.dispatch, xt.astype(jnp.float32))
    gate = jax.nn.silu(jnp.einsum("ecd,edf->ecf", expert_in, w_gate.astype(jnp.float32)))
    up = jnp.einsum("ecd,edf->ecf", expert_in, w_up.astype(jnp.float32))
    expert_out = jnp.einsum("ecf,efd->ecd", gate * up, w_down.astype(jnp.float32))
    out = jnp.einsum("tec,ecd->td", route.combine, expert_out)
    out = constrain(out, ("tokens", None))
    return out.reshape(b, l, d).astype(x.dtype), route.aux_loss
