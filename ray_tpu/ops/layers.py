"""Transformer layer primitives: RMSNorm, rotary embeddings, SwiGLU.

Pure functions over arrays — fusion into surrounding matmuls is left to XLA
(the right call on TPU: these are bandwidth-bound elementwise ops that XLA
fuses into the adjacent MXU ops automatically).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def rms_norm(x: jax.Array, weight: jax.Array, *, eps: float = 1e-6) -> jax.Array:
    """RMSNorm with float32 accumulation regardless of input dtype."""
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    normed = xf * jax.lax.rsqrt(var + eps)
    return (normed * weight.astype(jnp.float32)).astype(x.dtype)


def rotary_embedding(
    positions: jax.Array, head_dim: int, *, theta: float = 10000.0
) -> Tuple[jax.Array, jax.Array]:
    """cos/sin tables for RoPE. positions: [..., L] int; returns [..., L, D/2]."""
    freqs = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    angles = positions.astype(jnp.float32)[..., None] * freqs
    return jnp.cos(angles), jnp.sin(angles)


def apply_rotary(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Apply RoPE. x: [B, L, H, D]; cos/sin: [B, L, D/2] or [L, D/2]."""
    if cos.ndim == 2:
        cos = cos[None]
        sin = sin[None]
    cos = cos[:, :, None, :]  # [B, L, 1, D/2]
    sin = sin[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def swiglu(x: jax.Array, w_gate: jax.Array, w_up: jax.Array, w_down: jax.Array) -> jax.Array:
    """SwiGLU MLP: silu(x @ w_gate) * (x @ w_up) @ w_down."""
    gate = jax.nn.silu(x @ w_gate)
    return (gate * (x @ w_up)) @ w_down
