"""Transformer layer primitives: RMSNorm, rotary embeddings, SwiGLU.

Pure functions over arrays — fusion into surrounding matmuls is left to XLA
(the right call on TPU: these are bandwidth-bound elementwise ops that XLA
fuses into the adjacent MXU ops automatically).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp


def rms_norm(x: jax.Array, weight: jax.Array, *, eps: float = 1e-6) -> jax.Array:
    """RMSNorm with float32 accumulation regardless of input dtype.

    Carries a custom VJP that saves ONLY the low-precision ``x`` and
    ``weight`` as residuals and recomputes the f32 statistics in the
    backward pass. Plain autodiff of the f32 upcast saves f32 copies of
    the [B, L, D] intermediates per norm site (the `f32[12,16,2048,1024]`
    residuals in the round-4 HBM OOM dump); because bf16→f32 casting is
    exact, the recomputation is bit-identical to what autodiff would have
    used, at ~1/6 the residual bytes. This is what makes low/no-remat
    training fit HBM.
    """
    return _rms_norm_vjp(x, weight, eps)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _rms_norm_vjp(x, weight, eps):
    return _rms_norm_fwd_math(x, weight, eps)


def _rms_norm_fwd_math(x, weight, eps):
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    normed = xf * jax.lax.rsqrt(var + eps)
    return (normed * weight.astype(jnp.float32)).astype(x.dtype)


def _rms_norm_fwd(x, weight, eps):
    return _rms_norm_fwd_math(x, weight, eps), (x, weight)


def _rms_norm_bwd(eps, res, g):
    x, weight = res
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    wf = weight.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    r = jax.lax.rsqrt(var + eps)
    n = xf * r  # normalized (pre-weight) activations
    gw = gf * wf
    dx = r * gw - (r ** 3) * xf * jnp.mean(gw * xf, axis=-1, keepdims=True)
    dw = (gf * n).sum(axis=tuple(range(x.ndim - 1)))
    return dx.astype(x.dtype), dw.astype(weight.dtype)


_rms_norm_vjp.defvjp(_rms_norm_fwd, _rms_norm_bwd)


def layer_norm(
    x: jax.Array,
    weight: jax.Array,
    bias: jax.Array | None = None,
    *,
    eps: float = 1e-5,
) -> jax.Array:
    """LayerNorm with f32 accumulation and bf16-residual custom VJP.

    Same residual-size rationale as :func:`rms_norm`: saves only the
    low-precision ``x``/``weight`` and recomputes the exact f32
    mean/variance in the backward pass.
    """
    return _layer_norm_vjp(x, weight, bias, eps)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _layer_norm_vjp(x, weight, bias, eps):
    return _layer_norm_fwd_math(x, weight, bias, eps)


def _layer_norm_fwd_math(x, weight, bias, eps):
    xf = x.astype(jnp.float32)
    mu = xf.mean(axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mu), axis=-1, keepdims=True)
    out = (xf - mu) * jax.lax.rsqrt(var + eps) * weight.astype(jnp.float32)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(x.dtype)


def _layer_norm_fwd(x, weight, bias, eps):
    # bias rides along only for its presence/dtype (None is pytree
    # structure, so the branch below is static under jit)
    return _layer_norm_fwd_math(x, weight, bias, eps), (x, weight, bias)


def _layer_norm_bwd(eps, res, g):
    x, weight, bias = res
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    wf = weight.astype(jnp.float32)
    mu = xf.mean(axis=-1, keepdims=True)
    c = xf - mu
    var = jnp.mean(jnp.square(c), axis=-1, keepdims=True)
    r = jax.lax.rsqrt(var + eps)
    n = c * r
    gw = gf * wf
    dx = r * (
        gw
        - gw.mean(axis=-1, keepdims=True)
        - n * jnp.mean(gw * n, axis=-1, keepdims=True)
    )
    batch_axes = tuple(range(x.ndim - 1))
    dw = (gf * n).sum(axis=batch_axes)
    db = (gf.sum(axis=batch_axes).astype(bias.dtype)
          if bias is not None else None)
    return dx.astype(x.dtype), dw.astype(weight.dtype), db


_layer_norm_vjp.defvjp(_layer_norm_fwd, _layer_norm_bwd)


def rotary_embedding(
    positions: jax.Array, head_dim: int, *, theta: float = 10000.0
) -> Tuple[jax.Array, jax.Array]:
    """cos/sin tables for RoPE. positions: [..., L] int; returns [..., L, D/2]."""
    freqs = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    angles = positions.astype(jnp.float32)[..., None] * freqs
    return jnp.cos(angles), jnp.sin(angles)


def apply_rotary(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Apply RoPE. x: [B, L, H, D]; cos/sin: [B, L, D/2] or [L, D/2].

    Custom VJP: a rotation's backward is the inverse rotation, so only
    the tiny cos/sin tables are residuals. Plain autodiff keeps f32
    copies of the split halves of every rotated q and k (≈3 GB/step for
    a 12-layer model at batch 16 × 2048) for the multiply backwards.

    CONTRACT: ``cos``/``sin`` are non-differentiable position tables —
    their cotangents are always zero. A learned-rotary variant (trainable
    theta, position-interpolation scale) must NOT route gradients through
    this function.
    """
    return _apply_rotary_vjp(x, cos, sin)


@jax.custom_vjp
def _apply_rotary_vjp(x, cos, sin):
    return _rotate(x, cos, sin, +1.0)


def _rotate(x, cos, sin, sign):
    if cos.ndim == 2:
        cos = cos[None]
        sin = sin[None]
    cos = cos[:, :, None, :]  # [B, L, 1, D/2]
    sin = sin[:, :, None, :] * sign
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def _apply_rotary_fwd(x, cos, sin):
    return _rotate(x, cos, sin, +1.0), (cos, sin)


def _apply_rotary_bwd(res, g):
    cos, sin = res
    # cos/sin are non-differentiable tables (built from integer
    # positions); rotate the cotangent by the inverse angle. g carries
    # the primal output's dtype, which _rotate preserves.
    return (_rotate(g, cos, sin, -1.0),
            jnp.zeros_like(cos), jnp.zeros_like(sin))


_apply_rotary_vjp.defvjp(_apply_rotary_fwd, _apply_rotary_bwd)


def swiglu(x: jax.Array, w_gate: jax.Array, w_up: jax.Array, w_down: jax.Array) -> jax.Array:
    """SwiGLU MLP: silu(x @ w_gate) * (x @ w_up) @ w_down."""
    gate = jax.nn.silu(x @ w_gate)
    return (gate * (x @ w_up)) @ w_down
