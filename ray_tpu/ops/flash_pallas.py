"""Pallas TPU flash-attention kernel (causal + full), with GQA support.

Hand-tiled version of :func:`ray_tpu.ops.attention.blockwise_attention`:
grid ``(batch, heads, q_blocks, kv_blocks)`` where the kv dimension is
sequential ("arbitrary") and carries the online-softmax state in VMEM
scratch; batch/head/q dims are parallel. Causal skips fully-masked kv
blocks via predication, so the kernel does ~half the FLOPs of full
attention at long context.

Layout: the wrapper transposes to ``[B, H, L, D]`` so the last two dims of
every block are (seq_block, head_dim) — MXU/VPU tile friendly.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ray_tpu.ops.attention import (_softcap_dfactor as _softcap_dfac,
                                   _softcap_scores as _softcap_fwd)

NEG_INF = -1e30
LANES = 128  # running max / denom stored broadcast over one lane tile


def _block_band(qi, ki, block_q: int, block_k: int, causal: bool, window):
    """(live, band) for one (q block, kv block) pair — the ONE in-kernel
    definition of the causal/sliding-window band, shared by the forward
    and both backward kernels so their masking can never diverge.

    ``live``: the block intersects the band at all (predication skips the
    whole tile otherwise). ``band``: [bq, bk] bool, or None when unmasked.
    """
    live = True
    if causal:
        live = ki * block_k <= (qi + 1) * block_q - 1
    if window:
        live &= qi * block_q - ((ki + 1) * block_k - 1) < window
    if not (causal or window):
        return live, None
    rows = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    cols = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    band = rows >= cols
    if window:
        band &= rows - cols < window
    return live, band


def _require_causal_window(causal: bool, window) -> None:
    if window and not causal:
        raise ValueError("window requires causal attention")
    if window is not None and window <= 0:
        raise ValueError(f"window must be positive, got {window}")


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                  *, causal: bool, scale: float, block_q: int, block_k: int,
                  window=None, softcap: float = 0.0):
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    live, band = _block_band(qi, ki, block_q, block_k, causal, window)

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)          # [bq, d]
        k = k_ref[0, 0].astype(jnp.float32)          # [bk, d]
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                    # [bq, bk]
        s = _softcap_fwd(s, softcap)
        if band is not None:
            s = jnp.where(band, s, NEG_INF)
        m_prev = m_ref[:, 0:1]                       # [bq, 1]
        l_prev = l_ref[:, 0:1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)   # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)              # [bq, 1]
        p = jnp.exp(s - m_new)                       # [bq, bk]
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_ref[:, 0:1]
        l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows -> 0 output
        o_ref[0, 0] = (acc_ref[:] / l).astype(o_ref.dtype)
        # log-sum-exp per query row — the only residual (besides o) the
        # memory-efficient backward needs. Broadcast across the lane dim:
        # Mosaic requires output block last-two-dims (8,128)-tileable, so
        # the block is [block_q, LANES] and the wrapper slices lane 0.
        lse_ref[0, 0] = m_ref[:] + jnp.log(jnp.where(l_ref[:] == 0.0, 1.0,
                                                     l_ref[:]))


@functools.partial(
    jax.jit,
    static_argnames=("causal", "scale", "block_q", "block_k", "window",
                     "softcap", "interpret"),
)
def flash_attention_pallas_fwd(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    block_q: int = 512,
    block_k: int = 512,
    window: Optional[int] = None,
    softcap: float = 0.0,
    interpret: bool = False,
):
    """Flash attention forward returning ``(out, lse)``.

    ``q``: [B, Lq, H, D]; ``k``/``v``: [B, Lk, Hk, D]; ``lse``: [B, H, Lq]
    float32 log-sum-exp per query row, consumed by the memory-efficient
    backward in :mod:`ray_tpu.ops.attention`.
    """
    _require_causal_window(causal, window)
    b, lq, h, d = q.shape
    lk, hk = k.shape[1], k.shape[2]
    if h % hk:
        raise ValueError(f"q heads {h} not divisible by kv heads {hk}")
    group = h // hk
    scale = scale if scale is not None else d ** -0.5
    block_q = min(block_q, lq)
    block_k = min(block_k, lk)
    if lq % block_q or lk % block_k:
        from ray_tpu.ops.attention import _mha_fwd_blockwise, _repeat_kv

        return _mha_fwd_blockwise(q, _repeat_kv(k, h), _repeat_kv(v, h),
                                  causal, scale, lq, lk, window,
                                  softcap=softcap)
    nq, nk = lq // block_q, lk // block_k

    qt = q.transpose(0, 2, 1, 3)  # [B, H, Lq, D]
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    kernel = functools.partial(
        _flash_kernel, causal=causal, scale=scale,
        block_q=block_q, block_k=block_k, window=window, softcap=softcap,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, qi, ki: (b_, h_ // group, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, qi, ki: (b_, h_ // group, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
            pl.BlockSpec((1, 1, block_q, LANES),
                         lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, lq, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, lq, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(qt, kt, vt)
    return out.transpose(0, 2, 1, 3), lse[..., 0]


def flash_attention_pallas(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    block_q: int = 512,
    block_k: int = 512,
    window: Optional[int] = None,
    interpret: bool = False,
) -> jax.Array:
    """Forward-only flash attention (inference paths). For training, go
    through :func:`ray_tpu.ops.attention.flash_attention` which attaches
    the memory-efficient custom VJP."""
    out, _ = flash_attention_pallas_fwd(
        q, k, v, causal=causal, scale=scale,
        block_q=block_q, block_k=block_k, window=window,
        interpret=interpret)
    return out


# ---------------------------------------------------------------------------
# Backward kernels (FlashAttention-2 split: dKV sweep + dQ sweep)
# ---------------------------------------------------------------------------
#
# Residuals are (q, k, v, out, lse) — O(L). The backward recomputes p
# blockwise:  D = rowsum(dO * O);  p = exp(s - lse);  dp = dO V^T;
# ds = p * (dp - D);  dV += p^T dO;  dK += scale * ds^T Q;
# dQ += scale * ds K.  Two kernels so each output has one sequential axis:
# the dKV kernel owns a kv block and sweeps q blocks; the dQ kernel owns a
# q block and sweeps kv blocks.


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, dk_acc, dv_acc,
                          *, causal: bool, scale: float,
                          block_q: int, block_k: int, nq: int,
                          window=None, softcap: float = 0.0):
    """dK/dV sweep at NATIVE kv-head count: the sequential grid dim walks
    (group, q_block) pairs — ``t = g * nq + qi`` — so each kv head's
    gradients accumulate over every q head of its group without ever
    materializing group-expanded K/V or dK/dV (ADVICE r2 #5)."""
    ki = pl.program_id(2)
    t = pl.program_id(3)
    nt = pl.num_programs(3)
    qi = t % nq

    @pl.when(t == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    live, band = _block_band(qi, ki, block_q, block_k, causal, window)

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)           # [bq, d]
        k = k_ref[0, 0].astype(jnp.float32)           # [bk, d]
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)         # [bq, d]
        lse = lse_ref[0, 0][:, 0:1]                   # [bq, 1]
        delta = delta_ref[0, 0][:, 0:1]               # [bq, 1]
        s_hat = _softcap_fwd(
            jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale,
            softcap)
        s = s_hat
        if band is not None:
            s = jnp.where(band, s, NEG_INF)
        p = jnp.exp(s - lse)                          # [bq, bk]
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)       # p^T dO: [bk, d]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)                         # [bq, bk]
        if softcap:
            # masked entries have p = 0 already, so the factor is harmless
            ds = ds * _softcap_dfac(s_hat, softcap)
        dk_acc[:] += scale * jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)       # ds^T q: [bk, d]

    @pl.when(t == nt - 1)
    def _finish():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, dq_acc,
                         *, causal: bool, scale: float,
                         block_q: int, block_k: int, window=None,
                         softcap: float = 0.0):
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    live, band = _block_band(qi, ki, block_q, block_k, causal, window)

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, 0:1]
        delta = delta_ref[0, 0][:, 0:1]
        s_hat = _softcap_fwd(
            jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale,
            softcap)
        s = s_hat
        if band is not None:
            s = jnp.where(band, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        if softcap:
            ds = ds * _softcap_dfac(s_hat, softcap)
        dq_acc[:] += scale * jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)       # ds k: [bq, d]

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "scale", "block_q", "block_k", "window",
                     "softcap", "interpret"),
)
def flash_attention_pallas_bwd(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    out: jax.Array,
    lse: jax.Array,
    dout: jax.Array,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    block_q: int = 512,
    block_k: int = 512,
    window: Optional[int] = None,
    softcap: float = 0.0,
    interpret: bool = False,
):
    """Backward pass. ``q``/``out``/``dout``: [B, Lq, H, D]; ``k``/``v``
    may stay at their NATIVE (possibly fewer, GQA) head count [B, Lk, Hk,
    D] — dk/dv come back at that count with the per-group accumulation
    done in-kernel, so GQA pays no group-factor HBM for transients
    (ADVICE r2 #5). ``lse``: [B, H, Lq]. Returns (dq, dk, dv)."""
    _require_causal_window(causal, window)
    b, lq, h, d = q.shape
    lk, hk = k.shape[1], k.shape[2]
    if h % hk:
        raise ValueError(f"q heads {h} not divisible by kv heads {hk}")
    group = h // hk
    scale = scale if scale is not None else d ** -0.5
    block_q = min(block_q, lq)
    block_k = min(block_k, lk)
    nq, nk = lq // block_q, lk // block_k

    qt = q.transpose(0, 2, 1, 3)      # [B, H, L, D]
    kt = k.transpose(0, 2, 1, 3)      # [B, Hk, L, D]
    vt = v.transpose(0, 2, 1, 3)
    dot = dout.transpose(0, 2, 1, 3)
    outt = out.transpose(0, 2, 1, 3)
    # D = rowsum(dO * O), lane-broadcast like lse for tileable blocks
    delta = (dot.astype(jnp.float32) * outt.astype(jnp.float32)).sum(-1)
    lse_b = jnp.broadcast_to(lse[..., None], (*lse.shape, LANES))
    delta_b = jnp.broadcast_to(delta[..., None], (*delta.shape, LANES))

    # dK/dV at native kv heads: grid dim 1 walks kv heads, the sequential
    # dim walks (group, q_block) pairs t = g*nq + qi; q-side tensors index
    # the q head h_*group + t//nq
    def _qside(b_, h_, ki, t):
        return (b_, h_ * group + t // nq, t % nq, 0)

    dkv_kernel = functools.partial(
        _flash_bwd_dkv_kernel, causal=causal, scale=scale,
        block_q=block_q, block_k=block_k, nq=nq, window=window,
        softcap=softcap)
    dk_t, dv_t = pl.pallas_call(
        dkv_kernel,
        grid=(b, hk, nk, nq * group),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), _qside),
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, ki, t: (b_, h_, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, ki, t: (b_, h_, ki, 0)),
            pl.BlockSpec((1, 1, block_q, d), _qside),
            pl.BlockSpec((1, 1, block_q, LANES), _qside),
            pl.BlockSpec((1, 1, block_q, LANES), _qside),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, ki, t: (b_, h_, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, ki, t: (b_, h_, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hk, lk, d), k.dtype),
            jax.ShapeDtypeStruct((b, hk, lk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"),
        ),
        interpret=interpret,
    )(qt, kt, vt, dot, lse_b, delta_b)

    dq_kernel = functools.partial(
        _flash_bwd_dq_kernel, causal=causal, scale=scale,
        block_q=block_q, block_k=block_k, window=window, softcap=softcap)
    dq_t = pl.pallas_call(
        dq_kernel,
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
            # GQA: q head h_ reads kv head h_//group (forward's index-map trick)
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, qi, ki: (b_, h_ // group, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, qi, ki: (b_, h_ // group, ki, 0)),
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
            pl.BlockSpec((1, 1, block_q, LANES), lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
            pl.BlockSpec((1, 1, block_q, LANES), lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, lq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"),
        ),
        interpret=interpret,
    )(qt, kt, vt, dot, lse_b, delta_b)

    return (dq_t.transpose(0, 2, 1, 3), dk_t.transpose(0, 2, 1, 3),
            dv_t.transpose(0, 2, 1, 3))
