"""Attention: naive reference + blockwise (flash-style) XLA implementation.

Shapes follow the JAX convention ``[batch, seq, heads, head_dim]``. Grouped
query attention (GQA) is supported: ``k``/``v`` may have fewer heads than
``q`` as long as ``q_heads % kv_heads == 0``.

The blockwise implementation is the online-softmax algorithm (running max /
running denominator) expressed with ``lax.scan`` so XLA keeps static shapes
and can pipeline HBM→VMEM streaming; the Pallas kernel in
:mod:`ray_tpu.ops.flash_pallas` is the hand-tiled version of the same loop.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30

# Process-wide attention implementation override. "auto" dispatches Pallas on
# TPU / blockwise XLA elsewhere; bench/serving preflights may pin "xla" when
# the Pallas kernel fails to compile on the attached chip (Mosaic tiling or
# VMEM rejections surface only at real-TPU compile time). Seeded from the
# RTPU_ATTN_IMPL env var so subprocesses inherit the choice.
_ATTN_IMPL = None  # None -> consult env / auto


def set_default_attention_impl(impl: Optional[str]) -> None:
    """Pin the attention implementation: "auto" | "pallas" | "xla" | "naive".

    ``None`` resets to the default (env ``RTPU_ATTN_IMPL`` or "auto").
    Takes effect at trace time, so call before compiling the model.
    """
    global _ATTN_IMPL
    if impl is not None and impl not in ("auto", "pallas", "xla", "naive"):
        raise ValueError(f"unknown attention impl: {impl!r}")
    _ATTN_IMPL = impl


def resolve_attention_impl() -> str:
    """Concrete impl for this process/backend: "pallas" | "xla" | "naive"."""
    import os

    from ray_tpu import config

    impl = _ATTN_IMPL or config.get("attn_impl") or "auto"
    if impl == "auto":
        from ray_tpu.util.tpu_info import is_tpu_backend

        impl = "pallas" if is_tpu_backend() else "xla"
    return impl


def _band_mask(qpos, kpos, causal, window):
    """[qb, kb] visibility mask for the causal/sliding-window band, or None.

    The ONE definition shared by naive/blockwise/backward paths — forward
    and backward must never disagree on masking. ``window`` may be a
    TRACED int scalar (per-layer alternating windows ride a scanned layer
    stack in decode); ``None`` (not 0) means no window, so truthiness is
    never taken on a tracer.
    """
    if not (causal or window is not None):
        return None
    mask = qpos >= kpos
    if window is not None:
        mask &= (qpos - kpos) < window
    return mask


def _softcap_scores(s, softcap):
    """Attention-logit soft-capping (Gemma-2): ``cap * tanh(s / cap)``.
    Apply BEFORE masking — tanh(NEG_INF) would erase the mask value."""
    if not softcap:
        return s
    return softcap * jnp.tanh(s / softcap)


def _softcap_dfactor(s_hat, softcap):
    """d(capped)/d(raw) = 1 - tanh^2 = 1 - (s_hat/cap)^2, from the CAPPED
    (unmasked) score — shared by every backward recompute."""
    return 1.0 - jnp.square(s_hat / softcap)


def _repeat_kv(k: jax.Array, num_q_heads: int) -> jax.Array:
    """Expand kv heads to match q heads for GQA."""
    kv_heads = k.shape[2]
    if kv_heads == num_q_heads:
        return k
    if num_q_heads % kv_heads:
        raise ValueError(f"q heads {num_q_heads} not divisible by kv heads {kv_heads}")
    return jnp.repeat(k, num_q_heads // kv_heads, axis=2)


def naive_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    q_offset: int = 0,
    window: Optional[int] = None,
    k_offset=0,
    k_positions: Optional[jax.Array] = None,
    softcap: float = 0.0,
) -> jax.Array:
    """Materialized-scores attention; numerical reference for tests.

    ``q_offset`` shifts q's global positions (used for decode where q is a
    suffix of the kv sequence). ``window`` limits each query to the last
    ``window`` keys (sliding-window / Mistral-style local attention); it
    may be a traced int scalar (per-layer windows riding a decode scan).
    Ring KV caches position their keys explicitly: ``k_offset`` maps slot
    j to global position k_offset + j, or ``k_positions`` gives each slot
    an arbitrary global position; either way negative positions mean
    "slot not filled yet" and are masked. All three features require
    ``causal`` (they are defined in terms of the causal band).
    ``softcap`` applies Gemma-2-style tanh capping to the logits.
    """
    if isinstance(window, int) and window <= 0:
        window = None  # legacy "0 = off" callers; traced windows stay
    has_koff = (k_positions is not None
                or not (isinstance(k_offset, int) and k_offset == 0))
    if (window is not None or has_koff) and not causal:
        raise ValueError(
            "window / ring key positions require causal attention")
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    k = _repeat_kv(k, q.shape[2])
    v = _repeat_kv(v, q.shape[2])
    # [B, H, Lq, Lk]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32))
    scores = scores * scale
    scores = _softcap_scores(scores, softcap)
    if causal or window is not None or has_koff:
        lq, lk = q.shape[1], k.shape[1]
        if k_positions is not None:
            k_pos = k_positions
        else:
            k_pos = jnp.arange(lk) + k_offset
        mask = _band_mask(jnp.arange(lq)[:, None] + q_offset,
                          k_pos[None, :], causal, window)
        if has_koff:
            mask &= (k_pos >= 0)[None, :]
        scores = jnp.where(mask[None, None], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def _attend_block(q, k, v, m, l, o, mask, scale, softcap=0.0):
    """One online-softmax update: q block vs one kv block.

    q: [B, qb, H, D]; k/v: [B, kb, H, D]; m,l: [B, H, qb]; o: [B, qb, H, D];
    mask: [qb, kb] bool or None.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale  # fp32
    s = _softcap_scores(s, softcap)
    if mask is not None:
        s = jnp.where(mask[None, None], s, NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    l_new = l * alpha + p.sum(axis=-1)
    o_new = o * alpha.transpose(0, 2, 1)[..., None] + jnp.einsum(
        "bhqk,bkhd->bqhd", p, v
    )
    return m_new, l_new, o_new


def blockwise_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    q_block: int = 512,
    kv_block: int = 512,
    q_offset: int = 0,
    window: Optional[int] = None,
    softcap: float = 0.0,
) -> jax.Array:
    """Flash-style attention with online softmax, pure XLA.

    Memory is O(q_block * kv_block) per head rather than O(Lq * Lk). Blocks
    are static so XLA tiles cleanly onto the MXU. ``window`` masks each
    query to its last ``window`` keys (sliding-window attention).
    """
    if isinstance(window, int) and window <= 0:
        window = None
    if window is not None and not causal:
        raise ValueError("window requires causal attention")
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    b, lq, h, d = q.shape
    lk = k.shape[1]
    k = _repeat_kv(k, h)
    v = _repeat_kv(v, h)
    q_block = min(q_block, lq)
    kv_block = min(kv_block, lk)
    if lq % q_block or lk % kv_block:
        # Fall back for ragged lengths; decode paths use naive anyway.
        return naive_attention(q, k, v, causal=causal, scale=scale,
                               q_offset=q_offset, window=window,
                               softcap=softcap)
    nq, nk = lq // q_block, lk // kv_block

    qf = q.astype(jnp.float32).reshape(b, nq, q_block, h, d)
    kf = k.astype(jnp.float32).reshape(b, nk, kv_block, h, d)
    vf = v.astype(jnp.float32).reshape(b, nk, kv_block, h, d)

    q_ids = jnp.arange(q_block)
    k_ids = jnp.arange(kv_block)

    def per_q_block(qi, qb):
        # qb: [B, qb, H, D]
        m0 = jnp.full((b, h, q_block), NEG_INF, jnp.float32)
        l0 = jnp.zeros((b, h, q_block), jnp.float32)
        o0 = jnp.zeros((b, q_block, h, d), jnp.float32)

        def kv_step(carry, inp):
            m, l, o = carry
            ki, kb, vb = inp
            mask = _band_mask(qi * q_block + q_ids[:, None] + q_offset,
                              ki * kv_block + k_ids[None, :], causal, window)
            m, l, o = _attend_block(qb, kb, vb, m, l, o, mask, scale, softcap)
            return (m, l, o), None

        (m, l, o), _ = lax.scan(
            kv_step, (m0, l0, o0), (jnp.arange(nk), kf.swapaxes(0, 1), vf.swapaxes(0, 1))
        )
        return o / l.transpose(0, 2, 1)[..., None]

    out = lax.map(lambda args: per_q_block(*args), (jnp.arange(nq), qf.swapaxes(0, 1)))
    # out: [nq, B, qb, H, D] -> [B, Lq, H, D]
    out = out.swapaxes(0, 1).reshape(b, lq, h, d)
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Memory-efficient attention with a custom VJP (FlashAttention-2 style)
# ---------------------------------------------------------------------------
#
# Differentiating the blockwise/Pallas forward directly makes jax save every
# probability block as a residual — O(Lq*Lk) per layer, which stacks across
# a scanned-layer model into tens of GB (the round-1 bench OOMed a 16 GB
# v5e HBM on exactly this). The standard fix is a custom VJP: the forward
# saves only (q, k, v, out, lse) — O(L) per token — and the backward
# recomputes the probability blocks on the fly.
#
# Backward math (s = scale * q k^T, p = softmax rows = exp(s - lse)):
#   D  = rowsum(dout * out)            [B, H, Lq]
#   dp = dout v^T                      per block
#   ds = p * (dp - D)
#   dq = scale * ds k ; dk = scale * ds^T q ; dv = p^T dout
#
# GQA is handled OUTSIDE the custom-vjp core: kv heads are expanded with
# jnp.repeat first, whose autodiff sums gradients back over the group.


def _n_live_kv_blocks(nk: int, q_block: int, kv_block: int,
                      window) -> int:
    """Static count of kv blocks a q block can see under the window band.

    The visible columns for q block qi span ``q_block + window - 1``
    positions, which cross at most that many // kv_block + 2 block
    boundaries. Without a window every block is live.
    """
    if not window:
        return nk
    return min(nk, (q_block + window - 2) // kv_block + 2)


def _live_kv_start(qi, nk: int, n_live: int, q_block: int, kv_block: int,
                   window, pos_delta: int = 0):
    """First live kv block for q block ``qi`` (traced), clamped so the
    static-length slice stays in range. Clamping only ever EXTENDS
    coverage (earlier blocks get window-masked; later ones causal-masked),
    never drops a live block. ``pos_delta`` = (global q position of local
    q index 0) - (global k position of local k index 0) for the affine
    positional path (halo SP: delta = Lloc)."""
    if not window:
        return jnp.int32(0)
    start = (qi * q_block + pos_delta - (window - 1)) // kv_block
    return jnp.clip(start, 0, nk - n_live).astype(jnp.int32)


def _mha_fwd_blockwise(q, k, v, causal, scale, q_block, kv_block,
                       window=None, qpos=None, kpos=None, pos_delta=None,
                       softcap=0.0):
    """Blockwise forward returning (out, lse). Heads already expanded.

    Causal rows always see at least the diagonal key, so lse is finite.
    With ``window``, only the O(window/kv_block) live kv blocks per q block
    are scanned (static count, dynamic start) — the SWA FLOP win. A scanned
    block can still be fully masked for SOME rows: those rows accumulate
    exp(NEG_INF - NEG_INF) = 1 fake mass per key, which the online-softmax
    rescale alpha = exp(NEG_INF - m_finite) annihilates to exactly 0 at the
    first in-band block (every row's diagonal block IS in range). This
    relies on NEG_INF being a large FINITE negative — -inf would make the
    rescale exp(-inf - (-inf)) = NaN.
    """
    b, lq, h, d = q.shape
    lk = k.shape[1]
    nq, nk = lq // q_block, lk // kv_block
    qf = q.astype(jnp.float32).reshape(b, nq, q_block, h, d)
    kf = k.astype(jnp.float32).reshape(b, nk, kv_block, h, d)
    vf = v.astype(jnp.float32).reshape(b, nk, kv_block, h, d)
    kf_s, vf_s = kf.swapaxes(0, 1), vf.swapaxes(0, 1)  # [nk, B, kb, H, D]
    q_ids = jnp.arange(q_block)
    k_ids = jnp.arange(kv_block)
    # explicit position arrays keep the windowed live-block slicing as
    # long as the caller declares their affine delta (halo SP passes
    # Lloc); arbitrary non-affine positions fall back to the full scan
    n_live = (nk if (kpos is not None and pos_delta is None)
              else _n_live_kv_blocks(nk, q_block, kv_block, window))

    def per_q_block(qi, qb):
        m0 = jnp.full((b, h, q_block), NEG_INF, jnp.float32)
        l0 = jnp.zeros((b, h, q_block), jnp.float32)
        o0 = jnp.zeros((b, q_block, h, d), jnp.float32)

        def kv_step(carry, inp):
            m, l, o = carry
            ki, kb, vb = inp
            qp = (qi * q_block + q_ids if qpos is None
                  else lax.dynamic_slice_in_dim(qpos, qi * q_block, q_block))
            kp = (ki * kv_block + k_ids if kpos is None
                  else lax.dynamic_slice_in_dim(kpos, ki * kv_block,
                                                kv_block))
            mask = _band_mask(qp[:, None], kp[None, :], causal, window)
            if kpos is not None and mask is not None:
                mask &= (kp >= 0)[None, :]
            m, l, o = _attend_block(qb, kb, vb, m, l, o, mask, scale, softcap)
            return (m, l, o), None

        if kpos is not None and pos_delta is None:
            start = jnp.int32(0)
        else:
            start = _live_kv_start(qi, nk, n_live, q_block, kv_block,
                                   window, pos_delta or 0)
        idx = start + jnp.arange(n_live)
        ks = lax.dynamic_slice_in_dim(kf_s, start, n_live, axis=0)
        vs = lax.dynamic_slice_in_dim(vf_s, start, n_live, axis=0)
        (m, l, o), _ = lax.scan(kv_step, (m0, l0, o0), (idx, ks, vs))
        lse = m + jnp.log(jnp.maximum(l, 1e-30))        # [B, H, qb]
        return o / l.transpose(0, 2, 1)[..., None], lse

    out, lse = lax.map(lambda args: per_q_block(*args),
                       (jnp.arange(nq), qf.swapaxes(0, 1)))
    # out: [nq, B, qb, H, D] -> [B, Lq, H, D]; lse: [nq, B, H, qb] -> [B, H, Lq]
    out = out.swapaxes(0, 1).reshape(b, lq, h, d).astype(q.dtype)
    lse = lse.transpose(1, 2, 0, 3).reshape(b, h, lq)
    return out, lse


def _mha_bwd_blockwise(causal, scale, q_block, kv_block,
                       q, k, v, out, lse, dout, window=None,
                       qpos=None, kpos=None, pos_delta=None, softcap=0.0):
    """Blocked backward; recomputes p per (q-block, kv-block) pair."""
    b, lq, h, d = q.shape
    lk = k.shape[1]
    nq, nk = lq // q_block, lk // kv_block
    qf = q.astype(jnp.float32).reshape(b, nq, q_block, h, d).swapaxes(0, 1)
    kf = k.astype(jnp.float32).reshape(b, nk, kv_block, h, d).swapaxes(0, 1)
    vf = v.astype(jnp.float32).reshape(b, nk, kv_block, h, d).swapaxes(0, 1)
    dof = dout.astype(jnp.float32).reshape(b, nq, q_block, h, d).swapaxes(0, 1)
    outf = out.astype(jnp.float32).reshape(b, nq, q_block, h, d).swapaxes(0, 1)
    lsef = lse.reshape(b, h, nq, q_block).transpose(2, 0, 1, 3)  # [nq,B,H,qb]
    q_ids = jnp.arange(q_block)
    k_ids = jnp.arange(kv_block)

    n_live = (nk if (kpos is not None and pos_delta is None)
              else _n_live_kv_blocks(nk, q_block, kv_block, window))

    def q_step(carry, inp):
        dk_acc, dv_acc = carry                     # [nk, B, kb, H, D]
        qi, qb, dob, ob, lseb = inp
        dvec = (dob * ob).sum(-1).transpose(0, 2, 1)  # D: [B, H, qb]

        def kv_step(_, kin):
            ki, kb, vb = kin
            s = jnp.einsum("bqhd,bkhd->bhqk", qb, kb) * scale
            s_hat = _softcap_scores(s, softcap)  # pre-mask: dfactor source
            qp = (qi * q_block + q_ids if qpos is None
                  else lax.dynamic_slice_in_dim(qpos, qi * q_block, q_block))
            kp = (ki * kv_block + k_ids if kpos is None
                  else lax.dynamic_slice_in_dim(kpos, ki * kv_block,
                                                kv_block))
            mask = _band_mask(qp[:, None], kp[None, :], causal, window)
            if kpos is not None and mask is not None:
                mask &= (kp >= 0)[None, :]
            s = s_hat
            if mask is not None:
                s = jnp.where(mask[None, None], s, NEG_INF)
            # out-of-band keys: s = NEG_INF, lse finite -> p underflows to
            # exactly 0 (NEG_INF must stay a finite float for this)
            p = jnp.exp(s - lseb[..., None])       # [B, H, qb, kb]
            dp = jnp.einsum("bqhd,bkhd->bhqk", dob, vb)
            ds = p * (dp - dvec[..., None])
            if softcap:
                # chain through the cap: d(raw)/d(capped); masked entries
                # already have p = 0, so the (finite) factor is harmless
                ds = ds * _softcap_dfactor(s_hat, softcap)
            dq_c = scale * jnp.einsum("bhqk,bkhd->bqhd", ds, kb)
            dk_c = scale * jnp.einsum("bhqk,bqhd->bkhd", ds, qb)
            dv_c = jnp.einsum("bhqk,bqhd->bkhd", p, dob)
            return None, (dq_c, dk_c, dv_c)

        if kpos is not None and pos_delta is None:
            start = jnp.int32(0)
        else:
            start = _live_kv_start(qi, nk, n_live, q_block, kv_block,
                                   window, pos_delta or 0)
        idx = start + jnp.arange(n_live)
        ks = lax.dynamic_slice_in_dim(kf, start, n_live, axis=0)
        vs = lax.dynamic_slice_in_dim(vf, start, n_live, axis=0)
        _, (dq_cs, dk_cs, dv_cs) = lax.scan(kv_step, None, (idx, ks, vs))
        if n_live == nk:
            dk_acc = dk_acc + dk_cs
            dv_acc = dv_acc + dv_cs
        else:
            dk_acc = lax.dynamic_update_slice_in_dim(
                dk_acc,
                lax.dynamic_slice_in_dim(dk_acc, start, n_live, 0) + dk_cs,
                start, 0)
            dv_acc = lax.dynamic_update_slice_in_dim(
                dv_acc,
                lax.dynamic_slice_in_dim(dv_acc, start, n_live, 0) + dv_cs,
                start, 0)
        return (dk_acc, dv_acc), dq_cs.sum(0)

    zeros_kv = jnp.zeros((nk, b, kv_block, h, d), jnp.float32)
    (dk, dv), dq_blocks = lax.scan(
        q_step, (zeros_kv, zeros_kv),
        (jnp.arange(nq), qf, dof, outf, lsef))
    dq = dq_blocks.swapaxes(0, 1).reshape(b, lq, h, d).astype(q.dtype)
    dk = dk.swapaxes(0, 1).reshape(b, lk, h, d).astype(k.dtype)
    dv = dv.swapaxes(0, 1).reshape(b, lk, h, d).astype(v.dtype)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _mha(q, k, v, causal, scale, q_block, kv_block, use_pallas, window=None,
         softcap=0.0):
    out, _ = _mha_fwd(q, k, v, causal, scale, q_block, kv_block, use_pallas,
                      window, softcap)
    return out


def _mha_fwd(q, k, v, causal, scale, q_block, kv_block, use_pallas,
             window=None, softcap=0.0):
    """k/v stay at their native (possibly fewer, GQA) head count in the
    residuals — expanding before the VJP would multiply residual HBM by the
    group factor, eroding the O(L) memory win this VJP exists for."""
    if use_pallas:
        from ray_tpu.ops.flash_pallas import flash_attention_pallas_fwd

        # the Pallas kernel handles GQA natively (kv block reuse per group)
        out, lse = flash_attention_pallas_fwd(
            q, k, v, causal=causal, scale=scale,
            block_q=q_block, block_k=kv_block, window=window,
            softcap=softcap)
    else:
        h = q.shape[2]
        out, lse = _mha_fwd_blockwise(q, _repeat_kv(k, h), _repeat_kv(v, h),
                                      causal, scale, q_block, kv_block,
                                      window, softcap=softcap)
    return out, (q, k, v, out, lse)


def _mha_fwd_rule(q, k, v, causal, scale, q_block, kv_block, use_pallas,
                  window=None, softcap=0.0):
    out, res = _mha_fwd(q, k, v, causal, scale, q_block, kv_block, use_pallas,
                        window, softcap)
    return out, res


def _mha_bwd_rule(causal, scale, q_block, kv_block, use_pallas, window,
                  softcap, res, dout):
    q, k, v, out, lse = res
    b, lk, hk, d = k.shape
    lq, h = q.shape[1], q.shape[2]
    # Backward impl follows the forward: hand-tiled Pallas kernels (FA2
    # dKV/dQ sweeps) on TPU, blockwise XLA elsewhere — O(L) residuals
    # either way. The Pallas kernels are GQA-NATIVE (per-group index maps
    # + in-kernel group accumulation, ADVICE r2 #5); only the XLA fallback
    # expands kv transiently and group-sums the grads back.
    if (use_pallas and lq % min(q_block, lq) == 0
            and lk % min(kv_block, lk) == 0):
        from ray_tpu.ops.flash_pallas import flash_attention_pallas_bwd

        dq, dk, dv = flash_attention_pallas_bwd(
            q, k, v, out, lse, dout, causal=causal, scale=scale,
            block_q=q_block, block_k=kv_block, window=window,
            softcap=softcap)
    else:
        kx, vx = _repeat_kv(k, h), _repeat_kv(v, h)
        dq, dk, dv = _mha_bwd_blockwise(causal, scale, q_block, kv_block,
                                        q, kx, vx, out, lse, dout, window,
                                        softcap=softcap)
        if hk != h:
            group = h // hk
            dk = dk.reshape(b, lk, hk, group, d).sum(axis=3)
            dv = dv.reshape(b, lk, hk, group, d).sum(axis=3)
    return dq, dk, dv


_mha.defvjp(_mha_fwd_rule, _mha_bwd_rule)


# --- positional variant: explicit global positions per query/key ----------
# Used by the halo-exchange sequence-parallel sliding-window path, where
# each shard's queries/keys carry global positions (float32 so the
# custom-vjp cotangents are well-typed zeros; negative key positions mean
# "halo wrap garbage" and are masked). Same O(L) residuals as _mha.

@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _mha_pos(q, k, v, qpos, kpos, scale, q_block, kv_block, window,
             pos_delta=None, softcap=0.0):
    out, _ = _mha_pos_fwd(q, k, v, qpos, kpos, scale, q_block, kv_block,
                          window, pos_delta, softcap)
    return out


def _mha_pos_fwd(q, k, v, qpos, kpos, scale, q_block, kv_block, window,
                 pos_delta=None, softcap=0.0):
    h = q.shape[2]
    out, lse = _mha_fwd_blockwise(q, _repeat_kv(k, h), _repeat_kv(v, h),
                                  True, scale, q_block, kv_block, window,
                                  qpos=qpos, kpos=kpos, pos_delta=pos_delta,
                                  softcap=softcap)
    return out, (q, k, v, out, lse, qpos, kpos)


def _mha_pos_bwd(scale, q_block, kv_block, window, pos_delta, softcap,
                 res, dout):
    q, k, v, out, lse, qpos, kpos = res
    b, lk, hk, d = k.shape
    h = q.shape[2]
    kx, vx = _repeat_kv(k, h), _repeat_kv(v, h)
    dq, dk, dv = _mha_bwd_blockwise(True, scale, q_block, kv_block,
                                    q, kx, vx, out, lse, dout, window,
                                    qpos=qpos, kpos=kpos,
                                    pos_delta=pos_delta, softcap=softcap)
    if hk != h:
        group = h // hk
        dk = dk.reshape(b, lk, hk, group, d).sum(axis=3)
        dv = dv.reshape(b, lk, hk, group, d).sum(axis=3)
    return dq, dk, dv, jnp.zeros_like(qpos), jnp.zeros_like(kpos)


_mha_pos.defvjp(lambda *a: _mha_pos_fwd(*a), _mha_pos_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    impl: str = "auto",
    q_block: int = 512,
    kv_block: int = 512,
    window: Optional[int] = None,
    softcap: float = 0.0,
) -> jax.Array:
    """Dispatching entry point: Pallas kernel on TPU, blockwise XLA elsewhere.

    ``impl``: ``auto`` | ``pallas`` | ``xla`` | ``naive``. Both pallas and
    xla run through the memory-efficient custom VJP above, so this is safe
    to differentiate at long context (no O(L^2) residuals).

    ``window`` enables sliding-window (Mistral-style local) attention:
    each query sees only its last ``window`` keys. Requires ``causal``.
    Both the Pallas kernels (banded block-liveness predicates) and the
    blockwise-XLA path (live kv-block slicing) skip out-of-band blocks,
    so SWA costs O(L * window), not O(L^2).

    Deliberately NOT jitted here: "auto" must resolve at every trace so a
    later ``set_default_attention_impl`` (e.g. a preflight pinning "xla"
    after Mosaic rejects the kernel) is honored — a jit cache keyed on the
    literal "auto" would replay the stale choice. Callers jit the enclosing
    computation; eager use still compiles the Pallas/blockwise internals.
    """
    if window is not None:
        if not causal:
            raise ValueError("window requires causal attention")
        if window <= 0:
            raise ValueError(f"window must be positive, got {window}")
    if impl == "auto":
        impl = resolve_attention_impl()
    if impl == "naive":
        return naive_attention(q, k, v, causal=causal, window=window,
                               softcap=softcap)
    b, lq, h, d = q.shape
    lk, hk = k.shape[1], k.shape[2]
    q_block = min(q_block, lq)
    kv_block = min(kv_block, lk)
    if lq % q_block or lk % kv_block:
        # Non-divisible tile knob (e.g. RTPU_ATTN_BLOCK_Q=768 with seq
        # 2048): shrink to the largest divisor >=128 rather than silently
        # dispatching a differentiated TRAINING path to naive — naive
        # materializes O(L^2) scores and reintroduces the exact OOM the
        # custom VJP exists to prevent (ADVICE r4 #5). Genuinely ragged
        # short decode shapes (no >=128 divisor) still use naive.
        import warnings

        # blocks must stay sublane-aligned (x % 8) or Mosaic rejects the
        # Pallas BlockSpec on real silicon
        qb = next((x for x in range(q_block, 127, -1)
                   if lq % x == 0 and x % 8 == 0), 0)
        kb = next((x for x in range(kv_block, 127, -1)
                   if lk % x == 0 and x % 8 == 0), 0)
        if qb and kb:
            warnings.warn(
                f"attention tile sizes (q={q_block}, kv={kv_block}) do not "
                f"divide seq (lq={lq}, lk={lk}); using largest divisors "
                f"(q={qb}, kv={kb}) instead")
            q_block, kv_block = qb, kb
        else:
            return naive_attention(q, k, v, causal=causal, window=window,
                                   softcap=softcap)
    scale = d ** -0.5
    from ray_tpu.util import device_plane as _dp

    if _dp.device_plane_enabled() and not isinstance(q, jax.core.Tracer):
        # EAGER entry point (bench numerics, tests, preflights): the
        # blockwise/Pallas internals compile implicitly here — register
        # novel signatures as compiles of "ops::flash_attention" so the
        # device plane sees them too. Inside an enclosing jit (tracers)
        # the CALLER's registered program owns the compile.
        return _dp.tracked_call(
            "ops::flash_attention", "ops",
            lambda: _mha(q, k, v, causal, scale, q_block, kv_block,
                         impl == "pallas", window, softcap),
            (q, k, v),
            statics={"impl": impl, "causal": causal, "q_block": q_block,
                     "kv_block": kv_block, "window": window,
                     "softcap": softcap})
    return _mha(q, k, v, causal, scale, q_block, kv_block,
                impl == "pallas", window, softcap)
