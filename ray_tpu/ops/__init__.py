"""TPU kernel library: attention, normalization, rotary, MoE dispatch.

This package is green-field relative to the reference — Ray has no kernel
layer (long-context/sequence-parallel is absent upstream, SURVEY §5) — but it
is the compute substrate every ML library here builds on. Three tiers:

- pure-XLA blockwise implementations (:mod:`ray_tpu.ops.attention`) that run
  anywhere (CPU tests, TPU) and are the numerical reference;
- Pallas TPU kernels (:mod:`ray_tpu.ops.flash_pallas`) for the hot path;
- sequence-parallel ring attention (:mod:`ray_tpu.ops.ring_attention`)
  running inside ``shard_map`` with ``lax.ppermute`` over ICI neighbors.
"""

from ray_tpu.ops.attention import (
    naive_attention,
    blockwise_attention,
    flash_attention,
    set_default_attention_impl,
    resolve_attention_impl,
)
from ray_tpu.ops.ring_attention import ring_attention
from ray_tpu.ops.layers import (
    layer_norm,
    rms_norm,
    rotary_embedding,
    apply_rotary,
    swiglu,
)
from ray_tpu.ops.moe import (
    top_k_router,
    moe_layer_dense,
)

__all__ = [
    "naive_attention",
    "blockwise_attention",
    "flash_attention",
    "set_default_attention_impl",
    "resolve_attention_impl",
    "ring_attention",
    "layer_norm",
    "rms_norm",
    "rotary_embedding",
    "apply_rotary",
    "swiglu",
    "top_k_router",
    "moe_layer_dense",
]
