"""Mesh-aware collective primitives for use inside jit/shard_map.

These are thin, named wrappers over ``jax.lax`` collectives — the TPU
dataplane that replaces NCCL calls in the reference
(``python/ray/util/collective/collective.py:258`` allreduce etc.). They only
make sense inside a ``shard_map``/``pjit`` program where the axis names are
bound; :mod:`ray_tpu.collective` provides the host-level API with the same
verbs for actor-to-actor use.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

AxisName = Union[str, Sequence[str]]


def allreduce_sum(x, axis: AxisName):
    return lax.psum(x, axis)


def allreduce_mean(x, axis: AxisName):
    return lax.pmean(x, axis)


def allreduce_max(x, axis: AxisName):
    return lax.pmax(x, axis)


def allreduce_min(x, axis: AxisName):
    return lax.pmin(x, axis)


def allgather(x, axis: AxisName, *, tiled: bool = True, gather_axis: int = 0):
    return lax.all_gather(x, axis, axis=gather_axis, tiled=tiled)


def reducescatter_sum(x, axis: AxisName, *, scatter_axis: int = 0,
                      tiled: bool = True):
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_axis,
                            tiled=tiled)


def alltoall(x, axis: AxisName, *, split_axis: int, concat_axis: int):
    return lax.all_to_all(x, axis, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def broadcast(x, axis: AxisName, *, root: int = 0):
    """Every shard receives root's value (select + psum keeps it one pass)."""
    idx = lax.axis_index(axis)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(masked, axis)


def ring_permute(x, axis: AxisName, *, shift: int = 1):
    """Send each shard to its ring neighbor (the ring-attention step)."""
    n = lax.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


def axis_index(axis: AxisName):
    return lax.axis_index(axis)


def axis_size(axis: AxisName):
    return lax.axis_size(axis)


def quantized_psum(x, axis: AxisName, *, bits: int = 8, block: int = 256):
    """All-reduce-sum that ships int8 on the wire (EQuARX role,
    arxiv 2506.17615: quantized AllReduce in XLA for bandwidth-bound
    links). Designed for SMALL axes — the cross-slice ``dcn`` axis where
    gradient sync crosses data-center network: each shard quantizes its
    values blockwise (per-``block`` max-abs scale, symmetric int8),
    all-gathers the int8 payload + f32 scales (the int8 tensor is what
    rides the wire), then dequantizes and sums locally.

    Wire bytes ~= n * size/4 vs a float32 ring psum's ~2*size: a win for
    axis sizes up to ~8 (n=2: 4x less traffic; n=4: 2x). Accuracy: block
    max-abs symmetric quantization, worst-case elementwise error
    ``max_abs_in_block / 127`` per shard.
    """
    if bits != 8:
        raise NotImplementedError("int8 is the only wire dtype today")
    orig_shape, orig_dtype = x.shape, x.dtype
    flat = x.astype(jnp.float32).reshape(-1)
    pad = (-flat.shape[0]) % block
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    blocks = flat.reshape(-1, block)
    scale = jnp.max(jnp.abs(blocks), axis=1, keepdims=True) / 127.0
    safe = jnp.where(scale == 0.0, 1.0, scale)
    q = jnp.clip(jnp.round(blocks / safe), -127, 127).astype(jnp.int8)

    q_all = lax.all_gather(q, axis)          # [n, nblk, block] int8 wire
    s_all = lax.all_gather(scale, axis)      # [n, nblk, 1] f32 (tiny)
    total = (q_all.astype(jnp.float32) * s_all).sum(axis=0)
    total = total.reshape(-1)
    if pad:
        total = total[:-pad]
    return total.reshape(orig_shape).astype(orig_dtype)


def quantized_pmean(x, axis: AxisName, *, bits: int = 8, block: int = 256):
    """Mean variant of :func:`quantized_psum` (gradient averaging)."""
    return quantized_psum(x, axis, bits=bits, block=block) / axis_size(axis)
