"""Mesh-aware collective primitives for use inside jit/shard_map.

These are thin, named wrappers over ``jax.lax`` collectives — the TPU
dataplane that replaces NCCL calls in the reference
(``python/ray/util/collective/collective.py:258`` allreduce etc.). They only
make sense inside a ``shard_map``/``pjit`` program where the axis names are
bound; :mod:`ray_tpu.collective` provides the host-level API with the same
verbs for actor-to-actor use.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

AxisName = Union[str, Sequence[str]]


def allreduce_sum(x, axis: AxisName):
    return lax.psum(x, axis)


def allreduce_mean(x, axis: AxisName):
    return lax.pmean(x, axis)


def allreduce_max(x, axis: AxisName):
    return lax.pmax(x, axis)


def allreduce_min(x, axis: AxisName):
    return lax.pmin(x, axis)


def allgather(x, axis: AxisName, *, tiled: bool = True, gather_axis: int = 0):
    return lax.all_gather(x, axis, axis=gather_axis, tiled=tiled)


def reducescatter_sum(x, axis: AxisName, *, scatter_axis: int = 0,
                      tiled: bool = True):
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_axis,
                            tiled=tiled)


def alltoall(x, axis: AxisName, *, split_axis: int, concat_axis: int):
    return lax.all_to_all(x, axis, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def broadcast(x, axis: AxisName, *, root: int = 0):
    """Every shard receives root's value (select + psum keeps it one pass)."""
    idx = lax.axis_index(axis)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(masked, axis)


def ring_permute(x, axis: AxisName, *, shift: int = 1):
    """Send each shard to its ring neighbor (the ring-attention step)."""
    n = lax.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


def axis_index(axis: AxisName):
    return lax.axis_index(axis)


def axis_size(axis: AxisName):
    return lax.axis_size(axis)
