"""Device mesh construction with named parallelism axes.

The mesh is the TPU-native replacement for the reference's process-group
bootstrapping (``python/ray/train/torch/config.py:65``
``_setup_torch_process_group``): instead of wiring NCCL ranks, we lay chips
out on a logical grid and let GSPMD partition programs over it. Axis order
matters for ICI locality: the innermost axes (tp, sp) should map to
physically adjacent chips so their collectives ride ICI neighbor links;
dp/fsdp ride the remaining dims; a leading DCN axis (``dcn``) spans slices.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


@dataclass(frozen=True)
class MeshConfig:
    """Declarative parallelism layout.

    Sizes of -1 mean "absorb whatever devices remain" (at most one axis may
    be -1). Axes of size 1 are kept in the mesh — partition specs can always
    name them and XLA drops trivial collectives, which keeps downstream code
    free of special cases.

    ``pp`` (pipeline) is an ordinary mesh axis here; the pipeline schedule
    itself lives in :mod:`ray_tpu.train.pipeline`.
    """

    dp: int = 1          # pure data parallel (replicated params)
    fsdp: int = -1       # data parallel with sharded params (ZeRO-3)
    tp: int = 1          # tensor parallel
    sp: int = 1          # sequence/context parallel (ring attention axis)
    ep: int = 1          # expert parallel (MoE all_to_all axis)
    pp: int = 1          # pipeline stages
    dcn: int = 1         # cross-slice (multi-pod) axis, outermost
    axis_order: Tuple[str, ...] = ("dcn", "pp", "dp", "fsdp", "sp", "tp", "ep")

    def sizes(self) -> Dict[str, int]:
        return {
            "dcn": self.dcn, "pp": self.pp, "dp": self.dp, "fsdp": self.fsdp,
            "sp": self.sp, "tp": self.tp, "ep": self.ep,
        }

    def resolve(self, n_devices: int) -> Dict[str, int]:
        """Fill in a single -1 axis so the product equals ``n_devices``."""
        sizes = self.sizes()
        unknown = [a for a, s in sizes.items() if s == -1]
        if len(unknown) > 1:
            raise ValueError(f"at most one mesh axis may be -1, got {unknown}")
        known = math.prod(s for s in sizes.values() if s != -1)
        if unknown:
            if n_devices % known:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {known}"
                )
            sizes[unknown[0]] = n_devices // known
        elif known != n_devices:
            raise ValueError(
                f"mesh axes product {known} != device count {n_devices}"
            )
        return sizes


def mesh_shape_for(config: MeshConfig, n_devices: int) -> Tuple[Tuple[str, int], ...]:
    sizes = config.resolve(n_devices)
    return tuple((a, sizes[a]) for a in config.axis_order)


def make_mesh(
    config: Optional[MeshConfig] = None,
    devices: Optional[Sequence] = None,
    *,
    drop_trivial: bool = False,
):
    """Build a ``jax.sharding.Mesh`` from a :class:`MeshConfig`.

    Device order: we rely on ``jax.devices()`` order (XLA already orders TPU
    devices so that adjacent ids are ICI neighbors on the minor torus dims),
    reshaped row-major so the *last* axes of ``axis_order`` (sp, tp, ep) get
    adjacent chips. For multi-host meshes this must be called with the same
    config in every process of the slice.
    """
    import jax
    from jax.sharding import Mesh

    config = config or MeshConfig()
    devs = list(devices) if devices is not None else list(jax.devices())
    shape = mesh_shape_for(config, len(devs))
    if drop_trivial:
        shape = tuple((a, s) for a, s in shape if s > 1) or (("dp", 1),)
    names = tuple(a for a, _ in shape)
    dims = tuple(s for _, s in shape)
    arr = np.asarray(devs, dtype=object).reshape(dims)
    return Mesh(arr, axis_names=names)


def local_mesh(axis: str = "dp"):
    """A 1-D mesh over all local devices — the quick path for tests/demos."""
    import jax
    from jax.sharding import Mesh

    devs = np.asarray(jax.devices(), dtype=object)
    return Mesh(devs, axis_names=(axis,))
