"""TPU-native parallelism layer.

This package is the TPU seam of the framework (reference SURVEY §1 "key
facts": GPU code in Ray lives in the accelerator plugins, NCCL collective
group, Train's TorchConfig, and NCCL DAG channels — here all of it is
replaced by one coherent JAX/XLA layer):

- :mod:`ray_tpu.parallel.mesh` — device meshes with named axes
  (dp/fsdp/tp/sp/ep/pp), single- and multi-host.
- :mod:`ray_tpu.parallel.sharding` — logical-axis → mesh-axis rules and
  PartitionSpec derivation for parameters and activations.
- :mod:`ray_tpu.parallel.ops` — mesh-aware collective helpers usable inside
  jit (psum/all_gather/ppermute wrappers).

Unlike the reference's `ray.util.collective` (NCCL via cupy,
``python/ray/util/collective/collective_group/nccl_collective_group.py:128``)
where collectives are explicit host-initiated calls, the TPU-idiomatic path
is: build a Mesh, annotate shardings, let XLA insert collectives over ICI/DCN.
The explicit-collective API lives in :mod:`ray_tpu.collective` for parity.
"""

from ray_tpu.parallel.mesh import (
    MeshConfig,
    make_mesh,
    mesh_shape_for,
    local_mesh,
)
from ray_tpu.parallel.sharding import (
    ShardingRules,
    DEFAULT_RULES,
    logical_to_spec,
    shard_params,
    constrain,
)

__all__ = [
    "MeshConfig",
    "make_mesh",
    "mesh_shape_for",
    "local_mesh",
    "ShardingRules",
    "DEFAULT_RULES",
    "logical_to_spec",
    "shard_params",
    "constrain",
]
