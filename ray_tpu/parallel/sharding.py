"""Logical-axis sharding rules: name tensor dims, map names to mesh axes.

TPU-first replacement for torch DDP/FSDP wrapping
(``python/ray/train/torch/train_loop_utils.py:158`` ``prepare_model``): no
module wrappers — parameters are plain pytrees whose dims carry logical
names, and one rule table maps logical names to mesh axes. FSDP ≡ shard the
"embed"/"mlp" weight dims on the fsdp axis; TP ≡ shard head/ffn dims on tp;
switching strategies is editing the table, not rewrapping the model.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Sequence, Tuple, Union

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Axis = Union[str, Tuple[str, ...], None]


@dataclass
class ShardingRules:
    """Maps logical dim names → mesh axis (or tuple of axes, or None)."""

    rules: Dict[str, Axis] = field(default_factory=dict)

    def spec(self, logical_axes: Sequence[Optional[str]]) -> P:
        out = []
        for name in logical_axes:
            if name is None:
                out.append(None)
            else:
                out.append(self.rules.get(name))
        return P(*out)

    def updated(self, **overrides: Axis) -> "ShardingRules":
        new = dict(self.rules)
        new.update(overrides)
        return ShardingRules(new)


# The canonical rule table for transformer training. Batch shards over every
# data-ish axis; sequence over sp (ring attention's ring axis); attention
# heads + ffn hidden over tp; the model ("embed") dim of weights over fsdp so
# params/grads/opt-state are ZeRO-3 sharded; experts over ep.
DEFAULT_RULES = ShardingRules({
    "batch": ("dcn", "dp", "fsdp"),
    "seq": "sp",
    # flattened batch*seq (row-major, batch outer). Matches the
    # ("batch", "seq") device layout exactly when sp == 1 or the
    # per-device batch block is 1; otherwise a reshard to/from it is one
    # all-to-all (the MoE dispatch path pays that instead of the SPMD
    # partitioner's full rematerialization)
    "tokens": ("dcn", "dp", "fsdp", "sp"),
    "embed": "fsdp",
    "heads": "tp",
    "kv_heads": "tp",
    "head_dim": None,
    "mlp": "tp",
    "vocab": "tp",
    "expert": "ep",
    "stage": "pp",
    # the stacked layer dim shards over pp: each pipeline stage holds
    # L/pp layers (the pipelined forward routes through
    # train.pipeline.pipeline_apply; with pp == 1 this is a no-op)
    "layers": "pp",
    "norm": None,
})


def logical_to_spec(logical_axes: Sequence[Optional[str]],
                    rules: Optional[ShardingRules] = None) -> P:
    return (rules or DEFAULT_RULES).spec(logical_axes)


def shard_params(params: Any, abstract_axes: Any, mesh: Mesh,
                 rules: Optional[ShardingRules] = None) -> Any:
    """Device-put a param pytree according to its logical-axes pytree."""
    import jax

    rules = rules or DEFAULT_RULES
    def _place(x, axes):
        return jax.device_put(x, NamedSharding(mesh, rules.spec(axes)))
    return jax.tree.map(_place, params, abstract_axes,
                        is_leaf=lambda x: x is None)


def param_shardings(abstract_axes: Any, mesh: Mesh,
                    rules: Optional[ShardingRules] = None) -> Any:
    """NamedSharding pytree matching an abstract-axes pytree."""
    rules = rules or DEFAULT_RULES
    import jax
    return jax.tree.map(
        lambda axes: NamedSharding(mesh, rules.spec(axes)),
        abstract_axes,
        is_leaf=lambda x: isinstance(x, (tuple, list)) and all(
            a is None or isinstance(a, str) for a in x),
    )


def constrain(x, logical_axes: Sequence[Optional[str]],
              rules: Optional[ShardingRules] = None):
    """`with_sharding_constraint` by logical names; no-op outside a mesh."""
    import jax
    from jax.sharding import get_abstract_mesh

    spec = (rules or DEFAULT_RULES).spec(logical_axes)
    mesh = get_abstract_mesh()
    if mesh is None or mesh.empty:
        return x
    # Only constrain on axes the ambient mesh actually has.
    names = set(mesh.axis_names)
    def _filter(entry):
        if entry is None:
            return None
        if isinstance(entry, tuple):
            kept = tuple(a for a in entry if a in names)
            return kept or None
        return entry if entry in names else None
    spec = P(*(_filter(e) for e in spec))
    return jax.lax.with_sharding_constraint(x, spec)
