"""CompiledDAG: channel-wired actor pipelines.

Role analog: ``python/ray/dag/compiled_dag_node.py:278``. Compilation
allocates one mutable shm ring channel per DAG edge and launches a
long-running exec loop inside every participating actor (the reference's
per-actor exec loops). After that, invoking the DAG is: driver writes the
input channel → each actor's loop reads its upstream channels, runs its
methods, writes its output channel → driver reads the final channel. No
task submission, no scheduler, no per-call allocation on the hot path.

r13 pipelining: channels are rings of ``max_in_flight + 1`` slots, so
``execute()`` admits up to ``max_in_flight`` overlapping invocations — a
K-stage pipeline reaches stage-parallel throughput instead of lock-step
round-trips. Results are delivered strictly FIFO: invocation k's future
resolves to result k regardless of the order futures are awaited (an
out-of-order ``get()`` buffers earlier results into their futures).
``execute_async()``/awaitable futures let asyncio callers (serve
replicas) drive a compiled DAG without blocking their loop.

The exec loop intentionally occupies the actor (submitted as a normal actor
call that only returns at teardown) — a compiled DAG takes ownership of its
actors, matching the reference's semantics. A participating actor dying
mid-loop is detected by polling the loop refs while waiting on the output
channel: the failure surfaces promptly as :class:`DAGExecutionError`
instead of a channel-read timeout, and ``teardown()`` force-stops the
surviving stages by writing the stop sentinel into the dead actor's
output channels (safe: their writer is gone).
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from ray_tpu.dag.dag_node import ClassMethodNode, DAGNode, InputNode
from ray_tpu.experimental.channel import Channel
from ray_tpu.experimental.device_channel import DeviceChannel, DeviceTensorType


class _Stop:
    """Teardown sentinel propagated through the pipeline."""


class _NodeError:
    def __init__(self, err: BaseException, node_repr: str):
        self.err = err
        self.node_repr = node_repr


class DAGExecutionError(RuntimeError):
    pass


class DAGBackpressureError(DAGExecutionError):
    """``execute()`` found ``max_in_flight`` invocations already admitted
    and none completed within the deadline."""


# lazily-bound built-in metrics (defs in util/metric_defs); a metrics
# failure must never fail an execution
_m = {"execs": None, "inflight": None}


def _dag_metrics():
    if _m["execs"] is None:
        from ray_tpu.util import metric_defs

        _m["execs"] = metric_defs.get("rtpu_dag_executions_total")
        _m["inflight"] = metric_defs.get("rtpu_dag_inflight")
    return _m


def _dag_exec_loop(instance, stages: List[Dict[str, Any]]) -> int:
    """Runs inside the actor: per invocation, execute this actor's stages
    in topo order. ``stages``: [{method, inputs: [(kind, key[, chan_kind])],
    out, out_kind}] where kind is "chan" | "const".
    """
    executed = 0
    chans: Dict[str, Channel] = {}

    def chan(name: str, kind: str = "obj") -> Channel:
        if name not in chans:
            cls = DeviceChannel if kind == "device" else Channel
            chans[name] = cls(name, create=False)
        return chans[name]

    from ray_tpu.util import tracing

    # This loop occupies the actor's dispatch thread; on a concurrency-1
    # actor the worker main loop (which normally ships span/metric/profile
    # batches on idle ticks) never runs again until teardown — push from
    # here instead (rate-limited + thread-safe inside push_telemetry)
    try:
        from ray_tpu.core.runtime import _get_runtime

        _push = getattr(_get_runtime(), "push_telemetry", None)
    except Exception:
        _push = None

    while True:
        stop = False
        read_cache: Dict[str, Any] = {}  # one read per channel per tick
        for stage in stages:
            args = []
            err: Optional[_NodeError] = None
            for kind, key, *ck in stage["inputs"]:
                if kind == "const":
                    args.append(key)
                    continue
                if key in read_cache:
                    val = read_cache[key]
                else:
                    val = chan(key, ck[0] if ck else "obj").read()
                    read_cache[key] = val
                if isinstance(val, _Stop):
                    stop = True
                if isinstance(val, _NodeError):
                    err = val
                args.append(val)
            out = chan(stage["out"], stage.get("out_kind", "obj"))
            if stop:
                out.write(_Stop())
                continue
            if err is not None:
                out.write(err)   # propagate upstream failure
                continue
            try:
                method = getattr(instance, stage["method"])
                if tracing.tracing_enabled():
                    with tracing.span("dag::stage",
                                      {"method": stage["method"]}):
                        result = method(*args)
                else:
                    result = method(*args)
                out.write(result)
            except BaseException as e:  # noqa: BLE001 — shipped to driver
                out.write(_NodeError(e, stage["method"]))
        if stop:
            return executed
        executed += 1
        if _push is not None:
            try:
                _push()
            except Exception:
                pass


class CompiledDAGFuture:
    """Result handle for one ``execute()``. FIFO delivery: this future
    resolves to the result of ITS invocation; getting futures out of
    submission order buffers the earlier results into their futures.
    Awaitable (``await fut`` / ``await fut.get_async()``) for asyncio
    drivers — the blocking wait runs on the loop's default executor."""

    def __init__(self, dag: "CompiledDAG", seq: int):
        self._dag = dag
        self._seq = seq
        self._done = False
        self._result: Any = None
        self._error: Optional[BaseException] = None

    def _settle(self, val: Any) -> None:
        self._done = True
        if isinstance(val, _NodeError):
            self._error = DAGExecutionError(
                f"compiled DAG node {val.node_repr!r} failed")
            self._error.__cause__ = val.err
        elif isinstance(val, _Stop):
            self._error = DAGExecutionError("compiled DAG was torn down")
        else:
            self._result = val

    def _resolve(self):
        if self._error is not None:
            raise self._error
        return self._result

    def get(self, timeout: Optional[float] = 60.0) -> Any:
        """Default bounds the wait (a wedged-but-alive stage never trips
        the death detector); pass ``timeout=None`` to wait forever."""
        if not self._done:
            self._dag._drain_until(self, timeout)
        return self._resolve()

    async def get_async(self, timeout: Optional[float] = 60.0) -> Any:
        import asyncio

        return await asyncio.get_running_loop().run_in_executor(
            None, self.get, timeout)

    def __await__(self):
        return self.get_async().__await__()


class CompiledDAG:
    def __init__(self, output_node: DAGNode,
                 buffer_size_bytes: int = 1 << 20,
                 max_in_flight: Optional[int] = None):
        if max_in_flight is None:
            from ray_tpu import config

            max_in_flight = int(config.get("dag_max_in_flight"))
        if max_in_flight < 1:
            raise ValueError("max_in_flight must be >= 1")
        self._output_node = output_node
        self._buffer = buffer_size_bytes
        self._max_in_flight = int(max_in_flight)
        self._channels: List[Channel] = []
        self._input_channel: Optional[Channel] = None
        self._output_channel: Optional[Channel] = None
        self._loop_refs: List[Any] = []
        # channels each ACTOR writes, keyed by loop-ref index — teardown
        # force-stops a dead actor's downstream by writing _Stop there
        self._writer_channels: Dict[int, List[Channel]] = {}
        self._torn_down = False
        self._broken: Optional[str] = None
        # admitted-but-unresolved futures in submission order; all
        # admission/drain bookkeeping happens under _drive_lock (one
        # drainer reads the output channel at a time)
        self._pending: "List[CompiledDAGFuture]" = []
        self._exec_seq = 0
        self._drive_lock = threading.Lock()
        self._compile()

    # -- compilation ------------------------------------------------------

    def _compile(self) -> None:
        order = self._output_node.topo_sort()
        inputs = [n for n in order if isinstance(n, InputNode)]
        if len(inputs) != 1:
            raise ValueError("compiled DAG needs exactly one InputNode")
        for n in order:
            if not isinstance(n, (InputNode, ClassMethodNode)):
                raise TypeError(
                    f"compiled DAGs support actor-method nodes only, got {n!r}")
            if isinstance(n, ClassMethodNode) and not n._upstream():
                raise ValueError(
                    f"{n!r} has no upstream nodes; compiled stages must be "
                    "driven by the input (teardown could never reach it)")
        # channel names carry the session so a crashed/unclean driver's
        # leftovers are sweepable at shutdown (rtpu-chan-<session>-*)
        try:
            from ray_tpu.core.runtime import _get_runtime

            uid = f"{_get_runtime().session}-{uuid.uuid4().hex[:8]}"
        except Exception:
            uid = uuid.uuid4().hex[:8]

        # one ring channel per node output, max_in_flight + 1 slots so
        # admission never blocks on the ring itself; DeviceTensorType-
        # hinted edges get the raw device-tensor channel (reference
        # NCCL-channel role)
        nslots = self._max_in_flight + 1
        chan_name: Dict[int, str] = {}
        chan_kind: Dict[int, str] = {}
        chan_by_node: Dict[int, Channel] = {}
        for i, n in enumerate(order):
            name = f"{uid}-{i}"
            chan_name[id(n)] = name
            kind = ("device" if isinstance(getattr(n, "_type_hint", None),
                                           DeviceTensorType) else "obj")
            chan_kind[id(n)] = kind
            cls = DeviceChannel if kind == "device" else Channel
            ch = cls(name, capacity=self._buffer, create=True, slots=nslots)
            self._channels.append(ch)
            chan_by_node[id(n)] = ch
            if isinstance(n, InputNode):
                self._input_channel = ch
        self._output_channel = chan_by_node[id(self._output_node)]

        # group stages by actor, preserving topo order
        by_actor: Dict[Any, List[Dict[str, Any]]] = {}
        writer_chans: Dict[Any, List[Channel]] = {}
        for n in order:
            if isinstance(n, InputNode):
                continue
            inputs_desc = []
            for a in n.args:
                if isinstance(a, DAGNode):
                    inputs_desc.append(("chan", chan_name[id(a)],
                                        chan_kind[id(a)]))
                else:
                    inputs_desc.append(("const", a))
            if n.kwargs:
                raise TypeError("compiled DAGs do not support kwargs binds")
            by_actor.setdefault(n.actor, []).append({
                "method": n.method_name,
                "inputs": inputs_desc,
                "out": chan_name[id(n)],
                "out_kind": chan_kind[id(n)],
            })
            writer_chans.setdefault(n.actor, []).append(chan_by_node[id(n)])

        for actor, stages in by_actor.items():
            idx = len(self._loop_refs)
            self._loop_refs.append(
                actor.__rtpu_call__.remote(_dag_exec_loop, stages))
            self._writer_channels[idx] = writer_chans.get(actor, [])

    # -- invocation -------------------------------------------------------

    def execute(self, input_value: Any,
                timeout: Optional[float] = None) -> CompiledDAGFuture:
        """Admit one invocation; returns its FIFO future. With
        ``max_in_flight`` invocations already admitted, blocks until one
        completes (its result is buffered into its future) — bounded by
        ``timeout``, raising :class:`DAGBackpressureError` on expiry."""
        from ray_tpu.util import tracing

        if not tracing.tracing_enabled():
            return self._execute_inner(input_value, timeout)
        with tracing.span("dag::execute", {"seq": self._exec_seq}):
            return self._execute_inner(input_value, timeout)

    def _execute_inner(self, input_value: Any,
                       timeout: Optional[float]) -> CompiledDAGFuture:
        deadline = None if timeout is None else time.monotonic() + timeout
        # admission loop: each iteration holds the drive lock for at most
        # one bounded drain slice (~0.2s), so concurrent getters — and a
        # teardown() from another thread — always get their turn
        while True:
            with self._drive_lock:
                self._raise_if_unusable()
                if len(self._pending) < self._max_in_flight:
                    fut = CompiledDAGFuture(self, self._exec_seq)
                    self._exec_seq += 1
                    self._pending.append(fut)
                    # ring slots cover max_in_flight + 1 values, so with
                    # admission bounded above this write never blocks on a
                    # healthy pipeline; the bounded timeout is a safety
                    # valve for a wedged one
                    try:
                        self._input_channel.write(input_value, timeout=60.0)
                    except Exception:
                        self._pending.remove(fut)
                        raise
                    try:
                        # inflight moves by DELTAS: several DAGs in one
                        # process share the gauge, so set() would clobber
                        m = _dag_metrics()
                        m["execs"].inc()
                        m["inflight"].inc()
                    except Exception:
                        pass
                    return fut
                # pipeline full: drain the oldest pending result into its
                # future (keeps FIFO), freeing one admission slot
                if deadline is not None and time.monotonic() > deadline:
                    raise DAGBackpressureError(
                        f"compiled DAG has {self._max_in_flight} "
                        f"invocations in flight and none completed within "
                        f"{timeout}s (max_in_flight={self._max_in_flight})")
                self._drain_step()

    async def execute_async(self, input_value: Any,
                            timeout: Optional[float] = None
                            ) -> CompiledDAGFuture:
        """``execute()`` for asyncio callers (serve replicas): admission —
        which may block on backpressure — runs on the loop's default
        executor; the returned future is awaitable."""
        import asyncio

        return await asyncio.get_running_loop().run_in_executor(
            None, self.execute, input_value, timeout)

    # -- result draining (FIFO) ------------------------------------------

    def _raise_if_unusable(self) -> None:
        if self._torn_down:
            raise DAGExecutionError("DAG already torn down")
        if self._broken:
            raise DAGExecutionError(self._broken)

    def _drain_until(self, fut: CompiledDAGFuture,
                     timeout: Optional[float]) -> None:
        """Block until ``fut`` is settled, draining output values FIFO.
        Concurrent getters cooperate: whoever holds the drive lock drains
        one bounded slice for everyone, then releases; the rest re-check
        their future between attempts."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while not fut._done:
            acquired = self._drive_lock.acquire(timeout=0.1)
            if not acquired:
                if deadline is not None and time.monotonic() > deadline:
                    raise DAGExecutionError(
                        f"compiled DAG result not available after "
                        f"{timeout}s")
                continue
            try:
                if fut._done:
                    return
                if self._torn_down or self._broken:
                    # settle instead of raising the generic torn-down
                    # error: the future may never get another chance
                    fut._settle(_Stop() if self._broken is None
                                else _NodeError(
                                    DAGExecutionError(self._broken),
                                    "pipeline"))
                    return
                if deadline is not None and time.monotonic() > deadline:
                    raise DAGExecutionError(
                        f"compiled DAG result not available after "
                        f"{timeout}s")
                self._drain_step()
            finally:
                self._drive_lock.release()

    def _drain_step(self) -> None:
        """ONE bounded (~0.2s) drain slice, caller holds the drive lock:
        read the next output value if it arrives, settle the oldest
        pending future, and check the exec-loop refs on an empty slice so
        a participating actor's death is detected promptly instead of
        timing out on the channel. Bounded so the lock churns and other
        getters / teardown() interleave."""
        if not self._pending:
            return
        from ray_tpu.experimental.channel import ChannelTimeoutError

        try:
            val = self._output_channel.read(timeout=0.2)
        except ChannelTimeoutError:
            self._check_loop_refs()
            return
        fut = self._pending.pop(0)
        fut._settle(val)
        try:
            _dag_metrics()["inflight"].dec()
        except Exception:
            pass

    def _check_loop_refs(self) -> None:
        """A loop ref resolving mid-run means its actor died (the loop
        only returns at teardown): mark the DAG broken and surface
        promptly — never let a dead stage read as a get() timeout."""
        import ray_tpu

        try:
            ready, _ = ray_tpu.wait(self._loop_refs,
                                    num_returns=len(self._loop_refs),
                                    timeout=0)
        except Exception:
            return
        if not ready:
            return
        detail = "a participating actor's exec loop ended mid-run"
        for ref in ready:
            try:
                ray_tpu.get(ref, timeout=1)
            except Exception as e:  # noqa: BLE001 — diagnostic only
                detail = f"participating actor died mid-DAG: {e!r}"
                break
        self._broken = detail
        broken_err = DAGExecutionError(detail)
        for fut in self._pending:
            fut._done = True
            fut._error = broken_err
        try:
            _dag_metrics()["inflight"].dec(len(self._pending))
        except Exception:
            pass
        self._pending = []
        raise broken_err

    # -- teardown ---------------------------------------------------------

    def teardown(self, timeout: float = 10.0) -> None:
        if self._torn_down:
            return
        # flag FIRST: concurrent getters observe it between drain slices,
        # settle their futures as torn-down, and release the drive lock —
        # which this method then takes so its output-ring drain never
        # interleaves with a getter's cursor
        self._torn_down = True
        import ray_tpu

        with self._drive_lock:
            self._teardown_locked(timeout, ray_tpu)

    def _teardown_locked(self, timeout: float, ray_tpu) -> None:
        deadline = time.monotonic() + timeout
        stop_sent = False
        try:
            self._input_channel.write(_Stop(), timeout=2.0)
            stop_sent = True
        except Exception:
            pass
        # Drain the output so stalled rings free up and _Stop can flow
        # (retrying the input _Stop while draining — a full input ring
        # un-fills as stages progress); force-stop channels whose writer
        # actor is already gone (their loop ref is resolved, so writing
        # from here cannot race them).
        pending = list(range(len(self._loop_refs)))
        while pending and time.monotonic() < deadline:
            if not stop_sent:
                try:
                    self._input_channel.write(_Stop(), timeout=0.1)
                    stop_sent = True
                except Exception:
                    pass
            try:
                self._output_channel.read(timeout=0.2)
                continue  # drained one buffered value; keep going
            except Exception:
                pass
            still = []
            for i in pending:
                try:
                    ready, _ = ray_tpu.wait([self._loop_refs[i]],
                                            timeout=0)
                except Exception:
                    ready = [self._loop_refs[i]]  # runtime gone: stop waiting
                if ready:
                    for ch in self._writer_channels.get(i, []):
                        try:
                            ch.write(_Stop(), timeout=0.5)
                        except Exception:
                            pass
                else:
                    still.append(i)
            pending = still
        try:
            ray_tpu.get(self._loop_refs,
                        timeout=max(0.5, deadline - time.monotonic()))
        except Exception:
            pass
        for ch in self._channels:
            ch.unlink()
        try:
            _dag_metrics()["inflight"].dec(len(self._pending))
        except Exception:
            pass
        self._pending = []

    def __del__(self):
        try:
            self.teardown()
        except Exception:
            pass
