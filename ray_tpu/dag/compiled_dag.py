"""CompiledDAG: channel-wired actor pipelines.

Role analog: ``python/ray/dag/compiled_dag_node.py:278``. Compilation
allocates one mutable shm channel per DAG edge and launches a long-running
exec loop inside every participating actor (the reference's per-actor exec
loops). After that, invoking the DAG is: driver writes the input channel →
each actor's loop reads its upstream channels, runs its methods, writes its
output channel → driver reads the final channel. No task submission, no
scheduler, no per-call allocation on the hot path.

The exec loop intentionally occupies the actor (submitted as a normal actor
call that only returns at teardown) — a compiled DAG takes ownership of its
actors, matching the reference's semantics.
"""

from __future__ import annotations

import uuid
from typing import Any, Dict, List, Optional

from ray_tpu.dag.dag_node import ClassMethodNode, DAGNode, InputNode
from ray_tpu.experimental.channel import Channel
from ray_tpu.experimental.device_channel import DeviceChannel, DeviceTensorType


class _Stop:
    """Teardown sentinel propagated through the pipeline."""


class _NodeError:
    def __init__(self, err: BaseException, node_repr: str):
        self.err = err
        self.node_repr = node_repr


class DAGExecutionError(RuntimeError):
    pass


def _dag_exec_loop(instance, stages: List[Dict[str, Any]]) -> int:
    """Runs inside the actor: per invocation, execute this actor's stages
    in topo order. ``stages``: [{method, in_channels: [(kind, key)],
    out_channel, consts}] where kind is "chan" | "const".
    """
    executed = 0
    chans: Dict[str, Channel] = {}

    def chan(name: str, kind: str = "obj") -> Channel:
        if name not in chans:
            cls = DeviceChannel if kind == "device" else Channel
            chans[name] = cls(name, create=False)
        return chans[name]

    while True:
        stop = False
        read_cache: Dict[str, Any] = {}  # one read per channel per tick
        for stage in stages:
            args = []
            err: Optional[_NodeError] = None
            for kind, key, *ck in stage["inputs"]:
                if kind == "const":
                    args.append(key)
                    continue
                if key in read_cache:
                    val = read_cache[key]
                else:
                    val = chan(key, ck[0] if ck else "obj").read()
                    read_cache[key] = val
                if isinstance(val, _Stop):
                    stop = True
                if isinstance(val, _NodeError):
                    err = val
                args.append(val)
            out = chan(stage["out"], stage.get("out_kind", "obj"))
            if stop:
                out.write(_Stop())
                continue
            if err is not None:
                out.write(err)   # propagate upstream failure
                continue
            try:
                method = getattr(instance, stage["method"])
                result = method(*args)
                out.write(result)
            except BaseException as e:  # noqa: BLE001 — shipped to driver
                out.write(_NodeError(e, stage["method"]))
        if stop:
            return executed
        executed += 1


class CompiledDAGFuture:
    def __init__(self, channel: Channel, dag: "CompiledDAG"):
        self._channel = channel
        self._dag = dag
        self._done = False
        self._result: Any = None

    def get(self, timeout: Optional[float] = 60.0) -> Any:
        if self._done:
            return self._result
        val = self._channel.read(timeout=timeout)
        self._done = True
        self._dag._pending = None
        if isinstance(val, _NodeError):
            raise DAGExecutionError(
                f"compiled DAG node {val.node_repr!r} failed") from val.err
        if isinstance(val, _Stop):
            raise DAGExecutionError("compiled DAG was torn down")
        self._result = val
        return val


class CompiledDAG:
    def __init__(self, output_node: DAGNode,
                 buffer_size_bytes: int = 1 << 20):
        self._output_node = output_node
        self._buffer = buffer_size_bytes
        self._channels: List[Channel] = []
        self._input_channel: Optional[Channel] = None
        self._output_channel: Optional[Channel] = None
        self._loop_refs: List[Any] = []
        self._torn_down = False
        self._pending: Optional[CompiledDAGFuture] = None
        self._compile()

    def _compile(self) -> None:
        order = self._output_node.topo_sort()
        inputs = [n for n in order if isinstance(n, InputNode)]
        if len(inputs) != 1:
            raise ValueError("compiled DAG needs exactly one InputNode")
        for n in order:
            if not isinstance(n, (InputNode, ClassMethodNode)):
                raise TypeError(
                    f"compiled DAGs support actor-method nodes only, got {n!r}")
            if isinstance(n, ClassMethodNode) and not n._upstream():
                raise ValueError(
                    f"{n!r} has no upstream nodes; compiled stages must be "
                    "driven by the input (teardown could never reach it)")
        uid = uuid.uuid4().hex[:8]

        # one channel per node output; DeviceTensorType-hinted edges get
        # the raw device-tensor channel (reference NCCL-channel role)
        chan_name: Dict[int, str] = {}
        chan_kind: Dict[int, str] = {}
        for i, n in enumerate(order):
            name = f"{uid}-{i}"
            chan_name[id(n)] = name
            kind = ("device" if isinstance(getattr(n, "_type_hint", None),
                                           DeviceTensorType) else "obj")
            chan_kind[id(n)] = kind
            cls = DeviceChannel if kind == "device" else Channel
            ch = cls(name, capacity=self._buffer, create=True)
            self._channels.append(ch)
            if isinstance(n, InputNode):
                self._input_channel = ch
        self._output_channel = self._channels[
            [id(n) for n in order].index(id(self._output_node))]

        # group stages by actor, preserving topo order
        by_actor: Dict[Any, List[Dict[str, Any]]] = {}
        for n in order:
            if isinstance(n, InputNode):
                continue
            inputs_desc = []
            for a in n.args:
                if isinstance(a, DAGNode):
                    inputs_desc.append(("chan", chan_name[id(a)],
                                        chan_kind[id(a)]))
                else:
                    inputs_desc.append(("const", a))
            if n.kwargs:
                raise TypeError("compiled DAGs do not support kwargs binds")
            by_actor.setdefault(n.actor, []).append({
                "method": n.method_name,
                "inputs": inputs_desc,
                "out": chan_name[id(n)],
                "out_kind": chan_kind[id(n)],
            })

        for actor, stages in by_actor.items():
            self._loop_refs.append(
                actor.__rtpu_call__.remote(_dag_exec_loop, stages))

    # -- invocation -------------------------------------------------------

    def execute(self, input_value: Any) -> CompiledDAGFuture:
        if self._torn_down:
            raise DAGExecutionError("DAG already torn down")
        # Channels are single-slot: one execution may be in flight. A second
        # write would silently overwrite the unread input (and the caller's
        # first future would read the wrong result), so enforce consumption.
        if self._pending is not None and not self._pending._done:
            raise DAGExecutionError(
                "previous execute() result not consumed yet; call .get() "
                "on it first (compiled channels hold one value)")
        self._input_channel.write(input_value)
        fut = CompiledDAGFuture(self._output_channel, self)
        self._pending = fut
        return fut

    def teardown(self) -> None:
        if self._torn_down:
            return
        self._torn_down = True
        try:
            self._input_channel.write(_Stop())
            import ray_tpu

            ray_tpu.get(self._loop_refs, timeout=10)
        except Exception:
            pass
        for ch in self._channels:
            ch.unlink()

    def __del__(self):
        try:
            self.teardown()
        except Exception:
            pass
