"""ray_tpu.dag — lazy DAGs of actor-method calls + compiled execution.

Role analog: ``python/ray/dag`` (``dag_node.py``, ``compiled_dag_node.py:278``).
Build a graph with ``InputNode`` and ``ActorMethod.bind``; ``execute`` runs
it as ordinary actor calls; ``experimental_compile`` pre-allocates mutable
shm channels per edge and starts an exec-loop thread inside each actor, so
repeated invocations bypass task submission entirely — the driver writes
the input channel and reads the output channel.
"""

from ray_tpu.dag.dag_node import (
    DAGNode,
    InputNode,
    ClassMethodNode,
    FunctionNode,
)
from ray_tpu.dag.compiled_dag import CompiledDAG

__all__ = [
    "DAGNode",
    "InputNode",
    "ClassMethodNode",
    "FunctionNode",
    "CompiledDAG",
]
