"""ray_tpu.dag — lazy DAGs of actor-method calls + compiled execution.

Role analog: ``python/ray/dag`` (``dag_node.py``, ``compiled_dag_node.py:278``).
Build a graph with ``InputNode`` and ``ActorMethod.bind``; ``execute`` runs
it as ordinary actor calls; ``experimental_compile`` pre-allocates mutable
shm RING channels per edge (``max_in_flight + 1`` slots) and starts an
exec loop inside each actor, so repeated invocations bypass task
submission entirely — the driver writes the input channel and reads the
output channel, with up to ``max_in_flight`` invocations overlapping and
strict FIFO result delivery.
"""

from ray_tpu.dag.dag_node import (
    DAGNode,
    InputNode,
    ClassMethodNode,
    FunctionNode,
)
from ray_tpu.dag.compiled_dag import (
    CompiledDAG,
    CompiledDAGFuture,
    DAGBackpressureError,
    DAGExecutionError,
)

__all__ = [
    "DAGNode",
    "InputNode",
    "ClassMethodNode",
    "FunctionNode",
    "CompiledDAG",
    "CompiledDAGFuture",
    "DAGBackpressureError",
    "DAGExecutionError",
]
