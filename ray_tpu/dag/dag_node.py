"""Lazy DAG nodes.

Role analog: ``python/ray/dag/{dag_node,input_node,class_node}.py``. A node
is (callable target, upstream args); ``execute`` resolves bottom-up through
ordinary task/actor calls.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple


class DAGNode:
    _type_hint = None  # set by with_type_hint / with_tensor_transport

    def with_type_hint(self, hint) -> "DAGNode":
        """Annotate this node's OUTPUT edge (reference
        ``node.with_type_hint(TorchTensorType())``): a
        :class:`~ray_tpu.experimental.device_channel.DeviceTensorType`
        makes the compiled channel carry raw device-tensor bytes."""
        self._type_hint = hint
        return self

    def with_tensor_transport(self, device: str = None) -> "DAGNode":
        """Reference ``with_tensor_transport`` sugar for the device type."""
        from ray_tpu.experimental.device_channel import DeviceTensorType

        return self.with_type_hint(DeviceTensorType(device))

    def _upstream(self) -> List["DAGNode"]:
        out = []
        for a in list(getattr(self, "args", ())) + \
                list(getattr(self, "kwargs", {}).values()):
            if isinstance(a, DAGNode):
                out.append(a)
        return out

    def topo_sort(self) -> List["DAGNode"]:
        order: List[DAGNode] = []
        seen = set()

        def visit(n: "DAGNode"):
            if id(n) in seen:
                return
            seen.add(id(n))
            for u in n._upstream():
                visit(u)
            order.append(n)

        visit(self)
        return order

    def execute(self, *input_args) -> Any:
        """Eager execution through normal task/actor submission; returns the
        final ObjectRef (or value for InputNode)."""
        import ray_tpu

        values: Dict[int, Any] = {}
        for node in self.topo_sort():
            if isinstance(node, InputNode):
                values[id(node)] = input_args[0] if len(input_args) == 1 \
                    else input_args
                continue
            args = [values[id(a)] if isinstance(a, DAGNode) else a
                    for a in node.args]
            kwargs = {k: values[id(v)] if isinstance(v, DAGNode) else v
                      for k, v in node.kwargs.items()}
            values[id(node)] = node._invoke(args, kwargs)
        return values[id(self)]

    def experimental_compile(self, **kwargs):
        from ray_tpu.dag.compiled_dag import CompiledDAG

        return CompiledDAG(self, **kwargs)


class InputNode(DAGNode):
    """The DAG's runtime input placeholder. Supports context-manager use
    (reference style: ``with InputNode() as inp: ...``)."""

    args: Tuple = ()
    kwargs: Dict[str, Any] = {}

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class ClassMethodNode(DAGNode):
    def __init__(self, actor_handle, method_name: str,
                 args: Tuple, kwargs: Dict[str, Any]):
        self.actor = actor_handle
        self.method_name = method_name
        self.args = args
        self.kwargs = kwargs

    def _invoke(self, args, kwargs):
        return getattr(self.actor, self.method_name).remote(*args, **kwargs)

    def __repr__(self):
        return f"ClassMethodNode({self.method_name} on {self.actor})"


class FunctionNode(DAGNode):
    """A remote-function DAG node (``fn.bind`` analog)."""

    def __init__(self, remote_fn, args: Tuple, kwargs: Dict[str, Any]):
        self.fn = remote_fn
        self.args = args
        self.kwargs = kwargs

    def _invoke(self, args, kwargs):
        return self.fn.remote(*args, **kwargs)

    def __repr__(self):
        return f"FunctionNode({getattr(self.fn, '__name__', self.fn)})"
