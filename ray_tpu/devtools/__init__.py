"""Developer tooling that ships with the tree (linters, codegen).

Nothing here is imported by the runtime — keep it free of jax and of any
import with side effects so ``make lint`` stays cheap under the axon
sitecustomize.
"""
