"""Elastic-training discipline rules (family ``invariants``).

Elastic membership (ISSUE 20) makes ``world_size``/``world_rank`` a
per-session fact: a preemption fences the gang and re-forms it at a new
size, renumbering every rank. Code that freezes a world-size/rank read
into state that outlives the session — module globals, class attributes,
def-time default arguments, or a closure that a later session re-enters
— computes with the OLD membership after a re-form (wrong LR/batch
rescale, wrong shard arithmetic: the classic elastic-training bug). The
contract is to re-read from :class:`~ray_tpu.train.session.TrainContext`
at use time, every session.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional

from ray_tpu.devtools.graftlint.engine import Project
from ray_tpu.devtools.graftlint.model import (
    FAMILY_INVARIANTS,
    Finding,
    Rule,
    register,
)

#: TrainContext membership attributes that change across re-forms
_ATTRS = {"world_size", "world_rank", "local_rank", "local_world_size"}
#: ... and their accessor twins
_GETTERS = {"get_world_size", "get_world_rank", "get_local_rank",
            "get_local_world_size"}
#: the definition site itself (TrainContext stores these fields; the
#: executor stamps them per session)
_EXEMPT = ("ray_tpu/train/session.py",)

_FN_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _reads_membership(node: ast.AST) -> Optional[int]:
    """Line of the first world-size/rank read inside ``node``, else
    None. A read is an ``.world_size``-style attribute access or a
    ``get_world_size()``-style accessor call."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in _ATTRS \
                and isinstance(sub.ctx, ast.Load):
            return sub.lineno
        if isinstance(sub, ast.Call):
            f = sub.func
            if isinstance(f, ast.Attribute) and f.attr in _GETTERS:
                return sub.lineno
            if isinstance(f, ast.Name) and f.id in _GETTERS:
                return sub.lineno
    return None


@register
class StaleWorldSize(Rule):
    name = "stale-world-size"
    family = FAMILY_INVARIANTS
    summary = ("world_size/rank is re-read from TrainContext at use "
               "time — never frozen into module/class state, function "
               "defaults, or closures (elastic re-forms renumber ranks "
               "and resize the world between sessions)")

    def check(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules:
            if mod.scope_rel in _EXEMPT:
                continue
            yield from self._module_and_class_state(mod)
            yield from self._def_time_defaults(mod)
            yield from self._closure_captures(mod)

    # -- module / class state ----------------------------------------------

    def _module_and_class_state(self, mod) -> Iterator[Finding]:
        scopes = [("module", mod.tree.body)]
        scopes += [("class", node.body) for node in ast.walk(mod.tree)
                   if isinstance(node, ast.ClassDef)]
        for kind, body in scopes:
            for stmt in body:
                value = None
                if isinstance(stmt, (ast.Assign, ast.AugAssign)):
                    value = stmt.value
                elif isinstance(stmt, ast.AnnAssign):
                    value = stmt.value
                if value is None:
                    continue
                ln = _reads_membership(value)
                if ln is not None:
                    yield self.finding(
                        mod, stmt.lineno,
                        f"world_size/rank captured into {kind} state — "
                        "it outlives the training session, and an "
                        "elastic re-form changes both; read it from "
                        "TrainContext at use time instead")

    # -- def-time default arguments ----------------------------------------

    def _def_time_defaults(self, mod) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, _FN_DEFS):
                continue
            defaults = list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None]
            for d in defaults:
                ln = _reads_membership(d)
                if ln is not None:
                    yield self.finding(
                        mod, ln,
                        "world_size/rank read in a default argument — "
                        "defaults evaluate ONCE at def time, so every "
                        "call after an elastic re-form sees the old "
                        "membership; read it inside the function body")

    # -- closure captures ---------------------------------------------------

    def _closure_captures(self, mod) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._scan_function(mod, node)

    def _scan_function(self, mod, fn) -> Iterator[Finding]:
        """Flag ``ws = ctx.world_size`` bindings that a NESTED function
        then reads: the closure cell freezes the value, and closures are
        exactly what outlives a session (callbacks, jitted step fns,
        generators handed to the loop)."""
        nested: List[ast.AST] = []
        captured: Dict[str, int] = {}

        def visit(node: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, _FN_DEFS):
                    nested.append(child)
                    continue
                if isinstance(child, ast.Assign) \
                        and _reads_membership(child.value) is not None:
                    for tgt in child.targets:
                        if isinstance(tgt, ast.Name):
                            captured.setdefault(tgt.id, child.lineno)
                elif (isinstance(child, ast.AnnAssign)
                        and child.value is not None
                        and _reads_membership(child.value) is not None
                        and isinstance(child.target, ast.Name)):
                    captured.setdefault(child.target.id, child.lineno)
                visit(child)

        visit(fn)
        if not captured or not nested:
            return
        loaded = set()
        for nd in nested:
            for sub in ast.walk(nd):
                if isinstance(sub, ast.Name) \
                        and isinstance(sub.ctx, ast.Load):
                    loaded.add(sub.id)
        for name, ln in sorted(captured.items(), key=lambda kv: kv[1]):
            if name in loaded:
                yield self.finding(
                    mod, ln,
                    f"'{name}' binds a world_size/rank read and is "
                    "captured by a nested function — the closure cell "
                    "freezes pre-re-form membership; pass it as an "
                    "argument or re-read from TrainContext inside")
