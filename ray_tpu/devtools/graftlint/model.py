"""graftlint data model: findings, rules, and the rule registry.

A *rule* is a named check over the analyzed project (see
``engine.Project``). Rules are grouped into *families* — the unit
``tests/test_invariants.py`` asserts on — and every rule must ship at
least one positive and one negative fixture under
``tests/graftlint_fixtures/<rule>/`` (self-checked by
``tests/test_graftlint.py``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterable, Iterator, List

#: rule families (stable names: test_invariants keys off them)
FAMILY_LOCKS = "locks"
FAMILY_JAX = "jax"
FAMILY_LAYERING = "layering"
FAMILY_INVARIANTS = "invariants"
FAMILY_FAILPOINTS = "failpoints"
FAMILY_META = "meta"
#: whole-program families (ISSUE 15): cross-file analyses over the
#: already-built per-module models
FAMILY_PROTOCOL = "protocol"
FAMILY_LIFECYCLE = "lifecycle"
FAMILY_LOCKGRAPH = "lockgraph"

FAMILIES = (FAMILY_LOCKS, FAMILY_JAX, FAMILY_LAYERING, FAMILY_INVARIANTS,
            FAMILY_FAILPOINTS, FAMILY_META, FAMILY_PROTOCOL,
            FAMILY_LIFECYCLE, FAMILY_LOCKGRAPH)


@dataclass(frozen=True)
class Finding:
    """One violation. Rendered as ``path:line RULE message``."""

    path: str  # display path (repo-relative when detectable)
    line: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line} {self.rule} {self.message}"

    def sort_key(self):
        return (self.path, self.line, self.rule, self.message)


class Rule:
    """Base class. Subclasses set ``name``/``family``/``summary`` and
    implement :meth:`check`.

    ``summary`` is the one-line catalog entry (README table); keep it a
    statement of the invariant, not of the implementation.
    """

    name: str = ""
    family: str = ""
    summary: str = ""
    #: rules about suppressions themselves must not be suppressible —
    #: otherwise 'disable=all' with no reason silences its own finding
    suppressible: bool = True

    def check(self, project) -> Iterator[Finding]:  # pragma: no cover
        raise NotImplementedError

    def finding(self, module, line: int, message: str) -> Finding:
        return Finding(module.display, line, self.name, message)


_REGISTRY: Dict[str, Rule] = {}


def register(cls):
    """Class decorator: instantiate and register a rule."""
    inst = cls()
    if not inst.name or not inst.family or not inst.summary:
        raise ValueError(f"rule {cls.__name__} missing name/family/summary")
    if inst.name in _REGISTRY:
        raise ValueError(f"duplicate rule name {inst.name}")
    if inst.family not in FAMILIES:
        raise ValueError(f"rule {inst.name}: unknown family {inst.family}")
    _REGISTRY[inst.name] = inst
    return cls


def _load_rule_modules() -> None:
    # import for registration side effect; cheap (stdlib-only modules)
    from ray_tpu.devtools.graftlint import (  # noqa: F401
        rules_events,
        rules_failpoints,
        rules_invariants,
        rules_jax,
        rules_layering,
        rules_lifecycle,
        rules_lockgraph,
        rules_locks,
        rules_meta,
        rules_profiling,
        rules_protocol,
        rules_tracing,
        rules_train,
    )


def all_rules() -> List[Rule]:
    _load_rule_modules()
    return [_REGISTRY[k] for k in sorted(_REGISTRY)]


def get_rule(name: str) -> Rule:
    _load_rule_modules()
    return _REGISTRY[name]


def rule_names() -> List[str]:
    return [r.name for r in all_rules()]


def select_rules(names: Iterable[str] = (),
                 families: Iterable[str] = ()) -> List[Rule]:
    """Rules filtered by explicit names and/or families (empty = all)."""
    rules = all_rules()
    names, families = set(names), set(families)
    unknown = names - {r.name for r in rules}
    if unknown:
        raise KeyError(f"unknown rule(s): {sorted(unknown)}")
    bad_fams = families - set(FAMILIES)
    if bad_fams:
        raise KeyError(f"unknown family(ies): {sorted(bad_fams)}")
    if not names and not families:
        return rules
    return [r for r in rules
            if r.name in names or r.family in families]
