"""Trace-plane discipline rules (family ``invariants``).

The trace plane (ISSUE 7) is only as analyzable as its span names: the
critical-path analyzer, the Perfetto export's categories, and operators
grepping ``/api/traces`` all key off the ``<layer>::<what>`` catalog in
``util/tracing.py``'s docstring. And the ``span()`` context is
THREAD-LOCAL — held open across a ``yield`` it leaks onto whatever the
worker thread runs next, silently mis-parenting every later span. Mirrors
the failpoint-sites literal+unique+doc-sync pattern.
"""

from __future__ import annotations

import ast
import re
from collections import defaultdict
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ray_tpu.devtools.graftlint.engine import Project
from ray_tpu.devtools.graftlint.model import (
    FAMILY_INVARIANTS,
    Finding,
    Rule,
    register,
)

TRACING_MOD = "ray_tpu/util/tracing.py"
_SPAN_FNS = ("span", "manual_span", "record_span")
_NAME_RE = re.compile(r"^[a-z0-9_.]+::[a-z0-9_.]+$")
_PREFIX_RE = re.compile(r"^[a-z0-9_.]+::$")
_CATALOG_LINE = re.compile(r"^\s{4}([a-z0-9_.]+::[a-z0-9_.<>]*)\s{2,}\S")


def documented_span_names(tracing_source: str
                          ) -> Tuple[Set[str], Set[str]]:
    """(exact names, dynamic prefixes) from the ``Span names`` block of
    util/tracing.py's docstring. An entry like ``lock::<name>`` documents
    the prefix ``lock::``; ``serve.handle::route`` documents itself."""
    tree = ast.parse(tracing_source)
    doc = ast.get_docstring(tree) or ""
    names: Set[str] = set()
    prefixes: Set[str] = set()
    in_block = False
    seen_entry = False
    for line in doc.splitlines():
        if line.startswith("Span names"):
            in_block = True
            continue
        if in_block:
            m = _CATALOG_LINE.match(line)
            if m:
                seen_entry = True
                entry = m.group(1)
                if "<" in entry:
                    prefixes.add(entry.split("<", 1)[0])
                else:
                    names.add(entry)
            elif seen_entry and line.strip() and not line.startswith(" "):
                break  # next top-level section (after the entries)
    return names, prefixes


def _is_span_call(cs) -> Optional[str]:
    """The span-API function name when ``cs`` records spans, else None."""
    if cs.fq and cs.fq.startswith("ray_tpu.util.tracing."):
        fn = cs.fq.rsplit(".", 1)[1]
        return fn if fn in _SPAN_FNS else None
    if (cs.parts and len(cs.parts) >= 2 and cs.parts[-2] == "tracing"
            and cs.parts[-1] in _SPAN_FNS):
        return cs.parts[-1]
    return None


def _span_name_arg(node: ast.Call):
    """(kind, value): ('literal', name) for a str constant,
    ('prefix', p) for an f-string with a literal ``<layer>::`` head,
    (None, None) otherwise."""
    if not node.args:
        return None, None
    arg = node.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return "literal", arg.value
    if isinstance(arg, ast.JoinedStr) and arg.values:
        head = arg.values[0]
        if isinstance(head, ast.Constant) and isinstance(head.value, str) \
                and head.value.endswith("::"):
            return "prefix", head.value
    return None, None


@register
class TracingSpanNames(Rule):
    name = "tracing-span-names"
    family = FAMILY_INVARIANTS
    summary = ("tracing span/manual_span/record_span names are literal "
               "<layer>::<what> strings (or f-strings behind a literal "
               "<layer>:: prefix), unique per call site for exact names, "
               "and present in util/tracing.py's Span-names catalog")

    def check(self, project: Project) -> Iterator[Finding]:
        tr_mod = project.module(TRACING_MOD)
        documented = (documented_span_names(tr_mod.source)
                      if tr_mod is not None else None)
        literals: Dict[str, List[Tuple]] = defaultdict(list)
        used_prefixes: Set[str] = set()
        for mod in project.modules:
            if mod.scope_rel == TRACING_MOD:
                continue
            for cs in mod.calls:
                fn = _is_span_call(cs)
                if fn is None:
                    continue
                kind, value = _span_name_arg(cs.node)
                if kind is None:
                    yield self.finding(
                        mod, cs.line,
                        f"tracing.{fn}() with a non-literal name — span "
                        "names must be string literals (or f-strings "
                        "with a literal '<layer>::' prefix) so the "
                        "catalog, Perfetto categories, and critical-path "
                        "labels stay greppable")
                    continue
                if kind == "literal":
                    if not _NAME_RE.match(value):
                        yield self.finding(
                            mod, cs.line,
                            f"span name {value!r} does not follow the "
                            "'<layer>::<what>' convention "
                            "(lowercase dotted layer, '::', what)")
                        continue
                    literals[value].append((mod, cs.line))
                else:
                    if not _PREFIX_RE.match(value):
                        yield self.finding(
                            mod, cs.line,
                            f"span name prefix {value!r} does not follow "
                            "the '<layer>::' convention")
                        continue
                    used_prefixes.add(value)
                    if documented is not None and value not in documented[1]:
                        yield self.finding(
                            mod, cs.line,
                            f"span prefix '{value}<...>' is not in util/"
                            "tracing.py's Span-names catalog — add it "
                            "(the docstring is what operators and the "
                            "analyzers read)")
        for name, uses in sorted(literals.items()):
            if len(uses) > 1:
                locs = ", ".join(f"{m.display}:{ln}" for m, ln in uses)
                for m, ln in uses:
                    yield self.finding(
                        m, ln,
                        f"span name '{name}' is recorded from "
                        f"{len(uses)} call sites ({locs}) — exact names "
                        "are unique per call site so timeline segments "
                        "stay attributable; add a suffixed name")
            if documented is not None and name not in documented[0]:
                m, ln = uses[0]
                yield self.finding(
                    m, ln,
                    f"span name '{name}' is not in util/tracing.py's "
                    "Span-names catalog — add it there")
        if documented is not None and tr_mod is not None \
                and project.whole_package:
            stale = (documented[0] - set(literals)) | {
                p for p in documented[1] if p not in used_prefixes}
            for entry in sorted(stale):
                yield self.finding(
                    tr_mod, 1,
                    f"documented span name '{entry}' has no recording "
                    "call site left in the tree — remove it from the "
                    "Span-names catalog or restore the span")


def _yields_in_body(body: List[ast.stmt]) -> Optional[int]:
    """Line of the first yield lexically inside ``body``, not crossing
    into nested function/lambda scopes (their yields are other frames,
    executed after the with block exited)."""
    stack: List[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.Yield, ast.YieldFrom)):
            return node.lineno
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue  # prune nested scopes
        stack.extend(ast.iter_child_nodes(node))
    return None


@register
class TracingContextCapture(Rule):
    name = "tracing-context-capture"
    family = FAMILY_INVARIANTS
    summary = ("the thread-local span() context is never held open "
               "across a yield (generators must use manual_span/"
               "record_span), and tracing._ctx is never touched outside "
               "util/tracing.py")

    def check(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules:
            if mod.scope_rel == TRACING_MOD:
                continue
            # gate: both findings need the tracing module or a span
            # callable in scope — skip the full-module walk elsewhere
            if not ("tracing" in mod.imports or "span" in mod.imports
                    or "span" in mod.functions):
                continue
            for node in ast.walk(mod.tree):
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    if not any(
                            isinstance(item.context_expr, ast.Call)
                            and _is_span_call_node(mod, item.context_expr)
                            for item in node.items):
                        continue
                    line = _yields_in_body(node.body)
                    if line is not None:
                        yield self.finding(
                            mod, line,
                            "yield inside a `with tracing.span(...)` "
                            "body: the span context is thread-local and "
                            "leaks onto whatever this thread runs next "
                            "while the generator is suspended — record "
                            "the span with tracing.manual_span()/"
                            "record_span() instead")
                elif isinstance(node, ast.Attribute) \
                        and node.attr == "_ctx":
                    val = node.value
                    if isinstance(val, ast.Name) and val.id == "tracing":
                        yield self.finding(
                            mod, node.lineno,
                            "direct access to tracing._ctx outside util/"
                            "tracing.py — span context must re-enter "
                            "through the public tracing API "
                            "(current_traceparent()/span(parent=...))")


def _is_span_call_node(mod, call: ast.Call) -> bool:
    """Is this Call expression ``tracing.span(...)``? (context managers
    in With items are not in mod.calls' resolved index reliably, so
    match on the raw dotted parts.)"""
    from ray_tpu.devtools.graftlint.engine import dotted_parts

    parts = dotted_parts(call.func)
    if not parts:
        return False
    return (parts[-1] == "span"
            and (len(parts) == 1 or parts[-2] == "tracing"
                 or mod.resolve_parts(list(parts)) ==
                 "ray_tpu.util.tracing.span"))
