"""AST ports of the architecture invariants (family ``invariants``).

Each rule here supersedes a regex grep that used to live in
``tests/test_invariants.py``. The AST versions are alias-aware, survive
multi-line call sites, and — unlike the greps — know the difference
between ``collections.Counter`` and a metrics ``Counter``.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ray_tpu.devtools.graftlint.engine import Project
from ray_tpu.devtools.graftlint.model import (
    FAMILY_INVARIANTS,
    Finding,
    Rule,
    register,
)


@register
class PipeReceiverDiscipline(Rule):
    name = "pipe-receiver-discipline"
    family = FAMILY_INVARIANTS
    summary = ("one receiver thread demuxes each worker pipe: .recv()/"
               ".recv_bytes() only in worker._recv_loop, runtime's "
               "_accept_loop handshake + _reader_loop, and rpc.py's "
               "reader machinery")

    #: scope_rel -> function names allowed to block on a pipe read
    ALLOWED = {
        "ray_tpu/core/worker.py": {"_recv_loop"},
        "ray_tpu/core/runtime.py": {"_accept_loop", "_reader_loop"},
    }
    #: in cluster/, only rpc.py's reader machinery may block on a socket
    CLUSTER_ALLOWED = {"_recv_framed", "_client_handshake"}

    def check(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules:
            allowed = self.ALLOWED.get(mod.scope_rel)
            in_cluster = mod.scope_rel.startswith("ray_tpu/cluster/")
            if allowed is None and not in_cluster:
                continue
            if allowed is None:
                if mod.scope_rel == "ray_tpu/cluster/rpc.py":
                    allowed = self.CLUSTER_ALLOWED
                else:
                    allowed = set()
            for cs in mod.calls:
                if not cs.parts or cs.parts[-1] not in ("recv",
                                                        "recv_bytes"):
                    continue
                func_name = cs.func.rpartition(".")[2] or cs.func
                if func_name in allowed:
                    continue
                yield self.finding(
                    mod, cs.line,
                    f"{'.'.join(cs.parts)}() in {cs.func}() — a second "
                    f"pipe reader races the demux thread and corrupts "
                    f"reply routing (CLAUDE.md one-receiver-thread "
                    f"invariant); route new message kinds through the "
                    f"existing reader ({', '.join(sorted(allowed)) or 'rpc.py'})")


@register
class CloudpickleFirst(Rule):
    name = "cloudpickle-first"
    family = FAMILY_INVARIANTS
    summary = ("serialization.serialize tries cloudpickle FIRST — plain "
               "pickle serializes __main__ functions by reference and "
               "breaks workers")

    def check(self, project: Project) -> Iterator[Finding]:
        mod = project.module("ray_tpu/core/serialization.py")
        if mod is None:
            return
        dumps = []
        for cs in mod.calls:
            if cs.func.rpartition(".")[2] != "serialize":
                continue
            if cs.parts and cs.parts[-1] == "dumps":
                dumps.append(cs)
        if not dumps:
            yield self.finding(
                mod, 1,
                "serialize() no longer calls any .dumps — the "
                "cloudpickle-first invariant can't be verified")
            return
        first = min(dumps, key=lambda c: c.line)
        fq = first.fq or ".".join(first.parts)
        if not fq.startswith("cloudpickle."):
            yield self.finding(
                mod, first.line,
                f"serialize()'s first serializer is {fq} — cloudpickle "
                f"must come FIRST (plain pickle serializes __main__ "
                f"functions by reference and breaks workers)")


@register
class AdhocMetric(Rule):
    name = "adhoc-metric"
    family = FAMILY_INVARIANTS
    summary = ("core/ and cluster/ create metrics only via "
               "metric_defs.get — ad-hoc Counter/Gauge/Histogram "
               "instances skip the help/prefix/uniqueness invariants and "
               "the generated README table")

    _SCOPES = ("ray_tpu/core/", "ray_tpu/cluster/")
    _METRIC_FQS = {f"ray_tpu.util.metrics.{n}"
                   for n in ("Counter", "Gauge", "Histogram")}

    def check(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules:
            if not mod.scope_rel.startswith(self._SCOPES):
                continue
            for cs in mod.calls:
                if cs.fq in self._METRIC_FQS:
                    yield self.finding(
                        mod, cs.line,
                        f"ad-hoc {cs.fq.rpartition('.')[2]}() in core/"
                        f"cluster — define it in ray_tpu/util/"
                        f"metric_defs.py and fetch with "
                        f"metric_defs.get(name) so it lands in the "
                        f"generated README reference")


@register
class UndeadlinedWait(Rule):
    name = "undeadlined-wait"
    family = FAMILY_INVARIANTS
    summary = ("cluster-plane blocking waits carry deadlines: no bare "
               "event/condition .wait() in cluster/ — a wedged peer must "
               "surface a timeout, never park a thread forever")

    def _event_like(self, mod, ci, parts) -> bool:
        """Known Event/Condition attr, or an event-ish name."""
        import re

        name = parts[-2] if len(parts) >= 2 else parts[0]
        if (ci is not None and parts[0] == "self" and len(parts) == 3
                and parts[1] in ci.locks):
            return True
        return bool(re.search(
            r"(^|_)(ev|event|stop|cv|cond|ready|done|flag)\w*$", name))

    def check(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules:
            if not mod.scope_rel.startswith("ray_tpu/cluster/"):
                continue
            for cs in mod.calls:
                if not cs.parts or cs.parts[-1] != "wait":
                    continue
                # a real deadline: any arg/keyword that is not literal
                # None (wait(None) / wait(timeout=None) still block
                # forever)
                deadline = [a for a in cs.node.args
                            if not (isinstance(a, ast.Constant)
                                    and a.value is None)]
                deadline += [k for k in cs.node.keywords
                             if not (isinstance(k.value, ast.Constant)
                                     and k.value.value is None)]
                if deadline:
                    continue
                ci = mod.classes.get(cs.func.split(".")[0])
                if not self._event_like(mod, ci, list(cs.parts)):
                    continue
                yield self.finding(
                    mod, cs.line,
                    f"bare {'.'.join(cs.parts)}() in cluster/ — pass a "
                    f"timeout (and loop) so a wedged peer can't park "
                    f"this thread forever (chaos-plane invariant, "
                    f"ISSUE 5)")
