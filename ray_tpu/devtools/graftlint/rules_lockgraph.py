"""Global lock-order graph (family ``lockgraph``, ISSUE 15).

The per-class ``lock-order-inversion`` rule (rules_locks) sees two
locks of ONE class acquired in both orders. This rule merges every
module's held->acquired pairs into one directed graph over qualified
lock names — ``pkg.module.Class.attr`` for instance locks, the
import-resolved fully-qualified name for module-level locks (so
``from x import _lock`` references land on the same node as the
definition) — and reports every cycle, with a witness (file:line) for
each edge.

That catches what the per-class view structurally cannot: a 3+-cycle
inside one class (A->B, B->C, C->A never inverts any single pair), and
cross-class/cross-module cycles through shared module-level locks.
2-cycles whose edges both come from the same class are left to the
per-class rule (same finding, better message).

Edges come from direct lexical nesting only (``with a: ... with b:``),
the same evidence the engine already collects — call-through edges stay
per-class where the self-call graph is reliable.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Tuple

from ray_tpu.devtools.graftlint.engine import ModuleIndex, Project
from ray_tpu.devtools.graftlint.model import (
    FAMILY_LOCKGRAPH,
    Finding,
    Rule,
    register,
)

#: edge value: (display path, line, owning-class name or "")
_Witness = Tuple[str, int, str]


def _qualify(key: str, mod: ModuleIndex, class_name: str) -> str:
    if key.startswith("self."):
        return f"{mod.module_name}.{class_name}{key[4:]}"
    if "." not in key:
        # module-level lock: resolve through imports so the defining
        # module and its importers share one node
        fq = mod.imports.get(key)
        return fq if fq else f"{mod.module_name}.{key}"
    # x.y.lock style: scope to the module (no reliable cross-module
    # identity for attribute paths)
    return f"{mod.module_name}:{key}"


def _edges(project: Project) -> Dict[str, Dict[str, _Witness]]:
    adj: Dict[str, Dict[str, _Witness]] = {}
    for mod in project.modules:
        sources = [("", mod.lock_pairs)]
        sources += [(ci.name, ci.lock_pairs)
                    for ci in mod.classes.values()]
        for cname, pairs in sources:
            for outer, inner, line, _via in pairs:
                if outer == inner:
                    continue  # re-entrant acquire, not an ordering edge
                a = _qualify(outer, mod, cname)
                b = _qualify(inner, mod, cname)
                if a == b:
                    continue
                adj.setdefault(a, {}).setdefault(
                    b, (mod.display, line, cname))
    return adj


def _sccs(adj: Dict[str, Dict[str, _Witness]]) -> List[List[str]]:
    """Iterative Tarjan; returns SCCs with >= 2 nodes."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Dict[str, bool] = {}
    stack: List[str] = []
    out: List[List[str]] = []
    counter = [0]

    for root in sorted(adj):
        if root in index:
            continue
        work = [(root, iter(sorted(adj.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack[root] = True
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack[w] = True
                    work.append((w, iter(sorted(adj.get(w, ())))))
                    advanced = True
                    break
                if on_stack.get(w):
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack[w] = False
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) > 1:
                    out.append(sorted(comp))
    return out


def _shortest_cycle(adj: Dict[str, Dict[str, _Witness]],
                    comp: List[str]) -> Optional[List[str]]:
    """Shortest cycle through comp[0], edges restricted to the SCC."""
    nodes = set(comp)
    start = comp[0]
    prev: Dict[str, Optional[str]] = {start: None}
    frontier = [start]
    while frontier:
        nxt: List[str] = []
        for v in frontier:
            for w in sorted(adj.get(v, ())):
                if w not in nodes:
                    continue
                if w == start:
                    path = [v]
                    while prev[path[-1]] is not None:
                        path.append(prev[path[-1]])
                    return path[::-1] + [start]
                if w not in prev:
                    prev[w] = v
                    nxt.append(w)
        frontier = nxt
    return None


@register
class GlobalLockOrder(Rule):
    name = "global-lock-order"
    family = FAMILY_LOCKGRAPH
    summary = ("the whole-program held->acquired lock graph must be "
               "acyclic — any cycle (including 3+-cycles and cross-"
               "module cycles invisible to the per-class inversion "
               "rule) is a deadlock candidate; reported with a witness "
               "acquisition site per edge")

    def check(self, project: Project) -> Iterator[Finding]:
        adj = _edges(project)
        for comp in _sccs(adj):
            cycle = _shortest_cycle(adj, comp)
            if cycle is None:  # pragma: no cover - SCC>1 implies a cycle
                continue
            edges = [(cycle[i], cycle[i + 1],
                      adj[cycle[i]][cycle[i + 1]])
                     for i in range(len(cycle) - 1)]
            classes = {(w[0], w[2]) for _, _, w in edges}
            if len(edges) == 2 and len(classes) == 1 and edges[0][2][2]:
                # plain two-lock inversion inside one class: the
                # per-class rule owns that finding
                continue
            desc = "; ".join(
                f"{a.rsplit('.', 1)[-1]} -> {b.rsplit('.', 1)[-1]} "
                f"({w[0]}:{w[1]})" for a, b, w in edges)
            first = edges[0][2]
            mod = next(m for m in project.modules
                       if m.display == first[0])
            yield self.finding(
                mod, first[1],
                f"lock-order cycle across "
                f"{len({n for a, b, _ in edges for n in (a, b)})} locks: "
                f"{desc} — inconsistent global order deadlocks under "
                f"contention; pick one order")
