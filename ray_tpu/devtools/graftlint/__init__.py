"""graftlint — AST-based architecture linter for ray_tpu.

Rule families (see the generated catalog in README "Static analysis"):

- ``locks``      lock discipline / race detection (static twin of the
                 runtime contention profiler)
- ``jax``        JAX/TPU call discipline (VJP-safe attention, timing
                 barriers, JAX_PLATFORMS hygiene, worker-boot cost)
- ``layering``   the ML-libraries-over-public-API portability seam
- ``invariants`` AST ports of the old test_invariants.py regex greps
- ``failpoints`` chaos-plane site catalog consistency
- ``meta``       suppression hygiene
- ``protocol``   whole-program wire-protocol sync: every pipe cast/req,
                 GCS/peer rpc_* method, and pubsub topic matches the
                 checked-in catalog in ``core/protocol.py`` AND has both
                 a live sender and a dispatch arm
- ``lifecycle``  session-scoped resource lifecycles: shm/DeviceChannel
                 names carry the session id (sweep-reachable), BlockPool
                 claims roll back on every error path, manual spans are
                 finished or handed off
- ``lockgraph``  global lock-order graph: held->acquired edges merged
                 across ALL modules, cycles reported with witness paths

Public entry points::

    from ray_tpu.devtools import graftlint
    findings = graftlint.lint([Path("ray_tpu")])          # all rules
    findings = graftlint.lint(paths, families=["locks"])  # one family

CLI: ``python -m ray_tpu.devtools.graftlint`` (see --help / Makefile's
``make lint``). Stdlib-only by design — no jax import, safe under the
axon sitecustomize.
"""

from __future__ import annotations

from pathlib import Path
from typing import Iterable, List, Optional

from ray_tpu.devtools.graftlint.engine import (  # noqa: F401
    ModuleIndex,
    Project,
    build_project,
    load_module,
    run_rules,
)
from ray_tpu.devtools.graftlint.model import (  # noqa: F401
    FAMILIES,
    Finding,
    Rule,
    all_rules,
    rule_names,
    select_rules,
)


def lint(paths: List[Path], rules: Iterable[str] = (),
         families: Iterable[str] = (),
         root: Optional[Path] = None,
         cache: bool = True) -> List[Finding]:
    """Analyze ``paths`` and return sorted findings (parse errors
    included as findings). The one-call API tests build on.

    ``cache=False`` bypasses the ``.graftlint_cache/`` model cache
    (which is only consulted when ``root`` is given anyway)."""
    project, errors = build_project([Path(p) for p in paths], root=root,
                                    cache=cache)
    findings = run_rules(project, select_rules(rules, families))
    return sorted(errors + findings, key=lambda f: f.sort_key())
