"""Lint-hygiene rules about graftlint itself (family ``meta``).

Suppressions are the pressure valve that replaces a baseline file: a
violation judged intentional stays visible in the tree next to its
justification. That only works if every suppression really carries a
reason and names a real rule — otherwise it rots into exactly the silent
baseline entry the satellite spec bans.
"""

from __future__ import annotations

from typing import Iterator

from ray_tpu.devtools.graftlint.engine import Project
from ray_tpu.devtools.graftlint.model import (
    FAMILY_META,
    Finding,
    Rule,
    register,
    rule_names,
)


@register
class BareSuppression(Rule):
    name = "bare-suppression"
    family = FAMILY_META
    suppressible = False  # a bare 'disable=all' must not silence this
    summary = ("every '# graftlint: disable=RULE' carries '-- <reason>' "
               "and names a real rule — intentional violations are "
               "justified in place, never silently baselined")

    def check(self, project: Project) -> Iterator[Finding]:
        known = set(rule_names()) | {"all"}
        for mod in project.modules:
            for sup in mod.suppressions:
                if not sup.reason:
                    yield self.finding(
                        mod, sup.comment_line,
                        f"suppression of {', '.join(sup.rules)} has no "
                        f"reason — write '# graftlint: "
                        f"disable={','.join(sup.rules)} -- <why this is "
                        f"safe>'")
                for r in sup.rules:
                    if r not in known:
                        yield self.finding(
                            mod, sup.comment_line,
                            f"suppression names unknown rule '{r}' — it "
                            f"suppresses nothing; see --list-rules")
