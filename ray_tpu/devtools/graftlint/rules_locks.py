"""Lock-discipline / race-detection rules (family ``locks``).

The static twin of ``util/contention.py``'s runtime profiler: the r8
contention hunt proved the driver control plane is GIL-serialized CPU
under ONE coarse lock per component — so the two ways to lose are (a)
touching that shared state *off* the lock (a race the profiler can't
see) and (b) doing slow/blocking work *on* it (latency every other
thread pays). Both are lexically visible, so both are lint rules.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Iterator

from ray_tpu.devtools.graftlint.engine import Project
from ray_tpu.devtools.graftlint.model import (
    FAMILY_LOCKS,
    Finding,
    Rule,
    register,
)

#: caller-holds-the-lock convention: ``_*_locked`` methods are guarded by
#: contract (their call sites are checked instead, being under a lock)
_LOCKED_SUFFIX = "_locked"

#: attribute writes in these methods are single-threaded setup/teardown
#: even when the method is publicly callable
_LIFECYCLE = {"__init__", "__del__", "__enter__", "__exit__"}


def _is_guard_context(write, ci) -> bool:
    """True when a write site is considered lock-protected."""
    if write.locks:
        return True
    if write.method.endswith(_LOCKED_SUFFIX):
        return True
    return False


@register
class UnguardedSharedWrite(Rule):
    name = "unguarded-shared-write"
    family = FAMILY_LOCKS
    summary = ("in a class that runs threads, an attribute written under a "
               "lock somewhere must never be written bare elsewhere "
               "(outside __init__-only setup)")

    def check(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules:
            for ci in mod.classes.values():
                if not ci.thread_targets:
                    continue  # single-threaded class: nothing to race
                init_only = ci.init_only()
                thread_reach = ci.thread_reachable()
                # which locks guard each attribute (from guarded writes)
                guards = defaultdict(set)
                for w in ci.writes:
                    if w.locks:
                        guards[w.attr].update(w.locks)
                    elif w.method.endswith(_LOCKED_SUFFIX):
                        guards[w.attr].add("<caller-held lock>")
                for w in ci.writes:
                    if w.attr not in guards or _is_guard_context(w, ci):
                        continue
                    if w.in_nested_func:
                        continue  # closures: execution context unknown
                    if w.method in _LIFECYCLE or w.method in init_only:
                        continue
                    locks = ", ".join(sorted(guards[w.attr]))
                    ctx = ("thread entry "
                           if w.method in thread_reach else "method ")
                    yield self.finding(
                        mod, w.line,
                        f"{ci.name}.{w.attr} is written under {locks} "
                        f"elsewhere but bare in {ctx}{w.method}() — "
                        f"racy against the class's "
                        f"{'/'.join(sorted(ci.thread_targets))} thread(s); "
                        f"take the lock or mark the site "
                        f"# graftlint: disable={self.name} -- <why safe>")


@register
class LockOrderInversion(Rule):
    name = "lock-order-inversion"
    family = FAMILY_LOCKS
    summary = ("two locks of one class acquired in both nesting orders "
               "(directly or one call away) are a deadlock candidate")

    def check(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules:
            for ci in mod.classes.values():
                pairs = {}  # (outer, inner) -> first line observed
                # direct lexical nesting
                for outer, inner, line, _via in ci.lock_pairs:
                    if outer != inner:
                        pairs.setdefault((outer, inner), line)
                # one call level: under L, call self.m() where m acquires K
                for cs in mod.calls:
                    if not cs.locks or not cs.parts:
                        continue
                    if cs.parts[0] != "self" or len(cs.parts) != 2:
                        continue
                    callee = ci.methods.get(cs.parts[1])
                    if callee is None or cs.func.split(".")[0] != ci.name:
                        continue
                    held = cs.locks
                    for k in callee.acquires:
                        if k not in held:
                            for outer in held:
                                if outer != k:
                                    pairs.setdefault((outer, k), cs.line)
                for (a, b), line in sorted(pairs.items()):
                    if (b, a) in pairs and a < b:
                        other = pairs[(b, a)]
                        yield self.finding(
                            mod, line,
                            f"{ci.name} acquires {a} then {b} here but "
                            f"{b} then {a} at line {other} — inconsistent "
                            f"order deadlocks under contention; pick one "
                            f"order (or drop to one lock)")


#: dotted-call tails that block the calling thread
_BLOCKING_TAILS = {"recv", "recv_bytes", "recv_into", "accept", "connect",
                   "call"}
_BLOCKING_FQ = {"time.sleep", "select.select"}


@register
class BlockingUnderLock(Rule):
    name = "blocking-under-lock"
    family = FAMILY_LOCKS
    summary = ("no blocking call (sleep, pipe/socket recv/accept/connect, "
               "rpc call(), event wait) while holding a lock — every "
               "other thread on that lock stalls behind the I/O")

    def _cond_base_held(self, mod, ci, recv_parts, locks) -> bool:
        """cv.wait() while holding cv's base lock is the one LEGITIMATE
        wait-under-lock (the wait releases it)."""
        if ci is None or not recv_parts or recv_parts[0] != "self" \
                or len(recv_parts) != 2:
            return False
        info = ci.locks.get(recv_parts[1])
        if info is None or info.kind != "cond":
            return False
        self_key = f"self.{recv_parts[1]}"
        if self_key in locks:
            return True
        return info.cond_base is not None and f"self.{info.cond_base}" in locks

    def check(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules:
            for cs in mod.calls:
                if not cs.locks:
                    continue
                held = ", ".join(sorted(cs.locks))
                if cs.fq in _BLOCKING_FQ:
                    yield self.finding(
                        mod, cs.line,
                        f"{cs.fq}() while holding {held} — move the "
                        f"sleep/IO outside the lock (queue under the "
                        f"lock, ship outside: see runtime's "
                        f"OrderedCastFlusher pattern)")
                    continue
                if not cs.parts or len(cs.parts) < 2:
                    continue
                tail = cs.parts[-1]
                cls_name = cs.func.split(".")[0]
                ci = mod.classes.get(cls_name)
                if tail == "wait":
                    recv = cs.parts[:-1]
                    if self._cond_base_held(mod, ci, list(recv), cs.locks):
                        continue
                    yield self.finding(
                        mod, cs.line,
                        f"{'.'.join(cs.parts)}() while holding {held} — "
                        f"a wait on anything but a Condition built on the "
                        f"held lock parks every thread contending for "
                        f"{held}; wait outside the lock with a deadline")
                elif tail in _BLOCKING_TAILS:
                    yield self.finding(
                        mod, cs.line,
                        f"{'.'.join(cs.parts)}() while holding {held} — "
                        f"pipe/RPC I/O under a lock serializes the "
                        f"control plane (r8: the driver lock IS the hot "
                        f"path); send/recv outside, publish results under "
                        f"the lock")


@register
class NativeCallbackLockDiscipline(Rule):
    name = "native-callback-lock-discipline"
    family = FAMILY_LOCKS
    summary = ("``_native_cb_*`` callbacks (invoked from the native pipe "
               "engine's receiver drain) must not acquire locks — not "
               "directly and not one call away; append to the pending "
               "queue and let the reader loop's drain point apply under "
               "the driver.lock-family locks")

    #: the callback naming convention: the native drain path invokes
    #: exactly these; everything they touch must be lock-free
    #: (deque.append / event.set), or a slow lock holder stalls the whole
    #: connection's message intake
    PREFIX = "_native_cb_"

    def check(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules:
            for ci in mod.classes.values():
                for name, fi in ci.methods.items():
                    if not name.startswith(self.PREFIX):
                        continue
                    for key in sorted(fi.acquires):
                        yield self.finding(
                            mod, fi.lineno,
                            f"{ci.name}.{name}() acquires {key} — native "
                            f"drain callbacks must stay lock-free: queue "
                            f"the payload (deque.append is GIL-atomic) "
                            f"and apply it at the reader loop's "
                            f"_drain_native_pins() point")
                    # one call level: callback -> self.m() where m locks
                    for callee_name in sorted(fi.self_calls):
                        callee = ci.methods.get(callee_name)
                        if callee is None or not callee.acquires:
                            continue
                        locks = ", ".join(sorted(callee.acquires))
                        yield self.finding(
                            mod, fi.lineno,
                            f"{ci.name}.{name}() calls "
                            f"self.{callee_name}(), which acquires "
                            f"{locks} — native drain callbacks must not "
                            f"take driver.lock-family locks even "
                            f"indirectly; post to the pending queue "
                            f"instead")
