"""graftlint CLI.

Usage::

    python -m ray_tpu.devtools.graftlint [paths...]      # default: ray_tpu/
        [--rule RULE]... [--family FAM]... [--list-rules]
        [--markdown | --check README.md | --update README.md]
        [--baseline PATH] [--update-baseline]

Exit status: 0 clean, 1 findings (printed as ``path:line RULE message``),
2 usage/config error.

Safe under the axon sitecustomize: if that already imported jax into
this process, pin it to cpu before anything could query a device; we
never import jax ourselves (the linter is pure ``ast``).
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

# sitecustomize guard FIRST: never trigger an axon device query from a
# lint run (a bare query can hang for minutes when no TPU is claimable)
if "jax" in sys.modules:  # pragma: no cover - axon boxes only
    try:
        sys.modules["jax"].config.update("jax_platforms", "cpu")
    except Exception:
        pass

from ray_tpu.devtools import graftlint
from ray_tpu.devtools.graftlint import catalog


def _default_root() -> Path:
    """The repo root (parent of the ray_tpu package this module runs
    from) — makes ``make lint`` work from any cwd."""
    return Path(__file__).resolve().parents[3]


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m ray_tpu.devtools.graftlint",
        description="AST-based architecture linter "
                    "(lock discipline, JAX/TPU discipline, layering seam)")
    p.add_argument("paths", nargs="*",
                   help="files/dirs to lint (default: the ray_tpu/ package)")
    p.add_argument("--rule", action="append", default=[],
                   help="run only this rule (repeatable)")
    p.add_argument("--family", action="append", default=[],
                   help=f"run only this family (repeatable; "
                        f"one of {', '.join(graftlint.FAMILIES)})")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    p.add_argument("--markdown", action="store_true",
                   help="print the generated README rule table")
    p.add_argument("--check", metavar="README",
                   help="verify README's rule table matches the registry")
    p.add_argument("--update", metavar="README",
                   help="rewrite README's rule table in place")
    p.add_argument("--no-cache", action="store_true",
                   help="bypass the .graftlint_cache/ per-module model "
                        "cache (escape hatch; results must be identical "
                        "— tested by test_graftlint.py cache parity)")
    p.add_argument("--baseline", metavar="PATH",
                   help="baseline file of known findings to ignore "
                        "(default: <root>/.graftlint-baseline.json if "
                        "present; the tree intentionally ships none — "
                        "prefer inline '# graftlint: disable=... -- reason')")
    p.add_argument("--update-baseline", action="store_true",
                   help="write current findings to the baseline file")
    args = p.parse_args(argv)

    if args.list_rules:
        for r in graftlint.all_rules():
            print(f"{r.name:26s} [{r.family}] {r.summary}")
        return 0
    if args.markdown:
        print(catalog.markdown_table())
        return 0
    if args.check or args.update:
        return catalog.check_or_update(args.check or args.update,
                                       update=bool(args.update))

    root = _default_root()
    paths = [Path(p) for p in args.paths] or [root / "ray_tpu"]
    for path in paths:
        if not path.exists():
            print(f"error: no such path: {path}", file=sys.stderr)
            return 2
    try:
        findings = graftlint.lint(paths, rules=args.rule,
                                  families=args.family, root=root,
                                  cache=not args.no_cache)
    except KeyError as e:
        print(f"error: {e.args[0]}", file=sys.stderr)
        return 2

    baseline_path = Path(args.baseline) if args.baseline else (
        root / ".graftlint-baseline.json")
    if args.update_baseline:
        baseline_path.write_text(json.dumps(
            [f.render() for f in findings], indent=1) + "\n")
        print(f"wrote {len(findings)} finding(s) to {baseline_path}")
        return 0
    if baseline_path.exists():
        known = set(json.loads(baseline_path.read_text()))
        kept = [f for f in findings if f.render() not in known]
        hidden = len(findings) - len(kept)
        if hidden:
            # a baseline must never be SILENT — say what it swallowed
            print(f"note: {hidden} finding(s) hidden by {baseline_path} "
                  f"(prefer inline '# graftlint: disable=... -- reason')",
                  file=sys.stderr)
        findings = kept

    for f in findings:
        print(f.render())
    if findings:
        print(f"\n{len(findings)} finding(s). Fix, or annotate a "
              f"judged-intentional site with "
              f"'# graftlint: disable=<rule> -- <reason>'.",
              file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main())
