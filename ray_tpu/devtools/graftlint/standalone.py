"""Run graftlint without importing the ray_tpu package — or site.

``make lint`` invokes this file by path under ``python -S``:

    python -S ray_tpu/devtools/graftlint/standalone.py [args...]

Two boot taxes disappear: the axon sitecustomize (which imports jax —
~1.9 s of a ~2.1 s interpreter start on this box, the same tax the
worker zygote dodges) and ``ray_tpu/__init__.py`` (which imports
core.runtime at module scope and needs site-packages). graftlint itself
is stdlib-only pure ``ast``, so ``-S`` costs nothing.

The trick: register synthetic parent packages for ``ray_tpu`` and
``ray_tpu.devtools`` (ModuleType + ``__path__``) before importing the
real graftlint subpackage — the import machinery then resolves
``ray_tpu.devtools.graftlint.*`` through the stub path entries without
ever executing the parents' ``__init__.py``. Combined with the
``.graftlint_cache/`` model cache this keeps a warm ``make lint``
under the 1.5 s budget.

Running via ``python -m ray_tpu.devtools.graftlint`` (full package
import) remains supported and identical in behavior.
"""

import sys
import types
from pathlib import Path

_REPO = Path(__file__).resolve().parents[3]


def _stub_package(name: str, path: Path) -> None:
    mod = types.ModuleType(name)
    mod.__path__ = [str(path)]
    mod.__package__ = name
    sys.modules[name] = mod


if "ray_tpu" not in sys.modules:
    _stub_package("ray_tpu", _REPO / "ray_tpu")
    _stub_package("ray_tpu.devtools", _REPO / "ray_tpu" / "devtools")

if str(_REPO) not in sys.path:
    sys.path.insert(0, str(_REPO))

from ray_tpu.devtools.graftlint.__main__ import main  # noqa: E402

if __name__ == "__main__":
    raise SystemExit(main())
