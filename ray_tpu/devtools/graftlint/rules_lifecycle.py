"""Resource-lifecycle rules (family ``lifecycle``, ISSUE 15).

Session-scoped resources must be reclaimable by something other than
the code path that created them — this box's 2-vCPU contention kills
replicas mid-request routinely, and a leaked /dev/shm ring or an
un-rolled-back block claim survives the process that leaked it. Three
acquire/release disciplines, checked intra-function with lexical
path-sensitivity (guard-aware, closure-bodies included):

- every shm ring created (``Channel``/``DeviceChannel`` with
  ``create=True``) is session-named, so the runtime shutdown sweep
  (``rtpu-chan-<session>-*`` in core/runtime.py) reclaims it;
- every ``BlockPool.alloc`` claim is released on each failure exit
  (the admission invariant: a request that is NOT admitted holds zero
  blocks);
- every ``tracing.manual_span`` started in a function is finished
  there or handed off — an unfinished manual span silently records
  nothing, which is worse than crashing (the SLO decomposition just
  loses a term).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ray_tpu.devtools.graftlint.engine import (
    ModuleIndex,
    Project,
    dotted_parts,
)
from ray_tpu.devtools.graftlint.model import (
    FAMILY_LIFECYCLE,
    Finding,
    Rule,
    register,
)


def _kw(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _is_true(node: Optional[ast.AST]) -> bool:
    return isinstance(node, ast.Constant) and node.value is True


# ---------------------------------------------------------------------------
# rule 1: shm rings must be session-named
# ---------------------------------------------------------------------------

_CHANNEL_CLASSES = {"Channel", "DeviceChannel"}


def _mentions_session(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and "session" in n.id.lower():
            return True
        if isinstance(n, ast.Attribute) and "session" in n.attr.lower():
            return True
    return False


def _local_assigns(func: ast.AST) -> Dict[str, List[ast.AST]]:
    out: Dict[str, List[ast.AST]] = {}
    for n in ast.walk(func):
        if isinstance(n, ast.Assign) and n.value is not None:
            for t in n.targets:
                if isinstance(t, ast.Name):
                    out.setdefault(t.id, []).append(n.value)
    return out


def _session_tainted(name_arg: ast.AST, func: ast.AST,
                     mod: ModuleIndex) -> bool:
    """True when the channel-name expression derives from the session id,
    by transitive local dataflow within ``func`` plus one hop into
    same-module helper functions it calls (kv_transfer's
    ``channel_name()`` shape)."""
    assigns = _local_assigns(func)
    tainted: Set[str] = set()
    # seed: local names whose RHS mentions session directly
    changed = True
    while changed:
        changed = False
        for name, exprs in assigns.items():
            if name in tainted:
                continue
            for e in exprs:
                if _mentions_session(e) or any(
                        isinstance(n, ast.Name) and n.id in tainted
                        for n in ast.walk(e)):
                    tainted.add(name)
                    changed = True
                    break

    def expr_ok(expr: ast.AST) -> bool:
        if _mentions_session(expr):
            return True
        for n in ast.walk(expr):
            if isinstance(n, ast.Name) and n.id in tainted:
                return True
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Name):
                helper = mod.functions.get(n.func.id)
                if helper is not None and _mentions_session(helper.node):
                    return True
        return False

    return expr_ok(name_arg)


def _callee_names(call: ast.Call, assigns: Dict[str, List[ast.AST]]
                  ) -> Set[str]:
    """Terminal class names a call could construct, resolving one level
    of local aliasing (``cls = DeviceChannel if ... else Channel``)."""
    parts = dotted_parts(call.func)
    if not parts:
        return set()
    tail = parts[-1]
    if tail in _CHANNEL_CLASSES:
        return {tail}
    out: Set[str] = set()
    if len(parts) == 1:
        for e in assigns.get(tail, ()):
            for n in ast.walk(e):
                if isinstance(n, ast.Name) and n.id in _CHANNEL_CLASSES:
                    out.add(n.id)
    return out


@register
class ShmSessionLifecycle(Rule):
    name = "shm-session-lifecycle"
    family = FAMILY_LIFECYCLE
    summary = ("every shm ring created (Channel/DeviceChannel "
               "create=True) must derive its name from the runtime "
               "session id so the shutdown sweep (rtpu-chan-<session>-*) "
               "reclaims it when the creator dies uncleanly")

    def check(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules:
            if mod.scope_rel.startswith("ray_tpu/experimental/"):
                continue  # the channel implementation itself
            # cheap gate: a module that neither imports nor defines a
            # channel class cannot create one (the aliased-callee shape
            # still needs the class name in scope)
            if not (_CHANNEL_CLASSES & set(mod.imports)
                    or _CHANNEL_CLASSES & set(mod.classes)):
                continue
            # walk only functions that contain a create=True call
            # (mod.calls is already indexed; ast.walk per function is not)
            funcs = {cs.func for cs in mod.calls
                     if _is_true(_kw(cs.node, "create"))}
            for fi in mod.functions.values():
                if fi.qualname not in funcs:
                    continue
                assigns = _local_assigns(fi.node)
                for node in ast.walk(fi.node):
                    if not isinstance(node, ast.Call):
                        continue
                    if not _callee_names(node, assigns):
                        continue
                    if not _is_true(_kw(node, "create")):
                        continue  # attach side: somebody else's segment
                    name_arg = (node.args[0] if node.args
                                else _kw(node, "name"))
                    if name_arg is None:
                        continue
                    if not _session_tainted(name_arg, fi.node, mod):
                        yield self.finding(
                            mod, node.lineno,
                            "shm channel created with a name not derived "
                            "from the runtime session id — the shutdown "
                            "sweep (rtpu-chan-<session>-*) can never "
                            "reclaim it if this process dies; build the "
                            "name from get_runtime_context()."
                            "get_session_id()")


# ---------------------------------------------------------------------------
# rule 2: BlockPool claims roll back on failure exits
# ---------------------------------------------------------------------------

_CLAIM_TAILS = {"alloc"}
_RELEASE_TAILS = {"release", "release_all"}


def _pool_call_tail(node: ast.Call) -> Optional[str]:
    parts = dotted_parts(node.func)
    if not parts or len(parts) < 2:
        return None
    tail = parts[-1]
    if tail in _CLAIM_TAILS | _RELEASE_TAILS and (
            "pool" in parts[-2].lower()):
        return tail
    return None


def _falsy_exit(node: ast.AST) -> bool:
    if isinstance(node, ast.Raise):
        return True
    if isinstance(node, ast.Return):
        v = node.value
        return v is None or (isinstance(v, ast.Constant)
                             and v.value in (False, None))
    return False


def _none_guard_names(func: ast.AST, exit_node: ast.AST) -> Set[str]:
    """Names X for which ``exit_node`` sits inside an ``if X is None:`` /
    ``if not X:`` body — the claim-failed branch, where that claim holds
    nothing."""
    out: Set[str] = set()
    for n in ast.walk(func):
        if not isinstance(n, ast.If):
            continue
        in_body = any(exit_node is d or any(exit_node is dd
                                            for dd in ast.walk(d))
                      for d in n.body)
        if not in_body:
            continue
        t = n.test
        if (isinstance(t, ast.Compare) and len(t.ops) == 1
                and isinstance(t.ops[0], ast.Is)
                and isinstance(t.comparators[0], ast.Constant)
                and t.comparators[0].value is None
                and isinstance(t.left, ast.Name)):
            out.add(t.left.id)
        elif (isinstance(t, ast.UnaryOp) and isinstance(t.op, ast.Not)
              and isinstance(t.operand, ast.Name)):
            out.add(t.operand.id)
    return out


@register
class PoolClaimRollback(Rule):
    name = "pool-claim-rollback"
    family = FAMILY_LIFECYCLE
    summary = ("a function that claims KV blocks (pool.alloc) must "
               "release them on every failure exit (raise / return "
               "False/None) after the claim — an un-admitted request "
               "holding blocks leaks pool capacity until process death")

    def check(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules:
            # mod.calls is pre-indexed: group claim/release sites by
            # enclosing function, walk only the functions that claim
            by_func: Dict[str, Tuple[List[int], List[int]]] = {}
            for cs in mod.calls:
                tail = _pool_call_tail(cs.node)
                if tail is None:
                    continue
                sink = by_func.setdefault(cs.func, ([], []))
                (sink[0] if tail in _CLAIM_TAILS else sink[1]).append(
                    cs.line)
            for fi in mod.functions.values():
                claim_lines, releases = by_func.get(fi.qualname, ((), ()))
                if not claim_lines:
                    continue
                claims: List[Tuple[int, Optional[str]]] = [
                    (l, None) for l in claim_lines]
                for node in ast.walk(fi.node):
                    if (isinstance(node, ast.Assign)
                            and isinstance(node.value, ast.Call)
                            and _pool_call_tail(node.value) in _CLAIM_TAILS
                            and len(node.targets) == 1
                            and isinstance(node.targets[0], ast.Name)):
                        claims.append((node.value.lineno,
                                       node.targets[0].id))
                first_claim = min(l for l, _ in claims)
                claim_names = {n for _, n in claims if n}
                for node in ast.walk(fi.node):
                    if not _falsy_exit(node):
                        continue
                    line = node.lineno
                    if line <= first_claim:
                        continue
                    if any(first_claim < r <= line for r in releases):
                        continue  # rolled back before bailing
                    guards = _none_guard_names(fi.node, node)
                    if guards & claim_names:
                        continue  # the claim-failed branch holds nothing
                    yield self.finding(
                        mod, line,
                        f"failure exit after pool.alloc() at line "
                        f"{first_claim} without releasing the claimed "
                        f"blocks — release/release_all on every error "
                        f"path (see llm._claim_blocks's roll_back())")


# ---------------------------------------------------------------------------
# rule 3: manual spans are finished or handed off
# ---------------------------------------------------------------------------

@register
class ManualSpanFinish(Rule):
    name = "manual-span-finish"
    family = FAMILY_LIFECYCLE
    summary = ("a tracing.manual_span() started in a function must be "
               ".finish()ed there or escape (stored/passed/returned) — "
               "an abandoned manual span records nothing and silently "
               "drops a term from the request latency decomposition")

    def check(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules:
            if mod.scope_rel == "ray_tpu/util/tracing.py":
                continue  # the implementation
            # walk only functions that start a manual span (pre-indexed)
            span_funcs = {cs.func for cs in mod.calls
                          if cs.parts and cs.parts[-1] == "manual_span"}
            if not span_funcs:
                continue
            for fi in mod.functions.values():
                if fi.qualname not in span_funcs:
                    continue
                spans: Dict[str, int] = {}
                for node in ast.walk(fi.node):
                    if (isinstance(node, ast.Assign)
                            and isinstance(node.value, ast.Call)
                            and len(node.targets) == 1
                            and isinstance(node.targets[0], ast.Name)):
                        parts = dotted_parts(node.value.func)
                        if parts and parts[-1] == "manual_span":
                            spans.setdefault(node.targets[0].id,
                                             node.lineno)
                if not spans:
                    continue
                finished: Set[str] = set()
                escaped: Set[str] = set()
                for node in ast.walk(fi.node):
                    if isinstance(node, ast.Call):
                        parts = dotted_parts(node.func)
                        if (parts and len(parts) == 2
                                and parts[1] == "finish"
                                and parts[0] in spans):
                            finished.add(parts[0])
                        # bare span passed into another call = handoff
                        for a in list(node.args) + [
                                kw.value for kw in node.keywords]:
                            if isinstance(a, ast.Name) and a.id in spans:
                                escaped.add(a.id)
                    elif isinstance(node, (ast.Return, ast.Yield,
                                           ast.YieldFrom)):
                        v = getattr(node, "value", None)
                        if v is not None:
                            for n in ast.walk(v):
                                if (isinstance(n, ast.Name)
                                        and n.id in spans):
                                    escaped.add(n.id)
                    elif isinstance(node, ast.Assign):
                        # stored onto an object / container / other name
                        # (re-assignment of the span var itself is not an
                        # escape)
                        if any(not isinstance(t, ast.Name)
                               for t in node.targets):
                            for n in ast.walk(node.value):
                                if (isinstance(n, ast.Name)
                                        and n.id in spans):
                                    escaped.add(n.id)
                for name, line in sorted(spans.items()):
                    if name in finished or name in escaped:
                        continue
                    yield self.finding(
                        mod, line,
                        f"manual span '{name}' is started but never "
                        f".finish()ed in {fi.qualname}() and never "
                        f"escapes — the span will not be recorded; "
                        f"finish it in a finally: (error= on the "
                        f"failure path) or hand it off")
