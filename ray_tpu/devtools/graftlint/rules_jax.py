"""JAX/TPU discipline rules (family ``jax``).

SafeCheck-style ahead-of-time enforcement of the accelerator call
discipline this box taught the hard way (CLAUDE.md): the 50 GB-residual
mistake, the 70x-impossible MFU number, the chip-fight hang, and the
1.9 s/worker jax import are all cheaper to catch at lint time than at
the next once-a-round tunnel window.
"""

from __future__ import annotations

import ast
from typing import Iterator, Set

from ray_tpu.devtools.graftlint.engine import Project, dotted_parts
from ray_tpu.devtools.graftlint.model import (
    FAMILY_JAX,
    Finding,
    Rule,
    register,
)

#: raw kernels without a memory-efficient VJP; the dispatch wrapper
#: ``ray_tpu.ops.flash_attention`` carries the custom VJP
_RAW_KERNELS = {"flash_attention_pallas", "blockwise_attention"}

#: jax transforms that differentiate their function argument
_DIFF_TRANSFORMS = {"jax.grad", "jax.value_and_grad", "jax.vjp",
                    "jax.jacfwd", "jax.jacrev", "jax.hessian"}


def _is_raw_kernel_call(mod, cs) -> bool:
    """Alias-aware: matches the symbol wherever it came from —
    ``from ...flash_pallas import flash_attention_pallas as fap`` or
    ``ops.attention.blockwise_attention(...)`` both resolve."""
    if cs.fq:
        tail = cs.fq.rpartition(".")[2]
        if tail in _RAW_KERNELS:
            return True
    if cs.parts and cs.parts[-1] in _RAW_KERNELS:
        return True
    return False


@register
class RawAttentionKernel(Rule):
    name = "raw-attention-call"
    family = FAMILY_JAX
    summary = ("outside ray_tpu/ops/, attention goes through "
               "ops.flash_attention (memory-efficient VJP) — raw "
               "flash_attention_pallas/blockwise_attention calls OOM real "
               "HBM when differentiated; also flags jax.grad over a local "
               "function that reaches a raw kernel")

    def check(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules:
            in_ops = mod.scope_rel.startswith("ray_tpu/ops/")
            # functions (transitively, within the module) calling a raw kernel
            raw_callers: Set[str] = set()
            calls_by_func = {}
            for cs in mod.calls:
                calls_by_func.setdefault(cs.func, []).append(cs)
                if _is_raw_kernel_call(mod, cs):
                    raw_callers.add(cs.func)
                    if not in_ops:
                        yield self.finding(
                            mod, cs.line,
                            f"raw kernel {'.'.join(cs.parts or ('?',))}() "
                            f"called outside ray_tpu/ops — it has no "
                            f"memory-efficient VJP (saves every "
                            f"probability block: ~50 GB at llama-250M "
                            f"batch 16); call ray_tpu.ops.flash_attention "
                            f"instead")
            # close over intra-module plain-name calls
            changed = True
            while changed:
                changed = False
                for func, sites in calls_by_func.items():
                    if func in raw_callers:
                        continue
                    for cs in sites:
                        if (cs.parts and len(cs.parts) == 1
                                and any(rc.split(".")[-1] == cs.parts[0]
                                        for rc in raw_callers)):
                            raw_callers.add(func)
                            changed = True
                            break
            if not raw_callers or in_ops:
                # ops/ is the rule's documented home: its custom-VJP
                # machinery legitimately differentiates the raw kernels
                continue
            raw_tails = {rc.split(".")[-1] for rc in raw_callers}
            # jax.grad(f) where f reaches a raw kernel — differentiating
            # the raw path, even without a direct call at this site
            for cs in mod.calls:
                if cs.fq not in _DIFF_TRANSFORMS:
                    continue
                for arg in cs.node.args[:1]:
                    parts = dotted_parts(arg)
                    if parts and len(parts) == 1 and parts[0] in raw_tails:
                        yield self.finding(
                            mod, cs.line,
                            f"{cs.fq}({parts[0]}) differentiates a "
                            f"function that reaches a raw attention "
                            f"kernel — jax saves every probability block "
                            f"as a residual; route the attention through "
                            f"ray_tpu.ops.flash_attention")


@register
class UnreliableTimingBarrier(Rule):
    name = "unreliable-timing-barrier"
    family = FAMILY_JAX
    summary = ("block_until_ready is not a completion barrier on the "
               "tunneled axon backend (r2 measured a 70x-impossible MFU) "
               "— timed code must device_get a scalar data-dependent on "
               "the work")

    def check(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules:
            timer_funcs = {q for q, fi in mod.functions.items()
                           if fi.calls_timer}
            for cs in mod.calls:
                if not cs.parts or cs.parts[-1] != "block_until_ready":
                    continue
                if cs.func not in timer_funcs:
                    continue
                yield self.finding(
                    mod, cs.line,
                    f"block_until_ready in timing function {cs.func}() — "
                    f"it acks early on the tunneled axon backend "
                    f"(CLAUDE.md r2: ~70x-peak 'MFU'); time with a "
                    f"jax.device_get of a scalar data-dependent on all "
                    f"the work (TrainLoopHelper.run_steps pattern)")


@register
class JaxPlatformsLeak(Rule):
    name = "jax-platforms-leak"
    family = FAMILY_JAX
    summary = ("never read the driver's JAX_PLATFORMS env into a worker "
               "env (outside util/) — propagating the accelerator value "
               "makes every worker fight for the chip and hang")

    _ALLOWED_PREFIXES = ("ray_tpu/util/",)

    def _env_read(self, mod, cs) -> bool:
        # os.environ.get("JAX_PLATFORMS") / environ.get(...) / os.getenv(...)
        if cs.fq in ("os.environ.get", "os.getenv") and cs.node.args:
            a = cs.node.args[0]
            return isinstance(a, ast.Constant) and a.value == "JAX_PLATFORMS"
        return False

    def check(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules:
            if mod.scope_rel.startswith(self._ALLOWED_PREFIXES):
                continue
            if "JAX_PLATFORMS" not in mod.source:
                continue  # cheap gate before any tree walk
            for cs in mod.calls:
                if self._env_read(mod, cs):
                    yield self.finding(
                        mod, cs.line,
                        "reads the driver's JAX_PLATFORMS from "
                        "os.environ — workers hard-default to cpu "
                        "(DriverRuntime.worker_env); opt a designated "
                        "actor back in per-actor, don't forward the "
                        "driver's value")
            # os.environ["JAX_PLATFORMS"] *read* (a store is how the
            # allowed util/ helpers pin the value; elsewhere reads leak)
            for node in ast.walk(mod.tree):
                if (isinstance(node, ast.Subscript)
                        and isinstance(node.ctx, ast.Load)
                        and isinstance(node.slice, ast.Constant)
                        and node.slice.value == "JAX_PLATFORMS"):
                    parts = dotted_parts(node.value)
                    fq = mod.resolve_parts(parts) if parts else None
                    if fq == "os.environ":
                        yield self.finding(
                            mod, node.lineno,
                            "reads the driver's JAX_PLATFORMS from "
                            "os.environ — workers hard-default to cpu; "
                            "don't forward the driver's value")
            # {k: v for k, v in os.environ.items() if k in ("JAX_PLATFORMS",..)}
            for node in ast.walk(mod.tree):
                if not isinstance(node, (ast.DictComp, ast.SetComp,
                                         ast.ListComp, ast.GeneratorExp)):
                    continue
                over_environ = False
                for gen in node.generators:
                    it = gen.iter
                    if isinstance(it, ast.Call):
                        it = it.func
                    parts = dotted_parts(it)
                    fq = mod.resolve_parts(parts) if parts else None
                    if fq and fq.startswith("os.environ"):
                        over_environ = True
                if not over_environ:
                    continue
                for sub in ast.walk(node):
                    if (isinstance(sub, ast.Constant)
                            and sub.value == "JAX_PLATFORMS"):
                        yield self.finding(
                            mod, sub.lineno,
                            "filters JAX_PLATFORMS out of os.environ "
                            "into a forwarded env dict — the driver's "
                            "value (axon on TPU boxes) would make every "
                            "worker fight for the chip; set an explicit "
                            "per-worker value instead")
                        break


#: ML-tier trees whose jit/pmap sites must go through the device-plane
#: registry (util/device_plane.registered_jit) so every compiled program
#: gets a name, a signature history, and cost analysis
_REGISTRY_SCOPES = ("ray_tpu/models/", "ray_tpu/train/", "ray_tpu/serve/",
                    "ray_tpu/rllib/")

#: introspection calls fenced to util/device_plane.py — each costs a
#: lowering/compile or a full live-array walk, and scattering them
#: defeats the single bounded registry
_FENCED_INTROSPECTION = {"cost_analysis", "memory_analysis", "live_arrays"}

_PLANE_FILE = "ray_tpu/util/device_plane.py"


@register
class JitRegistryDiscipline(Rule):
    name = "jit-registry-discipline"
    family = FAMILY_JAX
    summary = ("under models//train//serve//rllib, jax.jit/jax.pmap goes "
               "through util.device_plane.registered_jit (named program, "
               "retrace detection, cost analysis); cost_analysis/"
               "memory_analysis/live_arrays are fenced to "
               "util/device_plane.py")

    def check(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules:
            is_plane = mod.scope_rel == _PLANE_FILE
            in_scope = mod.scope_rel.startswith(_REGISTRY_SCOPES)
            for cs in mod.calls:
                if in_scope and cs.fq in ("jax.jit", "jax.pmap"):
                    tail = cs.fq.rpartition(".")[2]
                    yield self.finding(
                        mod, cs.line,
                        f"raw jax.{tail}() in an ML-tier module — the "
                        f"compiled program is invisible to the device "
                        f"plane (no name, no retrace detection, no cost "
                        f"analysis); wrap it with "
                        f"ray_tpu.util.device_plane.registered_jit")
                if is_plane:
                    continue
                tail = None
                if cs.fq:
                    t = cs.fq.rpartition(".")[2]
                    if t in _FENCED_INTROSPECTION:
                        tail = t
                if tail is None and cs.parts \
                        and cs.parts[-1] in _FENCED_INTROSPECTION:
                    tail = cs.parts[-1]
                if tail is not None:
                    yield self.finding(
                        mod, cs.line,
                        f"{tail}() outside util/device_plane.py — XLA "
                        f"introspection costs a lowering (or a live-"
                        f"array walk) per call; the registry already "
                        f"holds it, read device_plane.registry() / "
                        f"state.device_report() instead")


@register
class JaxImportInCore(Rule):
    name = "jax-import-in-core"
    family = FAMILY_JAX
    summary = ("no module-scope jax import in core/ or cluster/ — zygote "
               "workers import these, and jax costs ~1.9 s per worker "
               "boot (defer to function scope)")

    _SCOPES = ("ray_tpu/core/", "ray_tpu/cluster/")

    def check(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules:
            if not mod.scope_rel.startswith(self._SCOPES):
                continue
            for line, fq in mod.module_import_nodes:
                if fq == "jax" or fq.startswith("jax."):
                    yield self.finding(
                        mod, line,
                        f"module-scope import of {fq} in a zygote-"
                        f"imported module — every worker boot pays "
                        f"~1.9 s; import inside the function that needs "
                        f"it (workers spawn with python -S precisely to "
                        f"dodge this)")
