"""graftlint analysis engine: one shared ``ast`` walk per module.

Everything the rule families consume is computed here, once:

- **import/alias resolution** — ``resolve()`` maps a dotted expression
  (``A.blockwise_attention`` after ``import ray_tpu.ops.attention as A``)
  to its fully qualified name, so rules match *symbols*, not spellings.
- **lock identification + with-block context** — attributes assigned from
  ``threading.Lock/RLock/Condition`` or ``util.contention.timed_lock/
  timed_rlock`` are lock attrs; ``Condition(self.x)`` remembers its base
  lock. Every statement is walked with the lexically-held lock set, so
  rules see "this write/call happened under ``self.lock``".
- **thread classification** — ``threading.Thread(target=self.m)`` marks
  ``m`` a thread entry; an intra-class ``self.m()`` call graph gives each
  method's reachability from thread entries vs the public API vs
  ``__init__``-only setup.
- **suppressions** — ``# graftlint: disable=rule1,rule2 -- reason`` on a
  line (or on its own line, applying to the next line) suppresses those
  rules there. A missing ``-- reason`` is itself reported (rule
  ``bare-suppression``): judged-intentional violations carry their
  justification in the tree, never a silent baseline entry.

The engine is stdlib-only (``ast`` + ``tokenize`` level machinery) and
must stay importable without jax — ``make lint`` runs it in every
environment, including under the axon sitecustomize.
"""

from __future__ import annotations

import ast
import hashlib
import os
import pickle
import re
import sys
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

# attributes assigned from these callables are lock objects
LOCK_FACTORIES = {
    "threading.Lock": "lock",
    "threading.RLock": "rlock",
    "threading.Condition": "cond",
    "ray_tpu.util.contention.timed_lock": "lock",
    "ray_tpu.util.contention.timed_rlock": "rlock",
}

# fallback when the constructor is out of view: a `with self.<x>:` whose
# name *reads* like a lock is still treated as one
_LOCKISH_NAME = re.compile(r"(^|_)(lock|mutex|rlock|cv|cond)s?($|_)|_cv$")

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable=([A-Za-z0-9_,\- ]+?)\s*(?:--\s*(.*))?$")
_PATH_OVERRIDE_RE = re.compile(r"#\s*graftlint:\s*path=(\S+)")

TIMER_CALLS = {"time.monotonic", "time.perf_counter", "time.time",
               "time.perf_counter_ns", "time.monotonic_ns"}


def is_lockish(name: str) -> bool:
    return bool(_LOCKISH_NAME.search(name))


def dotted_parts(node: ast.AST) -> Optional[List[str]]:
    """``a.b.c`` -> ["a","b","c"]; None for non-name expressions."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return None


@dataclass(frozen=True)
class Suppression:
    line: int
    rules: Tuple[str, ...]
    reason: str
    comment_line: int  # where the comment itself sits


@dataclass
class LockInfo:
    attr: str             # "lock", "_ref_lock", ... (no "self." prefix)
    kind: str             # "lock" | "rlock" | "cond"
    cond_base: Optional[str] = None  # Condition(self.X) -> "X"
    line: int = 0


@dataclass
class AttrWrite:
    attr: str
    line: int
    method: str           # method qualname within the class
    locks: FrozenSet[str]  # lock keys held ("self.lock", "_runtime_lock")
    kind: str             # "assign" | "aug" | "subscript"
    in_nested_func: bool  # inside a closure defined in the method


@dataclass
class CallSite:
    line: int
    func: str                     # enclosing function qualname ("" = module)
    fq: Optional[str]             # resolved fully-qualified target
    parts: Optional[Tuple[str, ...]]  # raw dotted parts of the callee
    locks: FrozenSet[str]
    loop_depth: int
    node: ast.Call


@dataclass
class FunctionInfo:
    name: str
    qualname: str
    node: ast.AST
    class_name: Optional[str]
    lineno: int
    self_calls: Set[str] = field(default_factory=set)
    calls_timer: bool = False
    # with-lock acquisitions made (lexically) anywhere in the body
    acquires: Set[str] = field(default_factory=set)


@dataclass
class ClassInfo:
    name: str
    node: ast.ClassDef
    lineno: int
    locks: Dict[str, LockInfo] = field(default_factory=dict)
    thread_targets: Set[str] = field(default_factory=set)
    methods: Dict[str, FunctionInfo] = field(default_factory=dict)
    writes: List[AttrWrite] = field(default_factory=list)
    # ordered (outer, inner, line, via) lock acquisitions; `via` names the
    # called method when the inner acquisition is one call level away
    lock_pairs: List[Tuple[str, str, int, str]] = field(default_factory=list)

    # -- reachability ---------------------------------------------------

    def _closure(self, roots: Set[str]) -> Set[str]:
        seen, work = set(), [r for r in roots if r in self.methods]
        while work:
            m = work.pop()
            if m in seen:
                continue
            seen.add(m)
            for callee in self.methods[m].self_calls:
                if callee in self.methods and callee not in seen:
                    work.append(callee)
        return seen

    def thread_reachable(self) -> Set[str]:
        return self._closure(set(self.thread_targets))

    def api_reachable(self) -> Set[str]:
        roots = {m for m in self.methods
                 if not m.startswith("_") or m in ("__call__", "__enter__",
                                                   "__exit__")}
        return self._closure(roots)

    def init_only(self) -> Set[str]:
        """Methods reachable from __init__ but from no API/thread root —
        single-threaded setup context."""
        init = self._closure({"__init__"})
        return init - self.api_reachable() - self.thread_reachable()


class ModuleIndex:
    """Per-file analysis product consumed by the rules."""

    def __init__(self, path: Path, display: str, scope_rel: str,
                 source: str):
        self.path = path
        self.display = display
        self.scope_rel = scope_rel  # "ray_tpu/..." posix path for scoping
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=str(path))
        self.parse_error: Optional[str] = None
        self.imports: Dict[str, str] = {}
        self.module_name = self._module_name()
        self.classes: Dict[str, ClassInfo] = {}
        self.functions: Dict[str, FunctionInfo] = {}  # by qualname
        self.module_locks: Set[str] = set()
        # (outer, inner, line, via) pairs acquired in module-level
        # functions — the class-free twin of ClassInfo.lock_pairs, so the
        # global lock-order graph sees edges outside any class
        self.lock_pairs: List[Tuple[str, str, int, str]] = []
        self.calls: List[CallSite] = []
        self.module_import_nodes: List[Tuple[int, str]] = []  # (line, fq)
        self.all_import_nodes: List[Tuple[int, str]] = []     # incl. nested
        self.suppressions: List[Suppression] = []
        self._suppress_map: Dict[int, Set[str]] = {}
        self._scan_comments()
        _Indexer(self).run()

    # -- identity -------------------------------------------------------

    def _module_name(self) -> str:
        rel = self.scope_rel
        if rel.endswith(".py"):
            rel = rel[:-3]
        return rel.replace("/", ".").removesuffix(".__init__")

    @property
    def package(self) -> str:
        # an __init__.py IS its package — relative imports resolve
        # against it, not its parent
        if self.scope_rel.endswith("/__init__.py"):
            return self.module_name
        return self.module_name.rpartition(".")[0]

    # -- comments: suppressions + path override -------------------------

    def _scan_comments(self) -> None:
        # real COMMENT tokens only — a disable= example inside a docstring
        # must not suppress anything (or demand a reason)
        import io
        import tokenize

        if "graftlint:" not in self.source:
            return
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(self.source).readline))
        except (tokenize.TokenError, IndentationError):
            return
        # statement spans: an own-line suppression covers the whole next
        # statement (incl. multi-line calls/comprehensions); a trailing
        # one covers the statement starting on its line
        spans = {}  # start line -> (start, end)
        _compound = (ast.If, ast.For, ast.AsyncFor, ast.While, ast.With,
                     ast.AsyncWith, ast.Try, ast.FunctionDef,
                     ast.AsyncFunctionDef, ast.ClassDef)
        for node in ast.walk(self.tree):
            if isinstance(node, ast.stmt) and hasattr(node, "end_lineno"):
                if isinstance(node, _compound):
                    # cover the HEADER only — a suppression before a
                    # def/with/if must not blanket the whole body
                    body = getattr(node, "body", None) or [node]
                    end = max(node.lineno, body[0].lineno - 1)
                else:
                    end = node.end_lineno or node.lineno
                cur = spans.get(node.lineno)
                if cur is None or end - node.lineno < cur[1] - cur[0]:
                    spans[node.lineno] = (node.lineno, end)

        def _cover(rules, start):
            span = spans.get(start, (start, start))
            for ln in range(span[0], span[1] + 1):
                self._suppress_map.setdefault(ln, set()).update(rules)
            return span[0]

        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            i = tok.start[0]
            rules = tuple(r.strip() for r in m.group(1).split(",")
                          if r.strip())
            reason = (m.group(2) or "").strip()
            own_line = self.lines[i - 1].lstrip().startswith("#")
            if own_line:
                # skip past continuation comment/blank lines to the code
                target = i + 1
                while (target <= len(self.lines)
                       and (not self.lines[target - 1].strip()
                            or self.lines[target - 1].lstrip()
                            .startswith("#"))):
                    target += 1
            else:
                target = i
            target = _cover(rules, target)
            self.suppressions.append(
                Suppression(target, rules, reason, i))

    def is_suppressed(self, line: int, rule: str) -> bool:
        rules = self._suppress_map.get(line)
        return bool(rules) and (rule in rules or "all" in rules)

    # -- resolution -----------------------------------------------------

    def resolve_parts(self, parts: List[str]) -> Optional[str]:
        """Fully-qualified name for a dotted reference, via the import
        table (falls back to local top-level defs)."""
        if not parts:
            return None
        head = parts[0]
        if head in self.imports:
            return ".".join([self.imports[head]] + parts[1:])
        if head == "self":
            return None
        if len(parts) == 1 and parts[0] in self.functions:
            return f"{self.module_name}.{parts[0]}"
        return None

    def resolve_node(self, node: ast.AST) -> Optional[str]:
        parts = dotted_parts(node)
        return self.resolve_parts(parts) if parts else None


class _Indexer:
    """Single recursive pass filling a ModuleIndex."""

    def __init__(self, mod: ModuleIndex):
        self.mod = mod

    def run(self) -> None:
        mod = self.mod
        # imports: one traversal; "module scope" = not enclosed in a
        # function (a try/if-guarded module-level import still runs at
        # import time, so it still counts)
        stack = [(mod.tree, False)]
        while stack:
            node, deferred = stack.pop()
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                self._collect_import(node, top=not deferred)
                continue
            if isinstance(node, ast.If) and not deferred:
                # `if TYPE_CHECKING:` bodies never run — type-only
                # imports are not module-scope runtime imports
                parts = dotted_parts(node.test)
                fq = mod.resolve_parts(parts) if parts else None
                if fq == "typing.TYPE_CHECKING" or (
                        parts and parts[-1] == "TYPE_CHECKING"):
                    for child in node.body:
                        stack.append((child, True))
                    for child in node.orelse:
                        stack.append((child, False))
                    continue
            child_deferred = deferred or isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda))
            for child in ast.iter_child_nodes(node):
                stack.append((child, child_deferred))
        # module-level locks: NAME = threading.Lock()
        for node in mod.tree.body:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)):
                fq = mod.resolve_node(node.value.func)
                if fq in LOCK_FACTORIES:
                    mod.module_locks.add(node.targets[0].id)
        # classes: find lock attrs + thread targets first (any method may
        # assign them), then walk bodies with lock context
        for node in mod.tree.body:
            if isinstance(node, ast.ClassDef):
                self._index_class(node)
        # module-level functions
        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fi = FunctionInfo(node.name, node.name, node, None,
                                  node.lineno)
                mod.functions[fi.qualname] = fi
                _BodyWalker(mod, None, fi).walk_function(node)
        # bare module-level statements (scripts/benches): one shared
        # pseudo-function, registered so per-function rules (e.g. the
        # timing-barrier check) see module-level code too
        top = FunctionInfo("<module>", "<module>", mod.tree, None, 0)
        mod.functions[top.qualname] = top
        walker = _BodyWalker(mod, None, top)
        for node in mod.tree.body:
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                walker.visit(node)

    def _collect_import(self, node: ast.AST, top: bool) -> None:
        mod = self.mod
        found: List[str] = []
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    mod.imports[a.asname] = a.name
                else:
                    head = a.name.split(".")[0]
                    mod.imports.setdefault(head, head)
                found.append(a.name)
        elif isinstance(node, ast.ImportFrom):
            base = node.module or ""
            if node.level:  # relative: resolve against this package
                pkg_parts = mod.package.split(".") if mod.package else []
                keep = pkg_parts[:len(pkg_parts) - (node.level - 1)]
                base = ".".join(keep + ([node.module] if node.module else []))
            for a in node.names:
                if a.name == "*":
                    continue
                mod.imports[a.asname or a.name] = (
                    f"{base}.{a.name}" if base else a.name)
                found.append(f"{base}.{a.name}" if base else a.name)
        else:
            return
        target = mod.module_import_nodes if top else None
        for fq in found:
            mod.all_import_nodes.append((node.lineno, fq))
            if target is not None:
                target.append((node.lineno, fq))

    # -- class indexing -------------------------------------------------

    def _index_class(self, cnode: ast.ClassDef) -> None:
        mod = self.mod
        ci = ClassInfo(cnode.name, cnode, cnode.lineno)
        mod.classes[cnode.name] = ci
        methods = [n for n in cnode.body
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        # pass 1: lock attrs + thread targets + self-call graph
        for m in methods:
            fi = FunctionInfo(m.name, f"{cnode.name}.{m.name}", m,
                              cnode.name, m.lineno)
            ci.methods[m.name] = fi
            mod.functions[fi.qualname] = fi
            for node in ast.walk(m):
                if isinstance(node, ast.Assign) and isinstance(
                        node.value, ast.Call):
                    fq = mod.resolve_node(node.value.func)
                    kind = LOCK_FACTORIES.get(fq or "")
                    if kind:
                        for t in node.targets:
                            if (isinstance(t, ast.Attribute)
                                    and isinstance(t.value, ast.Name)
                                    and t.value.id == "self"):
                                base = None
                                if kind == "cond" and node.value.args:
                                    bp = dotted_parts(node.value.args[0])
                                    if bp and bp[0] == "self" and len(bp) == 2:
                                        base = bp[1]
                                ci.locks[t.attr] = LockInfo(
                                    t.attr, kind, base, node.lineno)
                if isinstance(node, ast.Call):
                    fq = mod.resolve_node(node.func)
                    if fq in ("threading.Thread", "threading.Timer"):
                        for kw in node.keywords:
                            if kw.arg == "target":
                                tp = dotted_parts(kw.value)
                                if tp and tp[0] == "self" and len(tp) == 2:
                                    ci.thread_targets.add(tp[1])
                    parts = dotted_parts(node.func)
                    if parts and parts[0] == "self" and len(parts) == 2:
                        fi.self_calls.add(parts[1])
                    if fq in TIMER_CALLS:
                        fi.calls_timer = True
        # pass 2: body walk with lock context
        for m in methods:
            _BodyWalker(mod, ci, ci.methods[m.name]).walk_function(m)


class _BodyWalker(ast.NodeVisitor):
    """Walks one function body tracking held locks + loop depth."""

    def __init__(self, mod: ModuleIndex, ci: Optional[ClassInfo],
                 fi: FunctionInfo):
        self.mod = mod
        self.ci = ci
        self.fi = fi
        self.locks: List[str] = []
        self.loop_depth = 0
        self.nested_depth = 0

    def walk_function(self, node) -> None:
        for stmt in node.body:
            self.visit(stmt)

    # -- lock recognition ----------------------------------------------

    def _lock_key(self, expr: ast.AST) -> Optional[str]:
        parts = dotted_parts(expr)
        if not parts:
            return None
        if parts[0] == "self" and len(parts) == 2:
            attr = parts[1]
            if self.ci and attr in self.ci.locks:
                return f"self.{attr}"
            if is_lockish(attr):
                return f"self.{attr}"
            return None
        if len(parts) == 1:
            name = parts[0]
            if name in self.mod.module_locks or is_lockish(name):
                return name
            return None
        # x.y.lock style: treat a lockish tail as a lock key
        if is_lockish(parts[-1]):
            return ".".join(parts)
        return None

    def visit_With(self, node: ast.With) -> None:
        for item in node.items:
            self.visit(item.context_expr)
        acquired = []
        for item in node.items:
            key = self._lock_key(item.context_expr)
            if key:
                acquired.append(key)
        for key in acquired:
            # pair against EVERY held lock, not just the innermost —
            # a->b->c vs c->a inverts on (a,c)
            sink = (self.ci.lock_pairs if self.ci is not None
                    else self.mod.lock_pairs)
            for held in self.locks:
                sink.append((held, key, node.lineno, ""))
            self.locks.append(key)
            if self.nested_depth == 0:
                # a closure's acquisition happens when the CALLBACK runs,
                # not when the defining method is called — attributing it
                # to the method fabricates call-through inversions
                self.fi.acquires.add(key)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self.locks.pop()

    visit_AsyncWith = visit_With

    # -- loops ----------------------------------------------------------

    def visit_For(self, node) -> None:
        self.visit(node.iter)
        self.loop_depth += 1
        for stmt in node.body:
            self.visit(stmt)
        self.loop_depth -= 1
        for stmt in node.orelse:
            self.visit(stmt)

    visit_AsyncFor = visit_For

    def visit_While(self, node: ast.While) -> None:
        self.visit(node.test)
        self.loop_depth += 1
        for stmt in node.body:
            self.visit(stmt)
        self.loop_depth -= 1
        for stmt in node.orelse:
            self.visit(stmt)

    # -- nested functions: separate execution context --------------------

    def visit_FunctionDef(self, node) -> None:
        outer_locks, self.locks = self.locks, []
        outer_depth, self.loop_depth = self.loop_depth, 0
        self.nested_depth += 1
        for stmt in node.body:
            self.visit(stmt)
        self.nested_depth -= 1
        self.locks, self.loop_depth = outer_locks, outer_depth

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        outer_locks, self.locks = self.locks, []
        outer_depth, self.loop_depth = self.loop_depth, 0
        self.nested_depth += 1
        self.visit(node.body)
        self.nested_depth -= 1
        self.locks, self.loop_depth = outer_locks, outer_depth

    # -- events ----------------------------------------------------------

    def _record_write(self, target: ast.AST, kind: str, line: int) -> None:
        if self.ci is None:
            return
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._record_write(elt, kind, line)
            return
        if isinstance(target, ast.Attribute) and isinstance(
                target.value, ast.Name) and target.value.id == "self":
            self.ci.writes.append(AttrWrite(
                target.attr, line, self.fi.name,
                frozenset(self.locks), kind, self.nested_depth > 0))
        elif isinstance(target, ast.Subscript):
            inner = target.value
            if isinstance(inner, ast.Attribute) and isinstance(
                    inner.value, ast.Name) and inner.value.id == "self":
                self.ci.writes.append(AttrWrite(
                    inner.attr, line, self.fi.name,
                    frozenset(self.locks), "subscript",
                    self.nested_depth > 0))

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._record_write(t, "assign", node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record_write(node.target, "aug", node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._record_write(node.target, "assign", node.lineno)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        parts = dotted_parts(node.func)
        fq = self.mod.resolve_parts(parts) if parts else None
        self.mod.calls.append(CallSite(
            node.lineno, self.fi.qualname, fq,
            tuple(parts) if parts else None,
            frozenset(self.locks), self.loop_depth, node))
        if fq in TIMER_CALLS:
            self.fi.calls_timer = True
        # mutating container calls on self attrs count as writes
        if (self.ci is not None and parts and parts[0] == "self"
                and len(parts) == 3 and parts[2] in (
                    "append", "appendleft", "add", "pop", "popleft",
                    "update", "clear", "remove", "discard", "extend",
                    "setdefault")):
            self.ci.writes.append(AttrWrite(
                parts[1], node.lineno, self.fi.name,
                frozenset(self.locks), "mutcall", self.nested_depth > 0))
        self.generic_visit(node)


# ---------------------------------------------------------------------------
# project = a set of analyzed modules
# ---------------------------------------------------------------------------

class Project:
    def __init__(self, modules: List[ModuleIndex],
                 whole_package: bool = False):
        #: True when the lint scope covered the whole ray_tpu package —
        #: cross-file completeness checks (e.g. "documented failpoint has
        #: no call site") are only meaningful then
        self.whole_package = whole_package
        self.modules = modules
        self.by_scope: Dict[str, ModuleIndex] = {
            m.scope_rel: m for m in modules}

    def module(self, scope_rel: str) -> Optional[ModuleIndex]:
        return self.by_scope.get(scope_rel)

    def in_scope(self, prefix: str) -> List[ModuleIndex]:
        return [m for m in self.modules
                if m.scope_rel.startswith(prefix)]


def _scope_rel_for(path: Path) -> str:
    """Path used for rule scoping: the trailing ``ray_tpu/...`` segment
    when present (robust to cwd), else the basename. Fixture files
    override with ``# graftlint: path=ray_tpu/...``."""
    parts = list(path.parts)
    for i in range(len(parts) - 1, -1, -1):
        if parts[i] == "ray_tpu":
            return "/".join(parts[i:])
    return path.name


def load_module(path: Path, root: Optional[Path] = None) -> ModuleIndex:
    source = path.read_text()
    scope = _scope_rel_for(path)
    m = _PATH_OVERRIDE_RE.search("\n".join(source.splitlines()[:5]))
    if m:
        scope = m.group(1)
    if root is not None:
        try:
            display = path.resolve().relative_to(root.resolve()).as_posix()
        except ValueError:
            display = str(path)
    else:
        display = str(path)
    return ModuleIndex(path, display, scope, source)


# ---------------------------------------------------------------------------
# model cache (ISSUE 15): warm `make lint` re-analyzes only changed files
# ---------------------------------------------------------------------------

CACHE_DIR_NAME = ".graftlint_cache"
_CACHE_VERSION = 1
_engine_digest_memo: Optional[str] = None


def _engine_digest() -> str:
    """Invalidation key: a cached model is only valid for the engine
    source (and interpreter) that built it — ast node shapes and the
    analysis itself both change across versions."""
    global _engine_digest_memo
    if _engine_digest_memo is None:
        h = hashlib.sha256()
        h.update(Path(__file__).read_bytes())
        h.update(sys.version.encode())
        _engine_digest_memo = h.hexdigest()
    return _engine_digest_memo


def _set_display(mod: ModuleIndex, path: Path, root: Optional[Path]) -> None:
    # display is the only root-dependent field — recompute it after a
    # cache hit so findings render identically with any cwd/root
    if root is not None:
        try:
            mod.display = path.resolve().relative_to(
                root.resolve()).as_posix()
            return
        except ValueError:
            pass
    mod.display = str(path)


def _load_module_cached(f: Path, root: Optional[Path],
                        cache_dir: Path) -> ModuleIndex:
    """load_module through a (path, mtime_ns, size)-keyed pickle cache.
    Every failure mode (corrupt pickle, racing writer, read-only dir)
    falls back to a fresh parse — the cache can never change results,
    only skip work (parity-tested in test_graftlint.py)."""
    key = hashlib.sha256(
        str(f.resolve()).encode()).hexdigest()[:32]
    cpath = cache_dir / f"{key}.pkl"
    try:
        st = f.stat()
        with open(cpath, "rb") as fh:
            tag, mtime, size, digest, mod = pickle.load(fh)
        if (tag == _CACHE_VERSION and mtime == st.st_mtime_ns
                and size == st.st_size and digest == _engine_digest()
                and isinstance(mod, ModuleIndex)):
            _set_display(mod, f, root)
            return mod
    except Exception:
        pass
    mod = load_module(f, root)
    try:
        cache_dir.mkdir(exist_ok=True)
        tmp = cache_dir / f".{key}.{os.getpid()}.tmp"
        with open(tmp, "wb") as fh:
            pickle.dump((_CACHE_VERSION, f.stat().st_mtime_ns,
                         f.stat().st_size, _engine_digest(), mod),
                        fh, pickle.HIGHEST_PROTOCOL)
        tmp.replace(cpath)
    except Exception:
        pass
    return mod


def collect_files(paths: List[Path]) -> List[Path]:
    files: List[Path] = []
    seen = set()  # dedupe: a file named alongside its containing dir
    for p in paths:
        cands = sorted(p.rglob("*.py")) if p.is_dir() else (
            [p] if p.suffix == ".py" else [])
        for f in cands:
            key = f.resolve()
            if key in seen or "__pycache__" in f.parts:
                continue
            seen.add(key)
            files.append(f)
    return files


def build_project(paths: List[Path], root: Optional[Path] = None,
                  cache: bool = True):
    """Returns (Project, [Finding]) — the findings are parse errors.

    With ``cache=True`` and a ``root``, per-module models are pickled
    under ``<root>/.graftlint_cache/`` keyed (path, mtime_ns, size) +
    engine digest; rootless calls (single-fixture lints in tests) never
    touch the cache."""
    from ray_tpu.devtools.graftlint.model import Finding

    cache_dir = (root / CACHE_DIR_NAME) if (cache and root is not None) \
        else None
    modules, errors = [], []
    for f in collect_files(paths):
        try:
            if cache_dir is not None:
                modules.append(_load_module_cached(f, root, cache_dir))
            else:
                modules.append(load_module(f, root))
        except SyntaxError as e:
            errors.append(Finding(str(f), e.lineno or 0, "parse-error",
                                  f"syntax error: {e.msg}"))
    whole = any(p.is_dir() and (p.name == "ray_tpu"
                                or (p / "ray_tpu").is_dir())
                for p in paths)
    return Project(modules, whole_package=whole), errors


def run_rules(project: Project, rules) -> List:
    """Run rules, drop suppressed findings, return sorted findings."""
    by_display = {m.display: m for m in project.modules}
    findings = []
    for rule in rules:
        for f in rule.check(project):
            if getattr(rule, "suppressible", True):
                mod = by_display.get(f.path)
                if mod is not None and mod.is_suppressed(f.line, f.rule):
                    continue
            findings.append(f)
    return sorted(set(findings), key=lambda f: f.sort_key())
