"""Failpoint-site rule (family ``failpoints``).

The chaos plane (ISSUE 5) is only as trustworthy as its site catalog:
``tests/test_chaos_matrix.py`` arms sites by name, so a typo'd,
duplicated, or undocumented site silently turns a regression test into a
no-op. The docstring of ``util/failpoints.py`` is the canonical list;
this rule keeps code and catalog bidirectionally in sync.
"""

from __future__ import annotations

import ast
import re
from collections import defaultdict
from typing import Dict, Iterator, List, Set, Tuple

from ray_tpu.devtools.graftlint.engine import Project
from ray_tpu.devtools.graftlint.model import (
    FAMILY_FAILPOINTS,
    Finding,
    Rule,
    register,
)

_SITE_LINE = re.compile(r"^\s{4}([a-z0-9_.]+)\s{2,}\S")


def documented_sites(failpoints_source: str) -> Set[str]:
    """Parse the ``Sites`` block of util/failpoints.py's docstring."""
    tree = ast.parse(failpoints_source)
    doc = ast.get_docstring(tree) or ""
    sites: Set[str] = set()
    in_block = False
    for line in doc.splitlines():
        if line.startswith("Sites"):
            in_block = True
            continue
        if in_block:
            m = _SITE_LINE.match(line)
            if m:
                sites.add(m.group(1))
            elif line.strip() and not line.startswith(" "):
                break  # next top-level section
    return sites


@register
class FailpointSites(Rule):
    name = "failpoint-sites"
    family = FAMILY_FAILPOINTS
    summary = ("every failpoints.hit(name) site uses a unique literal "
               "name that appears in util/failpoints.py's documented "
               "site list (and every documented site still exists)")

    def check(self, project: Project) -> Iterator[Finding]:
        fp_mod = project.module("ray_tpu/util/failpoints.py")
        documented = (documented_sites(fp_mod.source)
                      if fp_mod is not None else None)
        sites: Dict[str, List[Tuple]] = defaultdict(list)
        for mod in project.modules:
            if mod.scope_rel == "ray_tpu/util/failpoints.py":
                continue
            for cs in mod.calls:
                is_hit = (cs.fq == "ray_tpu.util.failpoints.hit"
                          or (cs.parts and len(cs.parts) >= 2
                              and cs.parts[-2:] == ("failpoints", "hit")))
                if not is_hit:
                    continue
                if not cs.node.args or not isinstance(
                        cs.node.args[0], ast.Constant) or not isinstance(
                        cs.node.args[0].value, str):
                    yield self.finding(
                        mod, cs.line,
                        "failpoints.hit() with a non-literal site name — "
                        "sites must be greppable string literals (the "
                        "docstring catalog and chaos matrix key off them)")
                    continue
                sites[cs.node.args[0].value].append((mod, cs.line))
        for name, uses in sorted(sites.items()):
            if len(uses) > 1:
                locs = ", ".join(f"{m.display}:{ln}" for m, ln in uses)
                for m, ln in uses:
                    yield self.finding(
                        m, ln,
                        f"failpoint site '{name}' is hit from "
                        f"{len(uses)} call sites ({locs}) — site names "
                        f"are unique per call site so times=/once= "
                        f"budgets stay attributable; add a suffixed name")
            if documented is not None and name not in documented:
                m, ln = uses[0]
                yield self.finding(
                    m, ln,
                    f"failpoint site '{name}' is not in util/"
                    f"failpoints.py's documented Sites list — add it "
                    f"there (the docstring is the canonical catalog the "
                    f"chaos matrix authors read)")
        # stale-doc direction needs full-tree knowledge (whole_package);
        # given that, it must fire even when ZERO hit() sites remain —
        # that is the fully-stale-catalog case
        if documented is not None and fp_mod is not None \
                and project.whole_package:
            for name in sorted(documented - set(sites)):
                yield self.finding(
                    fp_mod, 1,
                    f"documented failpoint site '{name}' has no "
                    f"failpoints.hit call site left in the tree — "
                    f"remove it from the Sites list or restore the site")
