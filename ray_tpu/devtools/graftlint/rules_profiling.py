"""Profiling-plane discipline rules (family ``invariants``).

The sampling profiler (ISSUE 9, ``util/profiling.py``) observes every
instrumented runtime path from a background thread at ~67 Hz. That only
stays safe while the sampler is a pure OBSERVER: if its loop acquired a
TimedLock/TimedRLock-wrapped runtime lock it could deadlock against the
very contention it exists to measure; if it hit a failpoint it could
fire chaos inside the sampler; if it recorded spans it would recurse
into the instrumented tracing path and profile itself. This rule makes
that contract lexical.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set

from ray_tpu.devtools.graftlint.engine import Project, dotted_parts
from ray_tpu.devtools.graftlint.model import (
    FAMILY_INVARIANTS,
    Finding,
    Rule,
    register,
)

#: function names treated as the sampler's code path wherever they live
_SAMPLER_FUNCS = {"_sample_loop", "_sample_once"}
#: callables whose result is an instrumented (timed) lock
_TIMED_FACTORIES = {"timed_lock", "timed_rlock", "TimedLock", "TimedRLock"}
#: span-recording entry points of the tracing plane
_SPAN_FNS = {"span", "manual_span", "record_span"}


def _timed_lock_attrs(tree: ast.AST) -> Set[str]:
    """Attribute/variable names assigned from a timed-lock factory
    anywhere in the module (``self.lock = timed_lock(...)``,
    ``LOCK = TimedRLock(...)``)."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or \
                not isinstance(node.value, ast.Call):
            continue
        parts = dotted_parts(node.value.func)
        if not parts or parts[-1] not in _TIMED_FACTORIES:
            continue
        for t in node.targets:
            if isinstance(t, ast.Attribute):
                out.add(t.attr)
            elif isinstance(t, ast.Name):
                out.add(t.id)
    return out


def _sampler_scopes(tree: ast.AST) -> List[ast.AST]:
    """Function bodies that ARE the sampler: ``_sample_loop`` /
    ``_sample_once`` anywhere, plus every method of a class whose name
    contains ``Sampler``."""
    scopes: List[ast.AST] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and "Sampler" in node.name:
            scopes.extend(
                n for n in node.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name in _SAMPLER_FUNCS:
            scopes.append(node)
    return scopes


def _lock_target(expr: ast.AST) -> Optional[str]:
    """The lock name when ``expr`` is ``self.X`` / bare ``X`` (with-item
    or ``.acquire()`` receiver), else None."""
    parts = dotted_parts(expr)
    if not parts:
        return None
    if parts[0] == "self" and len(parts) == 2:
        return parts[1]
    if len(parts) == 1:
        return parts[0]
    return None


@register
class ProfilerSamplerDiscipline(Rule):
    name = "profiler-sampler-discipline"
    family = FAMILY_INVARIANTS
    summary = ("the sampling profiler's loop (_sample_loop/_sample_once "
               "and *Sampler* methods) stays observer-only: it may not "
               "acquire TimedLock/TimedRLock-wrapped locks, hit "
               "failpoints, or record tracing spans — it must never "
               "deadlock against or recurse into the instrumented paths "
               "it measures")

    def check(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules:
            # pre-indexed gate: sampler scopes exist only in modules
            # with a *Sampler* class or a _sample_loop/_sample_once
            # function — skip the two full-module walks everywhere else
            if not (any("Sampler" in c for c in mod.classes)
                    or any(fi.name in _SAMPLER_FUNCS
                           for fi in mod.functions.values())):
                continue
            timed = _timed_lock_attrs(mod.tree)
            seen_lines: Set[int] = set()
            for scope in _sampler_scopes(mod.tree):
                for node in ast.walk(scope):
                    for f in self._check_node(mod, node, timed):
                        if f.line not in seen_lines:
                            seen_lines.add(f.line)
                            yield f

    def _check_node(self, mod, node: ast.AST,
                    timed: Set[str]) -> Iterator[Finding]:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                lk = _lock_target(item.context_expr)
                if lk and lk in timed:
                    yield self.finding(
                        mod, node.lineno,
                        f"sampler loop acquires timed lock '{lk}' — the "
                        "profiler must stay observer-only (a "
                        "TimedLock/TimedRLock here can deadlock against "
                        "the contention it measures and records "
                        "rtpu_lock_* metrics from inside the sampler); "
                        "use a plain threading.Lock private to the "
                        "sampler")
            return
        if not isinstance(node, ast.Call):
            return
        parts = dotted_parts(node.func)
        if not parts:
            return
        if parts[-1] == "acquire":
            lk = _lock_target(node.func.value) \
                if isinstance(node.func, ast.Attribute) else None
            if lk and lk in timed:
                yield self.finding(
                    mod, node.lineno,
                    f"sampler loop calls {lk}.acquire() on a timed "
                    "lock — observer-only discipline (see "
                    "profiler-sampler-discipline)")
        elif parts[-1] == "hit" and (len(parts) == 1
                                     or parts[-2] == "failpoints"):
            yield self.finding(
                mod, node.lineno,
                "sampler loop hits a failpoint site — the chaos plane "
                "must never fire inside the profiler (a delay/raise "
                "here stalls or kills sampling for the whole process)")
        elif parts[-1] in _SPAN_FNS and len(parts) >= 2 \
                and parts[-2] == "tracing":
            yield self.finding(
                mod, node.lineno,
                f"sampler loop records a tracing {parts[-1]}() — the "
                "profiler would recurse into the instrumented trace "
                "path and profile itself; profile data leaves via "
                "drain_batches(), not spans")
        elif parts[-1] in _TIMED_FACTORIES:
            yield self.finding(
                mod, node.lineno,
                f"sampler loop constructs {parts[-1]}() — sampler-"
                "private locks must be plain threading.Lock "
                "(observer-only discipline)")
