"""Layering-seam rule (family ``layering``).

The portability seam from CLAUDE.md: everything ML-level builds ONLY on
the public task/actor/object API — the same property that lets the
reference's libraries (data/train/tune/serve/rllib, pure Python over L3)
run anywhere the core runs. One private import quietly couples a library
to driver internals and the seam is gone.
"""

from __future__ import annotations

from typing import Iterator

from ray_tpu.devtools.graftlint.engine import Project
from ray_tpu.devtools.graftlint.model import (
    FAMILY_LAYERING,
    Finding,
    Rule,
    register,
)

#: subpackages on the ML side of the seam
ML_LAYERS = ("data", "train", "tune", "serve", "rllib")

#: import prefixes the ML layers may use. The seam bans core/cluster
#: *internals*; public exception types and the util/ surface (state API,
#: metrics, placement groups...) are part of the contract.
ALLOWED_PREFIXES = (
    "ray_tpu.core.exceptions",
)


def _banned(fq: str) -> bool:
    if not fq.startswith(("ray_tpu.core", "ray_tpu.cluster")):
        return False
    return not any(fq == p or fq.startswith(p + ".")
                   for p in ALLOWED_PREFIXES)


@register
class LayeringSeam(Rule):
    name = "layering-seam"
    family = FAMILY_LAYERING
    summary = ("data/train/tune/serve/rllib import only the public "
               "task/actor/object API (top-level ray_tpu), util/, and "
               "sibling libraries — never core.*/cluster.* internals "
               "(except core.exceptions)")

    def check(self, project: Project) -> Iterator[Finding]:
        prefixes = tuple(f"ray_tpu/{p}/" for p in ML_LAYERS)
        for mod in project.modules:
            if not mod.scope_rel.startswith(prefixes):
                continue
            for line, fq in mod.all_import_nodes:
                if _banned(fq):
                    layer = mod.scope_rel.split("/")[1]
                    yield self.finding(
                        mod, line,
                        f"ray_tpu.{layer} imports {fq} — ML libraries "
                        f"build ONLY on the public task/actor/object API "
                        f"(CLAUDE.md portability seam); use the ray_tpu "
                        f"top-level API or add a public accessor to "
                        f"ray_tpu.util")
