"""Layering-seam rules (family ``layering``).

The portability seam from CLAUDE.md: everything ML-level builds ONLY on
the public task/actor/object API — the same property that lets the
reference's libraries (data/train/tune/serve/rllib, pure Python over L3)
run anywhere the core runs. One private import quietly couples a library
to driver internals and the seam is gone.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ray_tpu.devtools.graftlint.engine import Project, dotted_parts
from ray_tpu.devtools.graftlint.model import (
    FAMILY_LAYERING,
    Finding,
    Rule,
    register,
)

#: subpackages on the ML side of the seam
ML_LAYERS = ("data", "train", "tune", "serve", "rllib")

#: import prefixes the ML layers may use. The seam bans core/cluster
#: *internals*; public exception types and the util/ surface (state API,
#: metrics, placement groups...) are part of the contract.
ALLOWED_PREFIXES = (
    "ray_tpu.core.exceptions",
)


def _banned(fq: str) -> bool:
    if not fq.startswith(("ray_tpu.core", "ray_tpu.cluster")):
        return False
    return not any(fq == p or fq.startswith(p + ".")
                   for p in ALLOWED_PREFIXES)


@register
class LayeringSeam(Rule):
    name = "layering-seam"
    family = FAMILY_LAYERING
    summary = ("data/train/tune/serve/rllib import only the public "
               "task/actor/object API (top-level ray_tpu), util/, and "
               "sibling libraries — never core.*/cluster.* internals "
               "(except core.exceptions)")

    def check(self, project: Project) -> Iterator[Finding]:
        prefixes = tuple(f"ray_tpu/{p}/" for p in ML_LAYERS)
        for mod in project.modules:
            if not mod.scope_rel.startswith(prefixes):
                continue
            for line, fq in mod.all_import_nodes:
                if _banned(fq):
                    layer = mod.scope_rel.split("/")[1]
                    yield self.finding(
                        mod, line,
                        f"ray_tpu.{layer} imports {fq} — ML libraries "
                        f"build ONLY on the public task/actor/object API "
                        f"(CLAUDE.md portability seam); use the ray_tpu "
                        f"top-level API or add a public accessor to "
                        f"ray_tpu.util")


@register
class ServeRuntimeSeam(Rule):
    name = "serve-runtime-seam"
    family = FAMILY_LAYERING
    summary = ("the serving tier never touches runtime internals through "
               "an allowed module's private surface: no _get_runtime/"
               "global_worker calls and no module._private attribute "
               "reads from ray_tpu.serve (ISSUE 12 — load-aware routing "
               "reads state.actor_queue_depths and controller-mediated "
               "load reports, not the driver's tables); and only "
               "serve/kv_transfer.py may import ray_tpu.experimental "
               "channels (ISSUE 13's ONE sanctioned exception — public "
               "exception types are fine anywhere)")

    #: private runtime accessors the routing work is tempted by, in any
    #: spelling (bare call after a from-import, or module-qualified)
    BANNED_NAMES = ("_get_runtime", "global_worker", "global_runtime")

    #: the one serve module sanctioned to ride the experimental
    #: DeviceChannel rings (CLAUDE.md architecture invariants, r16)
    CHANNEL_EXEMPT = "serve/kv_transfer.py"

    def check(self, project: Project) -> Iterator[Finding]:
        for mod in project.modules:
            if not mod.scope_rel.startswith("ray_tpu/serve/"):
                continue
            if not mod.scope_rel.endswith(self.CHANNEL_EXEMPT):
                for line, fq in mod.all_import_nodes:
                    if not fq.startswith("ray_tpu.experimental"):
                        continue
                    # exception TYPES are contract surface (handles catch
                    # ChannelFullError on the compiled path) — transports,
                    # rings, and channel classes are not
                    if fq.rpartition(".")[2].endswith("Error"):
                        continue
                    yield self.finding(
                        mod, line,
                        f"ray_tpu.serve imports {fq} — only "
                        f"serve/kv_transfer.py rides the experimental "
                        f"channel plane (the sanctioned KV-shipping "
                        f"seam); everything else in the serving tier "
                        f"stays on the public task/actor/object API")
            # ast.walk visits every NESTED Attribute of one chain
            # (`a.b.c` -> a.b.c, a.b): dedupe by (line, offending name)
            # so one violation reports once
            seen = set()
            for node in ast.walk(mod.tree):
                if not isinstance(node, (ast.Attribute, ast.Name)):
                    continue
                parts = dotted_parts(node)
                if not parts:
                    continue
                hit = next((p for p in parts
                            if p in self.BANNED_NAMES), None)
                if hit is not None:
                    if (node.lineno, hit) in seen:
                        continue
                    seen.add((node.lineno, hit))
                    # bare name must actually BE the runtime accessor
                    # (an unrelated local `global_worker` variable is
                    # implausible enough to flag anyway — naming it that
                    # in serve/ is the confusion this rule exists for)
                    yield self.finding(
                        mod, node.lineno,
                        f"ray_tpu.serve reaches runtime internals via "
                        f"{'.'.join(parts)} — route load/queue state "
                        f"through ray_tpu.util.state or the serve "
                        f"controller's replica load reports")
                    continue
                # module-qualified private attribute: state._gcs(),
                # ray_tpu._private_thing — resolving the HEAD through the
                # import table proves it's a module, so self._x and
                # handle-internal attributes stay clean
                if len(parts) < 2:
                    continue
                priv = next((i for i, p in enumerate(parts)
                             if i > 0 and p.startswith("_")
                             and not p.startswith("__")), None)
                if priv is None:
                    continue
                key = (node.lineno, ".".join(parts[:priv + 1]))
                if key in seen:
                    continue
                fq = mod.resolve_parts(parts[:priv])
                if (fq is not None and fq.startswith("ray_tpu")
                        and not fq.startswith("ray_tpu.serve")):
                    # intra-tier privates (serve.handle._dag_cache from
                    # serve.api) are the tier's own business
                    seen.add(key)
                    yield self.finding(
                        mod, node.lineno,
                        f"ray_tpu.serve reads private attribute "
                        f"{'.'.join(parts[:priv + 1])} of {fq} — the "
                        f"serving tier stays on the public API seam; "
                        f"add a public accessor instead")
