"""Wire-protocol drift rules (family ``protocol``, ISSUE 15).

The runtime speaks four multi-process vocabularies: the worker<->driver
pipe (casts / reqs / top-level frame kinds), GCS RPC methods, peer
(daemon<->daemon) RPC methods, and pubsub topics. Each one has three
surfaces that must agree: the *senders* (literal ops at call sites), the
*dispatch arms* (``if op == "...":`` chains in the designated handler
functions), and the checked-in catalog (``ray_tpu/core/protocol.py``).

These rules extract the first two from the AST and diff all three — the
failpoint-doc-sync pattern applied to the whole wire. A send without a
handler is a silently-dropped message; a handler without a sender is
dead protocol (r14's native migration left two: the driver's ``refpin``
cast arm and the worker's driver->worker ``batch`` arm, both kept as
regression fixtures); drift from the catalog means the review surface
lied.

Cross-surface checks only fire when both sides are in scope (whole-tree
lints); the catalog-membership direction works on a single file, which
is what the fixtures exercise.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, Iterator, Optional, Tuple

from ray_tpu.devtools.graftlint.engine import ModuleIndex, Project
from ray_tpu.devtools.graftlint.model import (
    FAMILY_PROTOCOL,
    Finding,
    Rule,
    register,
)

CATALOG_SCOPE = "ray_tpu/core/protocol.py"
WORKER_SCOPE = "ray_tpu/core/worker.py"
RUNTIME_SCOPE = "ray_tpu/core/runtime.py"
GCS_SCOPE = "ray_tpu/cluster/gcs_server.py"
ADAPTER_SCOPE = "ray_tpu/cluster/adapter.py"


# ---------------------------------------------------------------------------
# catalog access: parse, never import (a lint run must not pull in the
# ray_tpu package)
# ---------------------------------------------------------------------------

def _parse_catalog(tree: ast.Module) -> Dict[str, Tuple[frozenset, int]]:
    out: Dict[str, Tuple[frozenset, int]] = {}
    for node in tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        val = node.value
        elts = None
        if (isinstance(val, ast.Call) and isinstance(val.func, ast.Name)
                and val.func.id == "frozenset" and val.args
                and isinstance(val.args[0], (ast.Set, ast.Tuple, ast.List))):
            elts = val.args[0].elts
        elif isinstance(val, (ast.Tuple, ast.Set, ast.List)):
            elts = val.elts
        if elts is None:
            continue
        lits = frozenset(e.value for e in elts
                         if isinstance(e, ast.Constant)
                         and isinstance(e.value, str))
        out[node.targets[0].id] = (lits, node.lineno)
    return out


def load_catalog(project: Project):
    """(catalog dict, catalog ModuleIndex or None). Prefers the catalog
    module inside the lint scope (so the drift test can substitute a
    modified one via the path override); falls back to the checked-in
    file on disk for single-file lints."""
    mod = project.module(CATALOG_SCOPE)
    if mod is not None:
        return _parse_catalog(mod.tree), mod
    p = Path(__file__).resolve().parents[2] / "core" / "protocol.py"
    try:
        return _parse_catalog(ast.parse(p.read_text())), None
    except Exception:
        return {}, None


# ---------------------------------------------------------------------------
# AST extraction helpers
# ---------------------------------------------------------------------------

def _const_str(node) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _literal_arg(call: ast.Call, idx: int) -> Optional[str]:
    if len(call.args) > idx:
        return _const_str(call.args[idx])
    return None


def dispatch_arms(mod: ModuleIndex, func_names,
                  var_names=("op", "kind", "method")) -> Dict[str, int]:
    """Literal arms of ``if <var> == "lit"`` / ``<var> in ("a", "b")`` /
    ``msg[0] == "lit"`` chains inside the named handler functions."""
    arms: Dict[str, int] = {}
    for fi in mod.functions.values():
        if fi.name not in func_names:
            continue
        for node in ast.walk(fi.node):
            if not (isinstance(node, ast.Compare) and len(node.ops) == 1
                    and isinstance(node.ops[0], (ast.Eq, ast.In))):
                continue
            left = node.left
            named = isinstance(left, ast.Name) and left.id in var_names
            # msg[0] == "batch" — restricted to the frame variable so a
            # payload compare (args[0] == "avail") is not a dispatch arm
            sub0 = (isinstance(left, ast.Subscript)
                    and isinstance(left.slice, ast.Constant)
                    and left.slice.value == 0
                    and isinstance(left.value, ast.Name)
                    and left.value.id == "msg")
            if not (named or sub0):
                continue
            comp = node.comparators[0]
            lits = []
            s = _const_str(comp)
            if s is not None:
                lits.append(s)
            elif isinstance(comp, (ast.Tuple, ast.List, ast.Set)):
                lits.extend(v for v in map(_const_str, comp.elts)
                            if v is not None)
            for lit in lits:
                arms.setdefault(lit, node.lineno)
    return arms


def _ifexp_branches(node):
    if isinstance(node, ast.IfExp):
        yield from _ifexp_branches(node.body)
        yield from _ifexp_branches(node.orelse)
    else:
        yield node


#: call tails that ship a ``(kind, ...)`` tuple down the pipe; _dropped
#: sees the same tuples (the chaos filter inspects the message it may
#: drop), so literal kinds reach the extractor even when the send itself
#: passes a variable
_SEND_TAILS = {"send", "_send", "_send_frame", "_dropped"}


def tuple_send_kinds(mod: ModuleIndex) -> Dict[str, int]:
    kinds: Dict[str, int] = {}
    for cs in mod.calls:
        if not cs.parts or cs.parts[-1] not in _SEND_TAILS:
            continue
        if not cs.node.args:
            continue
        for arg in _ifexp_branches(cs.node.args[0]):
            if isinstance(arg, ast.Tuple) and arg.elts:
                lit = _const_str(arg.elts[0])
                if lit is not None:
                    kinds.setdefault(lit, cs.line)
    return kinds


def _op_calls(mod: ModuleIndex, parts: Tuple[str, ...]) -> Dict[str, int]:
    """Literal first args of calls matching exactly ``parts``
    (e.g. ``self.cast("put", ...)``)."""
    out: Dict[str, int] = {}
    for cs in mod.calls:
        if cs.parts == parts:
            lit = _literal_arg(cs.node, 0)
            if lit is not None:
                out.setdefault(lit, cs.line)
    return out


def _fmt(names) -> str:
    return ", ".join(sorted(names))


# ---------------------------------------------------------------------------
# rule 1: the worker<->driver pipe
# ---------------------------------------------------------------------------

@register
class PipeProtocolSync(Rule):
    name = "pipe-protocol-sync"
    family = FAMILY_PROTOCOL
    summary = ("worker<->driver pipe vocabulary (casts, reqs, frame "
               "kinds) must agree three ways: every sender literal has a "
               "dispatch arm, every arm a sender, and both match the "
               "PIPE_* catalog in core/protocol.py")

    #: handler functions per direction (code facts, not protocol — the
    #: catalog holds the vocabulary, this holds where it is dispatched)
    RUNTIME_CAST_HANDLERS = ("_handle_cast",)
    RUNTIME_REQ_HANDLERS = ("_handle_req",)
    RUNTIME_KIND_HANDLERS = ("_handle_msg", "_accept_loop", "_reader_loop",
                             "_native_reader_loop")
    WORKER_KIND_HANDLERS = ("_dispatch_recv", "_recv_loop")

    def check(self, project: Project) -> Iterator[Finding]:
        catalog, cat_mod = load_catalog(project)
        casts = catalog.get("PIPE_CASTS", (frozenset(), 0))[0]
        reqs = catalog.get("PIPE_REQS", (frozenset(), 0))[0]
        wkinds = catalog.get("PIPE_WORKER_MSGS", (frozenset(), 0))[0]
        dkinds = catalog.get("PIPE_DRIVER_MSGS", (frozenset(), 0))[0]
        if not casts:
            return  # no catalog reachable: nothing to diff against

        worker = project.module(WORKER_SCOPE)
        runtime = project.module(RUNTIME_SCOPE)

        sent_casts = _op_calls(worker, ("self", "cast")) if worker else {}
        sent_reqs = _op_calls(worker, ("self", "request")) if worker else {}
        sent_wkinds = tuple_send_kinds(worker) if worker else {}
        sent_dkinds = tuple_send_kinds(runtime) if runtime else {}
        cast_arms = dispatch_arms(
            runtime, self.RUNTIME_CAST_HANDLERS) if runtime else {}
        req_arms = dispatch_arms(
            runtime, self.RUNTIME_REQ_HANDLERS) if runtime else {}
        wkind_arms = dispatch_arms(
            runtime, self.RUNTIME_KIND_HANDLERS) if runtime else {}
        dkind_arms = dispatch_arms(
            worker, self.WORKER_KIND_HANDLERS) if worker else {}

        surfaces = [
            # (vocab-name, catalog set, sender mod, sent, handler mod, arms)
            ("PIPE_CASTS", casts, worker, sent_casts, runtime, cast_arms),
            ("PIPE_REQS", reqs, worker, sent_reqs, runtime, req_arms),
            ("PIPE_WORKER_MSGS", wkinds, worker, sent_wkinds,
             runtime, wkind_arms),
            ("PIPE_DRIVER_MSGS", dkinds, runtime, sent_dkinds,
             worker, dkind_arms),
        ]
        for vocab, allowed, smod, sent, hmod, arms in surfaces:
            # catalog membership: works on a single file
            if smod is not None:
                for op, line in sorted(sent.items()):
                    if op not in allowed:
                        yield self.finding(
                            smod, line,
                            f"pipe op '{op}' is sent but absent from "
                            f"{vocab} in core/protocol.py — add it to the "
                            f"catalog (and a dispatch arm) or drop the "
                            f"send")
            if hmod is not None:
                for op, line in sorted(arms.items()):
                    if op not in allowed:
                        yield self.finding(
                            hmod, line,
                            f"dispatch arm for '{op}' is absent from "
                            f"{vocab} in core/protocol.py — dead protocol "
                            f"arm (r14-style leftover) or missing catalog "
                            f"entry")
            # sender<->handler sync: needs both modules in scope
            if smod is None or hmod is None:
                continue
            for op, line in sorted(sent.items()):
                if op in allowed and op not in arms:
                    yield self.finding(
                        smod, line,
                        f"pipe op '{op}' is sent but has no dispatch arm "
                        f"in {'/'.join(self._handlers_for(vocab))} — the "
                        f"message would be silently dropped")
            for op, line in sorted(arms.items()):
                if op in allowed and op not in sent:
                    yield self.finding(
                        hmod, line,
                        f"dispatch arm for '{op}' has no sender — dead "
                        f"protocol; remove the arm (keep the catalog "
                        f"honest) or wire up the sender")
            # catalog completeness: only when the catalog module itself
            # is in scope alongside both endpoints
            if cat_mod is not None:
                stale = allowed - set(sent) - set(arms)
                if stale:
                    line = catalog.get(vocab, (frozenset(), 1))[1]
                    yield self.finding(
                        cat_mod, line,
                        f"{vocab} lists {_fmt(stale)} but the tree "
                        f"neither sends nor handles them — stale catalog "
                        f"entries")

    def _handlers_for(self, vocab: str):
        return {
            "PIPE_CASTS": self.RUNTIME_CAST_HANDLERS,
            "PIPE_REQS": self.RUNTIME_REQ_HANDLERS,
            "PIPE_WORKER_MSGS": self.RUNTIME_KIND_HANDLERS,
            "PIPE_DRIVER_MSGS": self.WORKER_KIND_HANDLERS,
        }[vocab]


# ---------------------------------------------------------------------------
# rule 2: GCS + peer RPC
# ---------------------------------------------------------------------------

import re as _re

#: an RPC method literal: lowercase snake_case, >= 4 chars — excludes
#: ``memoryview.cast("B")`` and friends by construction
_METHOD_RE = _re.compile(r"^[a-z][a-z0-9_]{3,}$")

#: adapter helpers that take the method literal at arg index 1
_INDIRECT_SENDERS = {"_pg_call", "_call_with_attempt"}


def rpc_sender_literals(mod: ModuleIndex) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for cs in mod.calls:
        if not cs.parts:
            continue
        tail = cs.parts[-1]
        if tail in ("call", "cast"):
            # the worker's self.cast() is pipe vocabulary, not RPC
            if mod.scope_rel == WORKER_SCOPE and cs.parts == ("self",
                                                              "cast"):
                continue
            lit = _literal_arg(cs.node, 0)
        elif tail in _INDIRECT_SENDERS:
            lit = _literal_arg(cs.node, 1)
        else:
            continue
        if lit is not None and _METHOD_RE.match(lit):
            out.setdefault(lit, cs.line)
    return out


def _dict_key_literals(mod: ModuleIndex, func_names) -> Dict[str, int]:
    """String keys of dict literals inside the named functions — the
    adapter's local pg dispatch table names its peer methods this way."""
    out: Dict[str, int] = {}
    for fi in mod.functions.values():
        if fi.name not in func_names:
            continue
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Dict):
                for k in node.keys:
                    lit = _const_str(k)
                    if lit is not None and _METHOD_RE.match(lit):
                        out.setdefault(lit, node.lineno)
    return out


@register
class RpcMethodSync(Rule):
    name = "rpc-method-sync"
    family = FAMILY_PROTOCOL
    summary = ("every RPC literal sent via .call()/.cast() must name a "
               "registered GCS rpc_* method or a peer _serve_peer arm, "
               "and every registered method must have a sender (dynamic "
               "'kv_'+op dispatch is cataloged as a prefix)")

    def check(self, project: Project) -> Iterator[Finding]:
        catalog, _cat_mod = load_catalog(project)
        gcs_rpc = catalog.get("GCS_RPC", (frozenset(), 0))[0]
        peer_rpc = catalog.get("PEER_RPC", (frozenset(), 0))[0]
        prefixes = tuple(catalog.get("GCS_RPC_DYNAMIC_PREFIXES",
                                     (frozenset(), 0))[0])
        if not gcs_rpc:
            return
        allowed = gcs_rpc | peer_rpc

        # senders: the whole scope
        sent: Dict[str, int] = {}
        for mod in project.modules:
            for lit, line in rpc_sender_literals(mod).items():
                if lit not in allowed:
                    yield self.finding(
                        mod, line,
                        f"RPC literal '{lit}' is not a cataloged GCS or "
                        f"peer method (core/protocol.py) — a typo here "
                        f"fails at runtime with method-not-found")
                sent.setdefault(lit, line)

        # handlers: GCS rpc_* methods
        gcs = project.module(GCS_SCOPE)
        if gcs is not None:
            for ci in gcs.classes.values():
                for mname, fi in ci.methods.items():
                    if not mname.startswith("rpc_"):
                        continue
                    op = mname[4:]
                    if op not in gcs_rpc:
                        yield self.finding(
                            gcs, fi.lineno,
                            f"rpc_{op} is registered but absent from "
                            f"GCS_RPC in core/protocol.py — update the "
                            f"catalog alongside the method")
                    elif (project.whole_package and op not in sent
                          and not any(op.startswith(p) for p in prefixes)):
                        yield self.finding(
                            gcs, fi.lineno,
                            f"rpc_{op} has no sender anywhere in the "
                            f"tree — dead RPC surface; remove it or add "
                            f"the caller (dynamic dispatch needs a "
                            f"GCS_RPC_DYNAMIC_PREFIXES entry)")

        # handlers: peer _serve_peer arms (+ the local pg dispatch table,
        # which names the same methods)
        adapter = project.module(ADAPTER_SCOPE)
        if adapter is not None:
            arms = dispatch_arms(adapter, ("_serve_peer",))
            for op, line in sorted(arms.items()):
                if op not in peer_rpc:
                    yield self.finding(
                        adapter, line,
                        f"_serve_peer arm '{op}' is absent from PEER_RPC "
                        f"in core/protocol.py — update the catalog "
                        f"alongside the arm")
                elif project.whole_package and op not in sent:
                    yield self.finding(
                        adapter, line,
                        f"_serve_peer arm '{op}' has no sender anywhere "
                        f"in the tree — dead peer protocol")
            if project.whole_package:
                for op in sorted(peer_rpc - set(arms)):
                    yield self.finding(
                        adapter, 1,
                        f"PEER_RPC lists '{op}' but _serve_peer has no "
                        f"arm for it — unhandled peer method")

        # catalog completeness for GCS methods
        if gcs is not None and project.whole_package:
            registered = {m[4:] for ci in gcs.classes.values()
                          for m in ci.methods if m.startswith("rpc_")}
            for op in sorted(gcs_rpc - registered):
                yield self.finding(
                    gcs, 1,
                    f"GCS_RPC lists '{op}' but no rpc_{op} method is "
                    f"registered — unhandled RPC")


# ---------------------------------------------------------------------------
# rule 3: pubsub topics
# ---------------------------------------------------------------------------

def _module_str_consts(mod: ModuleIndex) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for node in mod.tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            lit = _const_str(node.value)
            if lit is not None:
                out[node.targets[0].id] = lit
    return out


def _channel_arg(mod: ModuleIndex, node: ast.Call, idx: int,
                 consts: Dict[str, str]) -> Optional[str]:
    if len(node.args) <= idx:
        return None
    arg = node.args[idx]
    lit = _const_str(arg)
    if lit is not None:
        return lit
    # CHANNEL module constants (util/tracing.py etc. publish this way)
    if isinstance(arg, ast.Name):
        return consts.get(arg.id)
    return None


@register
class PubsubTopicSync(Rule):
    name = "pubsub-topic-sync"
    family = FAMILY_PROTOCOL
    summary = ("every published pubsub channel must be in the "
               "PUBSUB_CHANNELS catalog, and every cataloged channel "
               "must be both published and subscribed somewhere — a "
               "topic nobody reads (or a subscription nobody feeds) is "
               "dead wire surface")

    def check(self, project: Project) -> Iterator[Finding]:
        catalog, cat_mod = load_catalog(project)
        channels = catalog.get("PUBSUB_CHANNELS", (frozenset(), 0))[0]
        if not channels:
            return
        published: Dict[str, Tuple[ModuleIndex, int]] = {}
        subscribed: Dict[str, Tuple[ModuleIndex, int]] = {}
        for mod in project.modules:
            consts = _module_str_consts(mod)
            for cs in mod.calls:
                if not cs.parts:
                    continue
                tail = cs.parts[-1]
                ch = None
                sink = None
                if tail == "_publish":
                    ch = _channel_arg(mod, cs.node, 0, consts)
                    sink = published
                elif tail in ("call", "cast"):
                    op = _literal_arg(cs.node, 0)
                    if op == "publish":
                        ch = _channel_arg(mod, cs.node, 1, consts)
                        sink = published
                    elif op == "subscribe":
                        ch = _channel_arg(mod, cs.node, 1, consts)
                        sink = subscribed
                if ch is None or sink is None:
                    continue
                if ch not in channels:
                    verb = ("published"
                            if sink is published else "subscribed")
                    yield self.finding(
                        mod, cs.line,
                        f"pubsub channel '{ch}' is {verb} but absent "
                        f"from PUBSUB_CHANNELS in core/protocol.py")
                sink.setdefault(ch, (mod, cs.line))
        if cat_mod is not None and project.whole_package:
            line = catalog.get("PUBSUB_CHANNELS", (frozenset(), 1))[1]
            for ch in sorted(channels - set(published)):
                yield self.finding(
                    cat_mod, line,
                    f"PUBSUB_CHANNELS lists '{ch}' but nothing publishes "
                    f"it — stale topic")
            for ch in sorted(channels - set(subscribed)):
                yield self.finding(
                    cat_mod, line,
                    f"PUBSUB_CHANNELS lists '{ch}' but nothing "
                    f"subscribes to it — topic published into the void")
