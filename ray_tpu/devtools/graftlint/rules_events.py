"""Event-plane discipline rules (family ``invariants``).

The event plane (ISSUE 18) is only as debuggable as its event names:
``state.list_events()`` filters, ``rtpu events --name``, and the alert
rules all key off the flat ``lower_snake`` catalog in
``util/events.py``'s docstring. Every name is emitted from exactly ONE
call site (the reaping/registration site that owns the fact), so a
head-visible event is attributable to a single code path — the same
literal+unique+doc-sync contract as failpoint sites and tracing spans.
Both ``events.emit()`` (ring + ship) and ``events.record()`` (build
only — the GCS appends directly to its own store) are emission sites.
"""

from __future__ import annotations

import ast
import re
from collections import defaultdict
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ray_tpu.devtools.graftlint.engine import Project
from ray_tpu.devtools.graftlint.model import (
    FAMILY_INVARIANTS,
    Finding,
    Rule,
    register,
)

EVENTS_MOD = "ray_tpu/util/events.py"
_EMIT_FNS = ("emit", "record")
_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
_CATALOG_LINE = re.compile(r"^\s{4}([a-z][a-z0-9_]*)\s{2,}\S")


def documented_event_names(events_source: str) -> Set[str]:
    """Exact names from the ``Event names`` block of util/events.py's
    docstring. Event names are flat ``lower_snake`` identifiers — there
    is deliberately no dynamic-prefix escape hatch (unlike spans): the
    catalog is closed so death/alert consumers can switch on it."""
    tree = ast.parse(events_source)
    doc = ast.get_docstring(tree) or ""
    names: Set[str] = set()
    in_block = False
    seen_entry = False
    for line in doc.splitlines():
        if line.startswith("Event names"):
            in_block = True
            continue
        if in_block:
            m = _CATALOG_LINE.match(line)
            if m:
                seen_entry = True
                names.add(m.group(1))
            elif seen_entry and line.strip() and not line.startswith(" "):
                break  # next top-level section (after the entries)
    return names


def _is_event_call(cs) -> Optional[str]:
    """The event-API function name when ``cs`` emits events, else None."""
    if cs.fq and cs.fq.startswith("ray_tpu.util.events."):
        fn = cs.fq.rsplit(".", 1)[1]
        return fn if fn in _EMIT_FNS else None
    if (cs.parts and len(cs.parts) >= 2
            and cs.parts[-2] in ("events", "_events")
            and cs.parts[-1] in _EMIT_FNS):
        return cs.parts[-1]
    return None


def _event_name_arg(node: ast.Call):
    """('literal', name) for a str constant first arg, (None, None)
    otherwise — event names have no f-string prefix form."""
    if not node.args:
        return None, None
    arg = node.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return "literal", arg.value
    return None, None


@register
class EventNameCatalog(Rule):
    name = "event-name-catalog"
    family = FAMILY_INVARIANTS
    summary = ("lifecycle event names passed to events.emit()/record() "
               "are literal lower_snake strings, unique per call site, "
               "and present in util/events.py's Event-names catalog")

    def check(self, project: Project) -> Iterator[Finding]:
        ev_mod = project.module(EVENTS_MOD)
        documented = (documented_event_names(ev_mod.source)
                      if ev_mod is not None else None)
        literals: Dict[str, List[Tuple]] = defaultdict(list)
        for mod in project.modules:
            if mod.scope_rel == EVENTS_MOD:
                continue
            for cs in mod.calls:
                fn = _is_event_call(cs)
                if fn is None:
                    continue
                kind, value = _event_name_arg(cs.node)
                if kind is None:
                    yield self.finding(
                        mod, cs.line,
                        f"events.{fn}() with a non-literal name — event "
                        "names must be string literals so the catalog, "
                        "list_events filters, and death-cause consumers "
                        "stay greppable (no dynamic funnels)")
                    continue
                if not _NAME_RE.match(value):
                    yield self.finding(
                        mod, cs.line,
                        f"event name {value!r} does not follow the flat "
                        "'lower_snake' convention (lowercase letters, "
                        "digits, underscores)")
                    continue
                literals[value].append((mod, cs.line))
        for name, uses in sorted(literals.items()):
            if len(uses) > 1:
                locs = ", ".join(f"{m.display}:{ln}" for m, ln in uses)
                for m, ln in uses:
                    yield self.finding(
                        m, ln,
                        f"event name '{name}' is emitted from "
                        f"{len(uses)} call sites ({locs}) — each event "
                        "name is owned by exactly one emitting site so "
                        "a head-visible event is attributable to one "
                        "code path; funnel through a single helper or "
                        "add a distinct name")
            if documented is not None and name not in documented:
                m, ln = uses[0]
                yield self.finding(
                    m, ln,
                    f"event name '{name}' is not in util/events.py's "
                    "Event-names catalog — add it there (the docstring "
                    "is what operators and `rtpu events` readers grep)")
        if documented is not None and ev_mod is not None \
                and project.whole_package:
            for entry in sorted(documented - set(literals)):
                yield self.finding(
                    ev_mod, 1,
                    f"documented event name '{entry}' has no emitting "
                    "call site left in the tree — remove it from the "
                    "Event-names catalog or restore the emission")
