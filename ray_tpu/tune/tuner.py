"""Tuner + tune.run: the public entry points.

Role analog: ``python/ray/tune/tuner.py`` and ``tune/tune.py``. A Tuner
expands the param space into trials, builds the controller, runs it, and
returns a ResultGrid.
"""

from __future__ import annotations

import inspect
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Union

from ray_tpu.train.config import RunConfig
from ray_tpu.tune.schedulers import TrialScheduler
from ray_tpu.tune.search import BasicVariantGenerator, Searcher, \
    generate_variants
from ray_tpu.tune.trainable import Trainable, wrap_function
from ray_tpu.tune.tune_controller import ResultGrid, TuneController


@dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: str = "min"
    num_samples: int = 1
    max_concurrent_trials: int = 0
    scheduler: Optional[TrialScheduler] = None
    search_alg: Optional[Searcher] = None
    seed: Optional[int] = None
    resources_per_trial: Dict[str, float] = field(
        default_factory=lambda: {"CPU": 1})
    checkpoint_at_end: bool = False


class Tuner:
    def __init__(
        self,
        trainable: Union[Callable, type],
        *,
        param_space: Optional[Dict[str, Any]] = None,
        tune_config: Optional[TuneConfig] = None,
        run_config: Optional[RunConfig] = None,
    ):
        # Trainer instances (ray_tpu.train.BaseTrainer) wrap to a trainable.
        from ray_tpu.train.trainer import BaseTrainer

        if isinstance(trainable, BaseTrainer):
            trainable = trainable.as_trainable()
        if isinstance(trainable, type) and issubclass(trainable, Trainable):
            self.trainable_cls = trainable
        elif callable(trainable):
            self.trainable_cls = wrap_function(trainable)
        else:
            raise TypeError(f"cannot interpret trainable: {trainable!r}")
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()

    def fit(self) -> ResultGrid:
        tc = self.tune_config
        lazy_source = None
        total = None
        if tc.search_alg is not None:
            # LAZY suggestion: the controller asks for each config when a
            # slot frees, so a model-based searcher (TPE/BOHB) conditions
            # every suggestion on all results reported so far — drawing
            # them upfront would leave the model permanently empty
            configs = []
            lazy_source = tc.search_alg.suggest
            total = tc.num_samples
        else:
            configs = list(generate_variants(
                self.param_space, tc.num_samples, tc.seed))
            if not configs:
                configs = [{}]

        controller = TuneController(
            self.trainable_cls,
            configs,
            run_config=self.run_config,
            scheduler=tc.scheduler,
            stopper=self.run_config.stop,
            max_concurrent=tc.max_concurrent_trials,
            resources_per_trial=tc.resources_per_trial,
            max_failures_per_trial=self.run_config.failure_config.max_failures,
            checkpoint_at_end=tc.checkpoint_at_end,
            config_source=lazy_source,
            total_trials=total,
        )
        # let model-based searchers observe completions (and partial
        # results — BOHB's estimator uses rung evaluations too)
        if tc.search_alg is not None:
            controller.searcher = tc.search_alg
            orig = controller.scheduler.on_trial_complete

            def observe(trial, result, _orig=orig):
                _orig(trial, result)
                if result:
                    tc.search_alg.on_trial_complete(trial.trial_id, result)

            controller.scheduler.on_trial_complete = observe
        trials = controller.run()
        return ResultGrid(trials, controller.exp_dir,
                          default_metric=tc.metric, default_mode=tc.mode)


def run(
    trainable: Union[Callable, type],
    *,
    config: Optional[Dict[str, Any]] = None,
    num_samples: int = 1,
    metric: Optional[str] = None,
    mode: str = "min",
    scheduler: Optional[TrialScheduler] = None,
    storage_path: Optional[str] = None,
    name: Optional[str] = None,
    **kwargs,
) -> ResultGrid:
    """Legacy-style ``tune.run`` facade over Tuner."""
    tuner = Tuner(
        trainable,
        param_space=config,
        tune_config=TuneConfig(metric=metric, mode=mode,
                               num_samples=num_samples, scheduler=scheduler),
        run_config=RunConfig(name=name, storage_path=storage_path),
    )
    return tuner.fit()
