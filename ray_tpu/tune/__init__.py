"""ray_tpu.tune — hyperparameter search over trial actors.

Role analog: ``python/ray/tune`` (SURVEY §2.5). Same shape as the
reference: Trainable (class + function APIs), Tuner/tune.run, trial
schedulers (ASHA/Median/PBT), search spaces and samplers, ResultGrid.
``tune.report`` is the same session primitive as ``train.report`` (the
reference shares it too — function trainables run in a ``_TrainSession``).
"""

from ray_tpu.train.session import get_checkpoint, get_context, report
from ray_tpu.tune.schedulers import (
    ASHAScheduler,
    HyperBandScheduler,
    FIFOScheduler,
    MedianStoppingRule,
    PopulationBasedTraining,
    TrialScheduler,
)
from ray_tpu.tune.search import (
    BasicVariantGenerator,
    Searcher,
    SimpleBayesSearch,
    TPESearch,
    BOHBSearch,
    choice,
    grid_search,
    loguniform,
    quniform,
    randint,
    sample_from,
    uniform,
)
from ray_tpu.tune.trainable import (
    FunctionTrainable,
    Trainable,
    with_parameters,
    wrap_function,
)
from ray_tpu.tune.loggers import Callback, CSVLoggerCallback, \
    JsonLoggerCallback
from ray_tpu.tune.stopper import (
    CombinedStopper,
    MaximumIterationStopper,
    MetricThresholdStopper,
    Stopper,
    TimeoutStopper,
    TrialPlateauStopper,
)
from ray_tpu.tune.tune_controller import ResultGrid, TuneController, Trial
from ray_tpu.tune.tuner import TuneConfig, Tuner, run

__all__ = [
    "report",
    "get_checkpoint",
    "get_context",
    "ASHAScheduler",
    "HyperBandScheduler",
    "FIFOScheduler",
    "MedianStoppingRule",
    "PopulationBasedTraining",
    "TrialScheduler",
    "BasicVariantGenerator",
    "Searcher",
    "SimpleBayesSearch",
    "TPESearch",
    "BOHBSearch",
    "choice",
    "grid_search",
    "loguniform",
    "quniform",
    "randint",
    "sample_from",
    "uniform",
    "Trainable",
    "FunctionTrainable",
    "with_parameters",
    "wrap_function",
    "Callback",
    "CSVLoggerCallback",
    "JsonLoggerCallback",
    "Stopper",
    "CombinedStopper",
    "MaximumIterationStopper",
    "MetricThresholdStopper",
    "TimeoutStopper",
    "TrialPlateauStopper",
    "ResultGrid",
    "TuneController",
    "Trial",
    "TuneConfig",
    "Tuner",
    "run",
]
