"""Search spaces + basic variant generation (grid/random), plus a simple
model-based searcher.

Role analog: ``python/ray/tune/search/`` — the sample domains
(``tune.uniform/loguniform/choice/randint/...``), grid_search markers, and
``BasicVariantGenerator``. The external-library searchers (hyperopt/optuna/
ax) are out of scope (not installable); a small TPE-flavored searcher covers
the "smarter than random" niche natively.
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


@dataclass
class Uniform(Domain):
    low: float
    high: float

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


@dataclass
class LogUniform(Domain):
    low: float
    high: float

    def sample(self, rng):
        return math.exp(rng.uniform(math.log(self.low), math.log(self.high)))


@dataclass
class QUniform(Domain):
    low: float
    high: float
    q: float

    def sample(self, rng):
        return round(rng.uniform(self.low, self.high) / self.q) * self.q


@dataclass
class RandInt(Domain):
    low: int
    high: int

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


@dataclass
class Choice(Domain):
    categories: List[Any]

    def sample(self, rng):
        return rng.choice(self.categories)


@dataclass
class GridSearch:
    values: List[Any]


def uniform(low: float, high: float) -> Uniform:
    return Uniform(low, high)


def loguniform(low: float, high: float) -> LogUniform:
    return LogUniform(low, high)


def quniform(low: float, high: float, q: float) -> QUniform:
    return QUniform(low, high, q)


def randint(low: int, high: int) -> RandInt:
    return RandInt(low, high)


def choice(categories: List[Any]) -> Choice:
    return Choice(list(categories))


def grid_search(values: List[Any]) -> Dict[str, Any]:
    return {"grid_search": list(values)}


def sample_from(fn: Callable[[Dict[str, Any]], Any]):
    return _SampleFrom(fn)


@dataclass
class _SampleFrom:
    fn: Callable


# ---------------------------------------------------------------------------
# Variant generation
# ---------------------------------------------------------------------------

def _split_grid(space: Dict[str, Any], prefix=()) -> List[Tuple[Tuple, List]]:
    grids = []
    for k, v in space.items():
        path = prefix + (k,)
        if isinstance(v, dict) and "grid_search" in v and len(v) == 1:
            grids.append((path, v["grid_search"]))
        elif isinstance(v, GridSearch):
            grids.append((path, v.values))
        elif isinstance(v, dict):
            grids.extend(_split_grid(v, path))
    return grids


def _set_path(d: Dict, path: Tuple, value: Any) -> None:
    for k in path[:-1]:
        d = d.setdefault(k, {})
    d[path[-1]] = value


def _resolve(space: Any, rng: random.Random, resolved: Dict) -> Any:
    if isinstance(space, dict):
        if "grid_search" in space and len(space) == 1:
            raise AssertionError("grid entries must be expanded before resolve")
        return {k: _resolve(v, rng, resolved) for k, v in space.items()}
    if isinstance(space, Domain):
        return space.sample(rng)
    if isinstance(space, _SampleFrom):
        return space.fn(resolved)
    return space


def generate_variants(
    param_space: Dict[str, Any],
    num_samples: int = 1,
    seed: Optional[int] = None,
) -> Iterator[Dict[str, Any]]:
    """Cross-product of grid axes × num_samples random draws of domains
    (reference BasicVariantGenerator semantics)."""
    rng = random.Random(seed)
    grids = _split_grid(param_space)

    def grid_combos(i=0) -> Iterator[List[Tuple[Tuple, Any]]]:
        if i == len(grids):
            yield []
            return
        path, values = grids[i]
        for v in values:
            for rest in grid_combos(i + 1):
                yield [(path, v)] + rest

    for _ in range(num_samples):
        for combo in grid_combos():
            cfg = _resolve(
                {k: v for k, v in param_space.items()
                 if not (isinstance(v, (GridSearch,)) or
                         (isinstance(v, dict) and "grid_search" in v))},
                rng, {})
            for path, v in combo:
                _set_path(cfg, path, v)
            yield cfg


class Searcher:
    """Minimal searcher interface (reference ``tune/search/searcher.py``)."""

    def __init__(self, metric: str = "loss", mode: str = "min"):
        self.metric = metric
        self.mode = mode

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None) -> None:
        pass


class BasicVariantGenerator(Searcher):
    def __init__(self, param_space: Dict[str, Any], num_samples: int = 1,
                 seed: Optional[int] = None, **kw):
        super().__init__(**kw)
        self._it = generate_variants(param_space, num_samples, seed)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        try:
            return next(self._it)
        except StopIteration:
            return None


class SimpleBayesSearch(Searcher):
    """Native "smarter than random" searcher: after ``n_initial`` random
    trials, sample candidates and pick the one nearest (in normalized space)
    to the best-seen configs (a cheap local-search/TPE stand-in)."""

    def __init__(self, param_space: Dict[str, Any], metric: str = "loss",
                 mode: str = "min", n_initial: int = 5,
                 n_candidates: int = 16, seed: Optional[int] = None):
        super().__init__(metric=metric, mode=mode)
        self.space = param_space
        self.rng = random.Random(seed)
        self.n_initial = n_initial
        self.n_candidates = n_candidates
        self.observations: List[Tuple[Dict[str, Any], float]] = []

    def _sample(self) -> Dict[str, Any]:
        return _resolve(self.space, self.rng, {})

    def _numeric_keys(self) -> List[str]:
        return [k for k, v in self.space.items()
                if isinstance(v, (Uniform, LogUniform, QUniform, RandInt))]

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if len(self.observations) < self.n_initial:
            return self._sample()
        sign = 1 if self.mode == "min" else -1
        best = sorted(self.observations, key=lambda o: sign * o[1])
        top = [c for c, _ in best[:max(1, len(best) // 4)]]
        keys = self._numeric_keys()
        if not keys:
            return self._sample()

        def dist(cfg):
            return min(
                sum((_norm(self.space[k], cfg[k]) -
                     _norm(self.space[k], t[k])) ** 2 for k in keys)
                for t in top)

        cands = [self._sample() for _ in range(self.n_candidates)]
        cands.sort(key=dist)
        return cands[0]

    def on_trial_complete(self, trial_id, result=None):
        if result and self.metric in result:
            # config is attached by the controller before calling
            cfg = result.get("config", {})
            self.observations.append((cfg, float(result[self.metric])))


def _norm(domain: Domain, value: float) -> float:
    if isinstance(domain, LogUniform):
        lo, hi = math.log(domain.low), math.log(domain.high)
        return (math.log(max(value, 1e-30)) - lo) / (hi - lo)
    if isinstance(domain, (Uniform, QUniform)):
        return (value - domain.low) / (domain.high - domain.low)
    if isinstance(domain, RandInt):
        return (value - domain.low) / max(domain.high - domain.low, 1)
    return 0.0


class TPESearch(Searcher):
    """Tree-structured Parzen Estimator (the real algorithm, not the
    nearest-neighbor stand-in above): observations split at the ``gamma``
    quantile into good/bad sets; numeric dims get Gaussian KDEs l(x)/g(x)
    in normalized space, categorical dims frequency estimates; candidates
    sample from l and the max expected-improvement ratio l/g wins.

    Role analog: the reference's hyperopt/BOHB searchers
    (``tune/search/bohb/bohb_search.py:49`` uses exactly this estimator);
    implemented natively since external searchers aren't installable.
    """

    def __init__(self, param_space: Dict[str, Any], metric: str = "loss",
                 mode: str = "min", n_initial: int = 8,
                 n_candidates: int = 24, gamma: float = 0.25,
                 seed: Optional[int] = None):
        super().__init__(metric=metric, mode=mode)
        self.space = param_space
        self.rng = random.Random(seed)
        self.n_initial = n_initial
        self.n_candidates = n_candidates
        self.gamma = gamma
        self.observations: List[Tuple[Dict[str, Any], float]] = []

    def _split(self):
        sign = 1 if self.mode == "min" else -1
        ranked = sorted(self.observations, key=lambda o: sign * o[1])
        n_good = max(1, int(len(ranked) * self.gamma))
        return ([c for c, _ in ranked[:n_good]],
                [c for c, _ in ranked[n_good:]] or [ranked[0][0]])

    def _kde_logpdf(self, xs: List[float], x: float) -> float:
        # Gaussian KDE in normalized [0,1] space; Scott-ish bandwidth with
        # a floor so singleton sets still generalize
        bw = max(0.1 * len(xs) ** -0.2, 0.03)
        acc = 0.0
        for mu in xs:
            acc += math.exp(-0.5 * ((x - mu) / bw) ** 2)
        return math.log(max(acc / (len(xs) * bw), 1e-12))

    def _denorm(self, domain: Domain, u: float) -> Any:
        u = min(max(u, 0.0), 1.0)
        if isinstance(domain, LogUniform):
            lo, hi = math.log(domain.low), math.log(domain.high)
            return math.exp(lo + u * (hi - lo))
        if isinstance(domain, QUniform):
            raw = domain.low + u * (domain.high - domain.low)
            return round(raw / domain.q) * domain.q
        if isinstance(domain, Uniform):
            return domain.low + u * (domain.high - domain.low)
        if isinstance(domain, RandInt):
            return min(domain.high - 1,
                       int(domain.low + u * (domain.high - domain.low)))
        raise TypeError(domain)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if len(self.observations) < self.n_initial:
            return _resolve(self.space, self.rng, {})
        good, bad = self._split()
        num_keys = [k for k, v in self.space.items()
                    if isinstance(v, (Uniform, LogUniform, QUniform,
                                      RandInt))]
        cat_keys = [k for k, v in self.space.items()
                    if isinstance(v, Choice)]
        best_cfg, best_score = None, -math.inf
        for _ in range(self.n_candidates):
            cfg = dict(_resolve(self.space, self.rng, {}))
            score = 0.0
            for k in num_keys:
                goods = [_norm(self.space[k], g[k]) for g in good]
                bads = [_norm(self.space[k], b[k]) for b in bad]
                # sample the candidate's value FROM l(x): perturb a good obs
                bw = max(0.1 * len(goods) ** -0.2, 0.03)
                u = self.rng.choice(goods) + self.rng.gauss(0, bw)
                cfg[k] = self._denorm(self.space[k], u)
                u = min(max(u, 0.0), 1.0)
                score += (self._kde_logpdf(goods, u)
                          - self._kde_logpdf(bads, u))
            for k in cat_keys:
                choices = list(self.space[k].categories)
                g_counts = {c: 1.0 for c in choices}
                for g in good:
                    g_counts[g[k]] = g_counts.get(g[k], 1.0) + 1.0
                total = sum(g_counts.values())
                # sample from the good-frequency distribution
                r = self.rng.uniform(0, total)
                acc = 0.0
                for c in choices:
                    acc += g_counts[c]
                    if r <= acc:
                        cfg[k] = c
                        break
                b_counts = {c: 1.0 for c in choices}
                for b in bad:
                    b_counts[b[k]] = b_counts.get(b[k], 1.0) + 1.0
                score += (math.log(g_counts[cfg[k]] / total)
                          - math.log(b_counts[cfg[k]]
                                     / sum(b_counts.values())))
            if score > best_score:
                best_cfg, best_score = cfg, score
        return best_cfg

    def on_trial_complete(self, trial_id, result=None):
        if result and self.metric in result:
            cfg = result.get("config", {})
            self.observations.append((cfg, float(result[self.metric])))


class BOHBSearch(TPESearch):
    """BOHB's searcher half (reference ``bohb_search.py:49``): TPE
    suggestions, designed to pair with :class:`HyperBandScheduler` — the
    scheduler allocates budgets in brackets, this model proposes configs.
    Partial results feed the model (``on_trial_result``), ONE observation
    per trial (its LATEST metric, i.e. the highest budget it reached) so
    a long-lived trial cannot dominate the good/bad split with hundreds
    of duplicate entries."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._obs_index: Dict[str, int] = {}  # trial_id -> observations idx

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]):
        if not (result and self.metric in result and "config" in result):
            return
        entry = (result["config"], float(result[self.metric]))
        idx = self._obs_index.get(trial_id)
        if idx is None:
            self._obs_index[trial_id] = len(self.observations)
            self.observations.append(entry)
        else:
            self.observations[idx] = entry  # latest budget wins

    def on_trial_complete(self, trial_id, result=None):
        # no-op: the trial's final evaluation already arrived (and
        # replaced its slot) via on_trial_result
        pass
