"""Trial schedulers: FIFO, ASHA, HyperBand-style rungs, Median stopping, PBT.

Role analog: ``python/ray/tune/schedulers/`` (ASHA =
``async_hyperband.py``, PBT = ``pbt.py``). The controller calls
``on_trial_result`` after every report and acts on the returned decision.
"""

from __future__ import annotations

import math
import random
from typing import Any, Callable, Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"
PAUSE = "PAUSE"


class TrialScheduler:
    def on_trial_add(self, trial) -> None:
        pass

    def on_trial_result(self, trial, result: Dict[str, Any]) -> str:
        return CONTINUE

    def on_trial_complete(self, trial, result: Optional[Dict[str, Any]]) -> None:
        pass

    def choose_trial_to_run(self, trials) -> Optional[Any]:
        for t in trials:
            if t.status == "PENDING":
                return t
        return None


class FIFOScheduler(TrialScheduler):
    pass


class ASHAScheduler(TrialScheduler):
    """Asynchronous Successive Halving (stopping rule form).

    At each rung (grace_period * reduction_factor**k iterations), a trial
    stops unless its metric is in the top 1/reduction_factor of completed
    rung entries — the asynchronous formulation (no waiting for a full
    bracket), matching the reference's ``AsyncHyperBandScheduler``.
    """

    def __init__(self, metric: str = "loss", mode: str = "min",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: float = 4,
                 time_attr: str = "training_iteration"):
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace = grace_period
        self.rf = reduction_factor
        self.time_attr = time_attr
        # rung level -> list of metric values recorded at that rung
        self.rungs: Dict[int, List[float]] = {}
        # trial id -> highest rung level already credited: rungs trigger on
        # *crossing* a milestone (t >= level), not exact equality — trials
        # reporting every k iterations or with float time attrs would
        # otherwise skip rungs and never be early-stopped (reference
        # AsyncHyperBand cuts on milestone crossing).
        self._credited: Dict[str, int] = {}
        levels = []
        t = grace_period
        while t < max_t:
            levels.append(int(t))
            t *= reduction_factor
        self.levels = levels

    def _better(self, a: float, b: float) -> bool:
        return a < b if self.mode == "min" else a > b

    def on_trial_result(self, trial, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr, 0)
        val = result.get(self.metric)
        if val is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        tid = getattr(trial, "trial_id", str(id(trial)))
        last = self._credited.get(tid, 0)
        # Only the HIGHEST newly-crossed rung gets this result: back-filling
        # lower rungs with late-iteration (better-trained) values would make
        # their cutoffs unfairly harsh on genuinely-young trials.
        for level in reversed(self.levels):
            if t >= level and level > last:
                self._credited[tid] = level
                recorded = self.rungs.setdefault(level, [])
                recorded.append(float(val))
                k = max(1, int(len(recorded) / self.rf))
                top = sorted(recorded, reverse=(self.mode == "max"))[:k]
                worst_top = top[-1]
                if not self._better(float(val), worst_top) and \
                        float(val) != worst_top:
                    return STOP
                break
        return CONTINUE


class MedianStoppingRule(TrialScheduler):
    def __init__(self, metric: str = "loss", mode: str = "min",
                 grace_period: int = 1,
                 time_attr: str = "training_iteration"):
        self.metric = metric
        self.mode = mode
        self.grace = grace_period
        self.time_attr = time_attr
        self.history: Dict[str, List[float]] = {}

    def on_trial_result(self, trial, result: Dict[str, Any]) -> str:
        val = result.get(self.metric)
        t = result.get(self.time_attr, 0)
        if val is None:
            return CONTINUE
        self.history.setdefault(trial.trial_id, []).append(float(val))
        if t < self.grace or len(self.history) < 3:
            return CONTINUE
        bests = []
        for tid, vals in self.history.items():
            if tid != trial.trial_id:
                bests.append(min(vals) if self.mode == "min" else max(vals))
        if not bests:
            return CONTINUE
        bests.sort()
        median = bests[len(bests) // 2]
        mine = (min(self.history[trial.trial_id]) if self.mode == "min"
                else max(self.history[trial.trial_id]))
        if self.mode == "min" and mine > median:
            return STOP
        if self.mode == "max" and mine < median:
            return STOP
        return CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT: exploit (copy weights+config of a top trial) + explore (perturb).

    Reference: ``tune/schedulers/pbt.py``. The controller implements the
    mechanics (checkpoint copy + actor restart); the scheduler decides when
    and what. ``hyperparam_mutations`` maps keys to either a list of choices
    or a (low, high) continuous resample range.
    """

    def __init__(self, metric: str = "loss", mode: str = "min",
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: Optional[int] = None):
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_p = resample_probability
        self.rng = random.Random(seed)
        self.last_perturb: Dict[str, int] = {}
        self.latest: Dict[str, float] = {}

    def on_trial_result(self, trial, result: Dict[str, Any]) -> str:
        val = result.get(self.metric)
        t = result.get("training_iteration", 0)
        if val is None:
            return CONTINUE
        self.latest[trial.trial_id] = float(val)
        last = self.last_perturb.get(trial.trial_id, 0)
        if t - last < self.interval or len(self.latest) < 2:
            return CONTINUE
        self.last_perturb[trial.trial_id] = t
        ranked = sorted(self.latest.items(), key=lambda kv: kv[1],
                        reverse=(self.mode == "max"))
        n = len(ranked)
        k = max(1, int(n * self.quantile))
        bottom_ids = {tid for tid, _ in ranked[n - k:]}
        top_ids = [tid for tid, _ in ranked[:k]]
        if trial.trial_id in bottom_ids and top_ids:
            trial.pbt_exploit_from = self.rng.choice(top_ids)
            return PAUSE  # controller performs exploit+explore, then resumes
        return CONTINUE

    def explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        new = dict(config)
        for key, spec in self.mutations.items():
            if self.rng.random() < self.resample_p or key not in new:
                if isinstance(spec, list):
                    new[key] = self.rng.choice(spec)
                elif isinstance(spec, tuple) and len(spec) == 2:
                    new[key] = self.rng.uniform(*spec)
                elif callable(spec):
                    new[key] = spec()
            else:
                cur = new[key]
                if isinstance(cur, (int, float)):
                    factor = self.rng.choice([0.8, 1.2])
                    new[key] = type(cur)(cur * factor) if isinstance(cur, float) \
                        else max(1, int(cur * factor))
                elif isinstance(spec, list):
                    new[key] = self.rng.choice(spec)
        return new


class HyperBandScheduler(TrialScheduler):
    """HyperBand with BRACKET diversity (reference
    ``tune/schedulers/hyperband.py:42``): incoming trials round-robin over
    s_max+1 brackets; bracket s starts trials at grace
    ``max_t * eta**-s`` and successively halves at rungs
    ``r0 * eta**k``, so aggressive brackets kill early on little evidence
    while conservative ones let slow starters mature — the hedge that
    distinguishes HyperBand from plain successive halving. Decisions are
    asynchronous (stop-on-milestone-crossing, like ASHA) because the
    controller has no pause/resume; the bracket structure is what adds
    value over ASHAScheduler above.
    """

    def __init__(self, metric: str = "loss", mode: str = "min",
                 max_t: int = 81, reduction_factor: float = 3,
                 time_attr: str = "training_iteration"):
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.eta = reduction_factor
        self.time_attr = time_attr
        self.s_max = max(1, int(math.log(max_t) / math.log(reduction_factor)))
        self._next_bracket = 0
        self._bracket_of: Dict[str, int] = {}
        # (bracket, rung level) -> recorded metric values
        self.rungs: Dict[Any, List[float]] = {}
        self._credited: Dict[str, int] = {}

    def _levels(self, s: int) -> List[int]:
        r0 = max(1, int(round(self.max_t * self.eta ** -s)))
        out = []
        t = r0
        while t < self.max_t:
            out.append(int(t))
            t *= self.eta
        return out

    def on_trial_add(self, trial) -> None:
        tid = getattr(trial, "trial_id", str(id(trial)))
        self._bracket_of[tid] = self._next_bracket % (self.s_max + 1)
        self._next_bracket += 1

    def _better(self, a: float, b: float) -> bool:
        return a < b if self.mode == "min" else a > b

    def on_trial_result(self, trial, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr, 0)
        val = result.get(self.metric)
        if val is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        tid = getattr(trial, "trial_id", str(id(trial)))
        s = self._bracket_of.setdefault(tid, 0)
        last = self._credited.get(tid, 0)
        for level in reversed(self._levels(s)):
            if t >= level and level > last:
                self._credited[tid] = level
                recorded = self.rungs.setdefault((s, level), [])
                recorded.append(float(val))
                k = max(1, int(len(recorded) / self.eta))
                top = sorted(recorded, reverse=(self.mode == "max"))[:k]
                worst_top = top[-1]
                if not self._better(float(val), worst_top) and \
                        float(val) != worst_top:
                    return STOP
                break
        return CONTINUE
