"""TuneController: the trial-driving event loop.

Role analog: ``python/ray/tune/execution/tune_controller.py:68`` (``step``
loop :666, actor scheduling :964, save :1691, restore :1791). Each trial is
one actor built from the trainable class; the controller keeps one in-flight
``train_step`` call per running trial and reacts to results with scheduler
decisions (CONTINUE/STOP/PAUSE-for-PBT).
"""

from __future__ import annotations

import json
import os
import time
import traceback
import uuid
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import CheckpointConfig, Result, RunConfig
from ray_tpu.tune.schedulers import CONTINUE, PAUSE, STOP, FIFOScheduler, \
    PopulationBasedTraining, TrialScheduler


class Trial:
    def __init__(self, trial_id: str, config: Dict[str, Any], trial_dir: str):
        self.trial_id = trial_id
        self.config = config
        self.trial_dir = trial_dir
        self.status = "PENDING"
        self.last_result: Dict[str, Any] = {}
        self.history: List[Dict[str, Any]] = []
        self.checkpoint_dir: Optional[str] = None
        self.actor = None
        self.pending_ref = None
        self.error: Optional[BaseException] = None
        self.pbt_exploit_from: Optional[str] = None
        self.iteration = 0

    def metric_history(self, key: str) -> List[float]:
        return [r[key] for r in self.history if key in r]


class TuneController:
    def __init__(
        self,
        trainable_cls: type,
        param_configs: List[Dict[str, Any]],
        *,
        run_config: Optional[RunConfig] = None,
        scheduler: Optional[TrialScheduler] = None,
        stopper: Optional[Callable[[str, Dict[str, Any]], bool]] = None,
        max_concurrent: int = 0,
        resources_per_trial: Optional[Dict[str, float]] = None,
        max_failures_per_trial: int = 0,
        checkpoint_at_end: bool = False,
        config_source: Optional[Callable[[str], Optional[Dict[str, Any]]]] = None,
        total_trials: Optional[int] = None,
    ):
        self.trainable_cls = trainable_cls
        self.run_config = run_config or RunConfig()
        self.scheduler = scheduler or FIFOScheduler()
        self.stopper = stopper
        self.max_concurrent = max_concurrent
        self.resources = resources_per_trial or {"CPU": 1}
        self.max_failures = max_failures_per_trial
        self.checkpoint_at_end = checkpoint_at_end
        self._failures: Dict[str, int] = {}

        name = self.run_config.name or f"tune_{uuid.uuid4().hex[:8]}"
        self.exp_dir = os.path.join(
            self.run_config.resolved_storage_path(), name)
        os.makedirs(self.exp_dir, exist_ok=True)

        # lazy suggestion mode (model-based searchers like BOHB): trials
        # are created one at a time as slots free, so each suggest() sees
        # every result reported so far — upfront generation would make the
        # model inert within a run
        self._config_source = config_source
        self._total_trials = (total_trials if total_trials is not None
                              else len(param_configs))

        self.trials: List[Trial] = []
        for cfg in param_configs:
            self._add_trial(cfg)

    def _add_trial(self, cfg: Dict[str, Any]) -> "Trial":
        tid = f"{len(self.trials):05d}"
        tdir = os.path.join(self.exp_dir, f"trial_{tid}")
        os.makedirs(tdir, exist_ok=True)
        t = Trial(tid, cfg, tdir)
        self.trials.append(t)
        self.scheduler.on_trial_add(t)
        return t

    def _maybe_suggest_trial(self) -> Optional["Trial"]:
        if (self._config_source is None
                or len(self.trials) >= self._total_trials):
            return None
        cfg = self._config_source(f"{len(self.trials):05d}")
        if cfg is None:
            self._total_trials = len(self.trials)  # searcher exhausted
            if not self.trials:
                # never return an empty experiment: one default trial
                # (matches the eager path's `configs = [{}]` fallback)
                return self._add_trial({})
            return None
        return self._add_trial(cfg)

    # -- actor management -------------------------------------------------

    def _make_actor(self, trial: Trial):
        cls = ray_tpu.remote(self.trainable_cls)
        opts = {"num_cpus": self.resources.get("CPU", 1),
                "resources": {k: v for k, v in self.resources.items()
                              if k != "CPU"}}
        return cls.options(**opts).remote(trial.config, trial.trial_dir)

    def _start_trial(self, trial: Trial, restore_from: Optional[str] = None):
        trial.actor = self._make_actor(trial)
        if restore_from:
            ray_tpu.get(trial.actor.restore.remote(restore_from))
        trial.status = "RUNNING"
        trial.pending_ref = trial.actor.train_step.remote()

    def _stop_trial(self, trial: Trial, status: str = "TERMINATED"):
        trial.status = status
        trial.pending_ref = None
        if trial.actor is not None:
            try:
                ray_tpu.kill(trial.actor)
            except Exception:
                pass
            trial.actor = None
        if status in ("TERMINATED", "ERROR"):
            for cb in self.run_config.callbacks:
                try:
                    cb.on_trial_complete(trial)
                except Exception:
                    pass

    # -- main loop --------------------------------------------------------

    def run(self) -> List[Trial]:
        while True:
            # experiment-wide stop (Stopper.stop_all, e.g. TimeoutStopper):
            # terminate running trials and drop pending ones
            if getattr(self.stopper, "stop_all", None) and \
                    self.stopper.stop_all():
                for t in self.trials:
                    if t.status == "RUNNING":
                        self._finalize_and_stop(t)
                    elif t.status == "PENDING":
                        t.status = "TERMINATED"
                break
            self._launch_pending()
            running = [t for t in self.trials if t.status == "RUNNING"
                       and t.pending_ref is not None]
            if not running:
                if any(t.status == "PENDING" for t in self.trials):
                    continue
                break
            refs = [t.pending_ref for t in running]
            ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=60.0)
            if not ready:
                continue
            for ref in ready:
                trial = next(t for t in running if t.pending_ref == ref)
                self._process_result(trial, ref)
        self._write_experiment_state()
        return self.trials

    def _launch_pending(self):
        running = sum(1 for t in self.trials if t.status == "RUNNING")
        limit = self.max_concurrent or max(len(self.trials),
                                           self._total_trials)
        while running < limit:
            t = next((t for t in self.trials if t.status == "PENDING"),
                     None)
            if t is None:
                t = self._maybe_suggest_trial()
                if t is None:
                    break
            try:
                self._start_trial(t, restore_from=t.checkpoint_dir)
                running += 1
            except Exception as e:  # resource exhaustion etc.
                t.error = e
                t.status = "ERROR"

    def _process_result(self, trial: Trial, ref):
        try:
            result = ray_tpu.get([ref])[0]
        except Exception as e:  # noqa: BLE001
            self._failures[trial.trial_id] = \
                self._failures.get(trial.trial_id, 0) + 1
            trial.error = e
            if self._failures[trial.trial_id] <= self.max_failures:
                self._stop_trial(trial, "PENDING")
                trial.status = "PENDING"  # retry from last checkpoint
            else:
                self._stop_trial(trial, "ERROR")
                self.scheduler.on_trial_complete(trial, None)
            return

        trial.pending_ref = None
        if result.get("done"):
            self._complete_trial(trial, trial.last_result)
            return

        result["config"] = trial.config
        trial.last_result = result
        trial.history.append(result)
        trial.iteration = result.get("training_iteration", trial.iteration + 1)
        if "_checkpoint_dir" in result:
            trial.checkpoint_dir = result["_checkpoint_dir"]
        self._append_progress(trial, result)
        for cb in self.run_config.callbacks:
            try:
                cb.on_trial_result(trial, result)
            except Exception:
                pass

        # periodic class-trainable checkpointing
        freq = self.run_config.checkpoint_config.checkpoint_frequency
        if freq and trial.iteration % freq == 0:
            trial.checkpoint_dir = ray_tpu.get([trial.actor.save.remote()])[0]

        if self.stopper and self.stopper(trial.trial_id, result):
            self._finalize_and_stop(trial)
            return

        # model-based searchers (BOHB) also learn from PARTIAL results;
        # Tuner.fit attaches the search_alg here when one is configured
        hook = getattr(getattr(self, "searcher", None),
                       "on_trial_result", None)
        if hook is not None:
            try:
                hook(trial.trial_id, {**result, "config": trial.config})
            except Exception:
                pass

        decision = self.scheduler.on_trial_result(trial, result)
        if decision == STOP:
            self._finalize_and_stop(trial)
        elif decision == PAUSE and trial.pbt_exploit_from:
            self._pbt_exploit(trial)
        else:
            trial.pending_ref = trial.actor.train_step.remote()

    def _finalize_and_stop(self, trial: Trial):
        if self.checkpoint_at_end and trial.actor is not None and \
                not isinstance(trial.checkpoint_dir, str):
            try:
                trial.checkpoint_dir = ray_tpu.get(
                    [trial.actor.save.remote()])[0]
            except Exception:
                pass
        self._stop_trial(trial, "TERMINATED")
        self.scheduler.on_trial_complete(trial, trial.last_result)

    def _complete_trial(self, trial: Trial, result: Dict[str, Any]):
        self._stop_trial(trial, "TERMINATED")
        self.scheduler.on_trial_complete(trial, result)

    def _pbt_exploit(self, trial: Trial):
        donor = next((t for t in self.trials
                      if t.trial_id == trial.pbt_exploit_from), None)
        trial.pbt_exploit_from = None
        if donor is None:
            trial.pending_ref = trial.actor.train_step.remote()
            return
        # snapshot the donor (queued behind its in-flight step)
        donor_ckpt = donor.checkpoint_dir
        if donor.actor is not None:
            try:
                donor_ckpt = ray_tpu.get([donor.actor.save.remote()])[0]
                donor.checkpoint_dir = donor_ckpt
            except Exception:
                pass
        assert isinstance(self.scheduler, PopulationBasedTraining)
        trial.config = self.scheduler.explore(donor.config)
        self._stop_trial(trial, "PAUSED")
        self._start_trial(trial, restore_from=donor_ckpt)

    # -- bookkeeping ------------------------------------------------------

    def _append_progress(self, trial: Trial, result: Dict[str, Any]):
        path = os.path.join(trial.trial_dir, "progress.jsonl")
        rec = {k: v for k, v in result.items() if not k.startswith("_")}
        rec["_timestamp"] = time.time()
        with open(path, "a") as f:
            f.write(json.dumps(rec, default=str) + "\n")

    def _write_experiment_state(self):
        state = {
            "trials": [
                {
                    "trial_id": t.trial_id,
                    "config": t.config,
                    "status": t.status,
                    "last_result": {k: v for k, v in t.last_result.items()
                                    if not k.startswith("_")},
                    "checkpoint_dir": t.checkpoint_dir,
                    "error": (traceback.format_exception_only(
                        type(t.error), t.error)[0].strip()
                        if t.error else None),
                }
                for t in self.trials
            ]
        }
        with open(os.path.join(self.exp_dir, "experiment_state.json"),
                  "w") as f:
            json.dump(state, f, indent=1, default=str)


class ResultGrid:
    def __init__(self, trials: List[Trial], exp_dir: str,
                 default_metric: Optional[str] = None,
                 default_mode: Optional[str] = None):
        self._trials = trials
        self.experiment_path = exp_dir
        self._default_metric = default_metric
        self._default_mode = default_mode

    def __len__(self):
        return len(self._trials)

    def __getitem__(self, i) -> Result:
        t = self._trials[i]
        return Result(
            metrics={k: v for k, v in t.last_result.items()
                     if not k.startswith("_")},
            checkpoint=Checkpoint(t.checkpoint_dir) if t.checkpoint_dir else None,
            path=t.trial_dir,
            error=t.error,
            config=t.config,
        )

    @property
    def errors(self) -> List[BaseException]:
        return [t.error for t in self._trials if t.error]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        """Best trial by ``metric``/``mode``; both default to the values
        set on ``TuneConfig`` (reference ``ResultGrid.get_best_result``)."""
        metric = metric or self._default_metric
        mode = mode or self._default_mode or "min"
        if metric is None:
            raise ValueError(
                "no metric: pass one or set TuneConfig(metric=...)")
        scored = [(i, t.last_result.get(metric)) for i, t in
                  enumerate(self._trials) if metric in t.last_result]
        if not scored:
            raise ValueError(f"no trial reported metric {metric!r}")
        best_i = (min if mode == "min" else max)(scored, key=lambda s: s[1])[0]
        return self[best_i]

    def get_dataframe(self):
        import pandas as pd

        return pd.DataFrame([
            {**{k: v for k, v in t.last_result.items()
                if not k.startswith("_")},
             "trial_id": t.trial_id, "status": t.status,
             **{f"config/{k}": v for k, v in t.config.items()
                if isinstance(v, (int, float, str, bool))}}
            for t in self._trials
        ])
