"""Trainable API: class trainables + function trainables.

Role analog: ``python/ray/tune/trainable/trainable.py`` (class API) and the
function-trainable wrapper (reference wraps function trainables in a
``_TrainSession`` too — SURVEY §2.5 Ray Tune row). A Trainable runs inside a
trial actor; the controller drives it via ``train_step``/``save``/``restore``
actor calls.
"""

from __future__ import annotations

import os
import pickle
import tempfile
from typing import Any, Callable, Dict, Optional

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.session import TrainContext, _Session, _init_session


class Trainable:
    """Class API: subclass and override setup/step/save/load."""

    def __init__(self, config: Optional[Dict[str, Any]] = None,
                 trial_dir: str = "."):
        self.config = dict(config or {})
        self.trial_dir = trial_dir
        self.iteration = 0
        self._setup_done = False

    # -- user overrides ---------------------------------------------------

    def setup(self, config: Dict[str, Any]) -> None:
        pass

    def step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def save_checkpoint(self, checkpoint_dir: str) -> Optional[Dict[str, Any]]:
        return None

    def load_checkpoint(self, checkpoint: Optional[Dict[str, Any]],
                        checkpoint_dir: str) -> None:
        pass

    def cleanup(self) -> None:
        pass

    # -- controller-facing ------------------------------------------------

    def train_step(self) -> Dict[str, Any]:
        if not self._setup_done:
            self.setup(self.config)
            self._setup_done = True
        result = self.step() or {}
        self.iteration += 1
        result.setdefault("training_iteration", self.iteration)
        result.setdefault("done", False)
        return result

    def save(self) -> str:
        if not self._setup_done:
            self.setup(self.config)
            self._setup_done = True
        d = os.path.join(self.trial_dir,
                         f"checkpoint_{self.iteration:06d}")
        os.makedirs(d, exist_ok=True)
        data = self.save_checkpoint(d)
        if data is not None:
            with open(os.path.join(d, "trainable_state.pkl"), "wb") as f:
                pickle.dump(data, f)
        with open(os.path.join(d, ".tune_meta.pkl"), "wb") as f:
            pickle.dump({"iteration": self.iteration}, f)
        return d

    def restore(self, checkpoint_dir: str) -> None:
        if not self._setup_done:
            self.setup(self.config)
            self._setup_done = True
        meta_p = os.path.join(checkpoint_dir, ".tune_meta.pkl")
        if os.path.exists(meta_p):
            with open(meta_p, "rb") as f:
                self.iteration = pickle.load(f)["iteration"]
        data = None
        data_p = os.path.join(checkpoint_dir, "trainable_state.pkl")
        if os.path.exists(data_p):
            with open(data_p, "rb") as f:
                data = pickle.load(f)
        self.load_checkpoint(data, checkpoint_dir)

    def reset_config(self, new_config: Dict[str, Any]) -> bool:
        """Return True if the trainable can hot-swap configs (PBT reuse)."""
        return False

    def stop(self) -> None:
        self.cleanup()


class FunctionTrainable(Trainable):
    """Wraps ``def train_fn(config)`` using the train session machinery: the
    function runs on a thread, ``tune.report`` enqueues results, and each
    ``step()`` drains one."""

    _train_fn: Callable = None  # bound by wrap_function subclass

    def __init__(self, config: Optional[Dict[str, Any]] = None,
                 trial_dir: str = "."):
        super().__init__(config, trial_dir)
        # Session state lives in __init__, NOT setup(): the controller calls
        # restore() before the first train_step() triggers setup(), and a
        # setup()-time reset would wipe the restore dir (PBT exploits and
        # failure retries would silently restart from scratch).
        self._restore_dir: Optional[str] = None
        self._session: Optional[_Session] = None

    def _ensure_session(self):
        if self._session is not None:
            return
        ctx = TrainContext(
            world_rank=0, world_size=1,
            trial_dir=self.trial_dir,
            trial_name=os.path.basename(self.trial_dir),
            loop_config=dict(self.config),
        )
        ckpt = Checkpoint(self._restore_dir) if self._restore_dir else None
        fn = type(self)._train_fn
        import inspect

        try:
            nparams = len(inspect.signature(fn).parameters)
        except (TypeError, ValueError):
            nparams = 1
        runner = (lambda: fn(dict(self.config))) if nparams >= 1 else fn
        self._session = _Session(runner, ctx, ckpt)
        _init_session(self._session)
        self._session.start()

    def step(self) -> Dict[str, Any]:
        self._ensure_session()
        kind, payload, ckpt_path = self._session.next_result(timeout=600.0)
        if kind == "error":
            raise payload
        if kind == "done":
            return {"done": True}
        if kind == "pending":
            raise TimeoutError("function trainable produced no result in 600s")
        result = dict(payload)
        result["done"] = False
        if ckpt_path:
            result["_checkpoint_dir"] = ckpt_path
        return result

    def save(self) -> str:
        # Function trainables checkpoint via tune.report(checkpoint=...);
        # save() returns the latest reported checkpoint dir.
        cands = sorted(d for d in os.listdir(self.trial_dir)
                       if d.startswith("checkpoint_"))
        if not cands:
            d = os.path.join(self.trial_dir, "checkpoint_empty")
            os.makedirs(d, exist_ok=True)
            return d
        return os.path.join(self.trial_dir, cands[-1])

    def restore(self, checkpoint_dir: str) -> None:
        self._restore_dir = checkpoint_dir


def wrap_function(train_fn: Callable) -> type:
    """Create a FunctionTrainable subclass bound to ``train_fn``."""

    class _WrappedTrainable(FunctionTrainable):
        _train_fn = staticmethod(train_fn)

    _WrappedTrainable.__name__ = getattr(train_fn, "__name__", "fn") + "_trainable"
    return _WrappedTrainable


def with_parameters(fn_or_cls, **kwargs):
    """Partially bind large objects into a trainable (reference
    ``tune.with_parameters``)."""
    if isinstance(fn_or_cls, type) and issubclass(fn_or_cls, Trainable):
        class _Bound(fn_or_cls):
            def setup(self, config):
                super().setup({**config, **kwargs})
        _Bound.__name__ = fn_or_cls.__name__
        return _Bound

    import functools

    @functools.wraps(fn_or_cls)
    def wrapped(config):
        return fn_or_cls(config, **kwargs)

    return wrapped
