"""Stoppers: declarative trial/experiment stop conditions.

Role analog: ``python/ray/tune/stopper/``. A stopper is callable as
``(trial_id, result) -> bool``; combine with CombinedStopper.
"""

from __future__ import annotations

import time
from collections import defaultdict, deque
from typing import Any, Dict, Optional


class Stopper:
    def __call__(self, trial_id: str, result: Dict[str, Any]) -> bool:
        raise NotImplementedError

    def stop_all(self) -> bool:
        return False


class MaximumIterationStopper(Stopper):
    def __init__(self, max_iter: int):
        self.max_iter = max_iter

    def __call__(self, trial_id, result):
        return result.get("training_iteration", 0) >= self.max_iter


class MetricThresholdStopper(Stopper):
    def __init__(self, metric: str, threshold: float, mode: str = "min"):
        self.metric = metric
        self.threshold = threshold
        self.mode = mode

    def __call__(self, trial_id, result):
        v = result.get(self.metric)
        if v is None:
            return False
        return v <= self.threshold if self.mode == "min" else \
            v >= self.threshold


class TrialPlateauStopper(Stopper):
    """Stop when the metric stops improving (reference
    ``TrialPlateauStopper``: std of the last N values under a tolerance)."""

    def __init__(self, metric: str, *, num_results: int = 4,
                 std: float = 0.01, grace_period: int = 4):
        self.metric = metric
        self.num_results = num_results
        self.std = std
        self.grace = grace_period
        self._history = defaultdict(lambda: deque(maxlen=num_results))
        self._count = defaultdict(int)

    def __call__(self, trial_id, result):
        v = result.get(self.metric)
        if v is None:
            return False
        self._history[trial_id].append(float(v))
        self._count[trial_id] += 1
        h = self._history[trial_id]
        if self._count[trial_id] < self.grace or len(h) < self.num_results:
            return False
        mean = sum(h) / len(h)
        var = sum((x - mean) ** 2 for x in h) / len(h)
        return var ** 0.5 <= self.std


class TimeoutStopper(Stopper):
    """Experiment wall-clock budget. The clock starts at the first check
    (i.e. when the experiment actually runs), not at construction — a
    RunConfig built ahead of time or reused across fits gets the full
    budget each run... within one controller; reuse re-arms it."""

    def __init__(self, timeout_s: float):
        self.timeout_s = timeout_s
        self._deadline: Optional[float] = None

    def _check(self) -> bool:
        if self._deadline is None:
            self._deadline = time.monotonic() + self.timeout_s
            return False
        return time.monotonic() >= self._deadline

    def __call__(self, trial_id, result):
        return self._check()

    def stop_all(self):
        return self._check()


class CombinedStopper(Stopper):
    def __init__(self, *stoppers: Stopper):
        self.stoppers = stoppers

    def __call__(self, trial_id, result):
        return any(s(trial_id, result) for s in self.stoppers)

    def stop_all(self):
        return any(s.stop_all() for s in self.stoppers)
