"""Result loggers/callbacks: CSV + JSONL per trial.

Role analog: ``python/ray/tune/logger/`` (CSV/JSON writers; W&B/MLflow
integrations are external services and out of scope). The controller calls
``on_trial_result``/``on_trial_complete`` on every registered callback.
"""

from __future__ import annotations

import csv
import json
import os
import time
from typing import Any, Dict, List, Optional


class Callback:
    def on_trial_result(self, trial, result: Dict[str, Any]) -> None:
        pass

    def on_trial_complete(self, trial) -> None:
        pass


class JsonLoggerCallback(Callback):
    """One result.json (JSONL) per trial dir."""

    def on_trial_result(self, trial, result):
        path = os.path.join(trial.trial_dir, "result.json")
        rec = {k: v for k, v in result.items() if not k.startswith("_")}
        rec["timestamp"] = time.time()
        rec["trial_id"] = trial.trial_id
        with open(path, "a") as f:
            f.write(json.dumps(rec, default=str) + "\n")


class CSVLoggerCallback(Callback):
    """progress.csv per trial; header unioned from the first result."""

    def __init__(self):
        self._writers: Dict[str, csv.DictWriter] = {}
        self._files: Dict[str, Any] = {}

    def on_trial_result(self, trial, result):
        rec = {k: v for k, v in result.items()
               if not k.startswith("_") and
               isinstance(v, (int, float, str, bool))}
        tid = trial.trial_id
        if tid not in self._writers:
            path = os.path.join(trial.trial_dir, "progress.csv")
            f = open(path, "a", newline="")
            fields = sorted(rec)
            w = csv.DictWriter(f, fieldnames=fields, extrasaction="ignore")
            if f.tell() == 0:
                w.writeheader()
            self._writers[tid] = w
            self._files[tid] = f
        self._writers[tid].writerow(rec)
        self._files[tid].flush()

    def on_trial_complete(self, trial):
        f = self._files.pop(trial.trial_id, None)
        if f:
            f.close()
        self._writers.pop(trial.trial_id, None)
