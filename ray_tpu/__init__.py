"""ray_tpu — a TPU-native distributed AI runtime.

A brand-new framework with the capabilities of Ray (reference:
``/root/reference``, see ``python/ray/__init__.py``) designed idiomatically
for TPUs: JAX/XLA is the compute substrate, collectives lower to ``jax.lax``
ops over ICI/DCN meshes instead of NCCL, and the ML libraries (data, train,
tune, serve, rl) are built over the same task/actor/object primitives that
make Ray's libraries portable (reference SURVEY: every ML library is pure
Python over L3).
"""

from ray_tpu._version import __version__
from ray_tpu.core.runtime import (
    init,
    shutdown,
    is_initialized,
    remote,
    get,
    put,
    wait,
    kill,
    cancel,
    get_actor,
    available_resources,
    cluster_resources,
    nodes,
    method,
    timeline,
)
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.actor import ActorClass, ActorHandle
from ray_tpu.core.runtime_context import get_runtime_context

__all__ = [
    "__version__",
    "init",
    "shutdown",
    "is_initialized",
    "remote",
    "get",
    "put",
    "wait",
    "kill",
    "cancel",
    "get_actor",
    "get_runtime_context",
    "available_resources",
    "cluster_resources",
    "nodes",
    "method",
    "timeline",
    "ObjectRef",
    "ActorClass",
    "ActorHandle",
]
