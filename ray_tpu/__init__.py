"""ray_tpu — a TPU-native distributed AI runtime.

A brand-new framework with the capabilities of Ray (reference:
``/root/reference``, see ``python/ray/__init__.py``) designed idiomatically
for TPUs: JAX/XLA is the compute substrate, collectives lower to ``jax.lax``
ops over ICI/DCN meshes instead of NCCL, and the ML libraries (data, train,
tune, serve, rl) are built over the same task/actor/object primitives that
make Ray's libraries portable (reference SURVEY: every ML library is pure
Python over L3).
"""

import importlib

from ray_tpu._version import __version__

_SUBPACKAGES = ("core", "parallel", "collective", "ops", "models", "train",
                "tune", "data", "serve", "rllib", "util", "accelerators")


def __getattr__(name: str):
    """Lazy subpackage access: ``import ray_tpu; ray_tpu.data.range(...)``
    (mirrors ``ray.data`` etc. being importable off the top-level package)."""
    if name in _SUBPACKAGES:
        return importlib.import_module(f"ray_tpu.{name}")
    raise AttributeError(f"module 'ray_tpu' has no attribute {name!r}")
from ray_tpu.core.runtime import (
    init,
    shutdown,
    is_initialized,
    remote,
    get,
    put,
    wait,
    kill,
    cancel,
    free,
    get_actor,
    available_resources,
    object_store_memory,
    cluster_resources,
    nodes,
    method,
    timeline,
)
from ray_tpu.core.object_ref import ObjectRef, ObjectRefGenerator
from ray_tpu.core.actor import ActorClass, ActorHandle
from ray_tpu.core.runtime_context import get_runtime_context

__all__ = [
    "__version__",
    "init",
    "shutdown",
    "is_initialized",
    "remote",
    "get",
    "put",
    "wait",
    "kill",
    "cancel",
    "free",
    "get_actor",
    "get_runtime_context",
    "available_resources",
    "object_store_memory",
    "cluster_resources",
    "nodes",
    "method",
    "timeline",
    "ObjectRef",
    "ObjectRefGenerator",
    "ActorClass",
    "ActorHandle",
]
