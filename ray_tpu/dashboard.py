"""Dashboard-lite: in-driver HTTP endpoints for state + metrics.

Role analog: the reference dashboard head (``dashboard/head.py``) reduced
to its API surface: JSON state endpoints (nodes/actors/tasks/objects/
workers/placement groups/summaries) and a Prometheus ``/metrics``
exposition, served from the driver process on a background thread.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, *a):  # silent
        pass

    def do_GET(self):  # noqa: N802 — stdlib API
        from ray_tpu.util import state as st

        from ray_tpu.serve import config_api as serve_rest

        routes = {
            "/api/nodes": st.list_nodes,
            "/api/actors": st.list_actors,
            "/api/tasks": st.list_tasks,
            "/api/objects": st.list_objects,
            "/api/workers": st.list_workers,
            "/api/placement_groups": st.list_placement_groups,
            "/api/summary/tasks": st.summarize_tasks,
            "/api/summary/actors": st.summarize_actors,
            "/api/summary/objects": st.summarize_objects,
            # task-lifecycle flight recorder (recent per-phase records)
            "/api/task_events": st.list_task_events,
            # serve REST (reference dashboard/modules/serve role)
            "/api/serve/applications": serve_rest.serve_rest_get,
            # Chrome-trace task spans (reference timeline view role)
            "/api/timeline": _timeline_events,
        }
        try:
            if self.path == "/metrics":
                body = _metrics_text().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if self.path == "/":
                from ray_tpu.dashboard_ui import INDEX_HTML

                body = INDEX_HTML.encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/html; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if self.path == "/api":
                payload = {"endpoints": sorted(routes) + ["/metrics"]}
            elif self.path in routes:
                payload = routes[self.path]()
            else:
                self.send_response(404)
                self.end_headers()
                return
            body = json.dumps({"result": payload}, default=str).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        except Exception as e:  # noqa: BLE001
            body = json.dumps({"error": str(e)}).encode()
            self.send_response(500)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)


    def _json_reply(self, code: int, payload: dict) -> None:
        body = json.dumps(payload, default=str).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_PUT(self):  # noqa: N802 — declarative serve deploy (REST)
        if self.path != "/api/serve/applications":
            self.send_response(404)
            self.end_headers()
            return
        try:
            from ray_tpu.serve import config_api as serve_rest

            n = int(self.headers.get("Content-Length", 0) or 0)
            cfg = json.loads(self.rfile.read(n) or b"{}")
            self._json_reply(200, {"result": serve_rest.serve_rest_put(cfg)})
        except Exception as e:  # noqa: BLE001
            self._json_reply(500, {"error": str(e)})

    def do_DELETE(self):  # noqa: N802 — serve shutdown (REST)
        if self.path != "/api/serve/applications":
            self.send_response(404)
            self.end_headers()
            return
        try:
            from ray_tpu.serve import config_api as serve_rest

            self._json_reply(200,
                             {"result": serve_rest.serve_rest_delete()})
        except Exception as e:  # noqa: BLE001
            self._json_reply(500, {"error": str(e)})


def _timeline_events():
    """Driver timeline (Chrome-trace X events) for the UI's swimlanes."""
    import ray_tpu

    return ray_tpu.timeline()


def _metrics_text() -> str:
    """Federated Prometheus exposition: this process's registry (unlabeled,
    pre-federation format), its workers' pushed samples, and — on a
    cluster head — every peer node's samples pulled from the GCS, all as
    one scrape target with node_id/worker_id/component labels."""
    from ray_tpu.util.metrics import federation, prometheus_text

    extra = federation.export()
    try:
        from ray_tpu.core.runtime import _runtime

        rt = _runtime
        if rt is not None and getattr(rt, "cluster", None) is not None:
            remote = rt.cluster.gcs.call(
                "metrics_get", rt.node_id.binary(), timeout=5)
            if remote:
                extra.extend(remote)
    except Exception:
        pass  # scrape must degrade to local samples, never 500
    return prometheus_text(extra=extra)


class Dashboard:
    def __init__(self, host: str = "127.0.0.1", port: int = 8265):
        self.host = host
        self.port = port
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "Dashboard":
        self._server = ThreadingHTTPServer((self.host, self.port), _Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="rtpu_dashboard")
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server = None


_dashboard: Optional[Dashboard] = None


def start_dashboard(host: str = "127.0.0.1", port: int = 8265) -> Dashboard:
    global _dashboard
    if _dashboard is None:
        _dashboard = Dashboard(host, port).start()
    return _dashboard


def stop_dashboard() -> None:
    global _dashboard
    if _dashboard is not None:
        _dashboard.stop()
        _dashboard = None
