"""Dashboard-lite: in-driver HTTP endpoints for state + metrics + jobs.

Role analog: the reference dashboard head (``dashboard/head.py``) reduced
to its API surface: JSON state endpoints (nodes/actors/tasks/objects/
workers/placement groups/summaries), a Prometheus ``/metrics``
exposition, and the job-submission REST surface (reference
``dashboard/modules/job/job_head.py``: submit/stop/status/logs over
HTTP), served from the driver process on a background thread. The server
is a ``ThreadingHTTPServer`` on purpose: one slow log poll or job submit
must never block a concurrent ``/metrics`` scrape.
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

_JOB_ID_RE = re.compile(r"^/api/jobs/([\w.-]+)(/logs|/stop)?$")

_job_client = None
_job_client_lock = threading.Lock()


def _jobs():
    """Lazy singleton JobSubmissionClient — created on first REST use so
    starting a dashboard never spawns job machinery by itself."""
    global _job_client
    with _job_client_lock:
        if _job_client is None:
            from ray_tpu.job_submission import JobSubmissionClient

            _job_client = JobSubmissionClient()
        return _job_client


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, *a):  # silent
        pass

    def do_GET(self):  # noqa: N802 — stdlib API
        from urllib.parse import parse_qs

        from ray_tpu.util import state as st

        from ray_tpu.serve import config_api as serve_rest

        path, _, query = self.path.partition("?")
        params = parse_qs(query)

        def _p(name, default=None):
            vals = params.get(name)
            return vals[0] if vals else default

        routes = {
            # trace plane (reference tracing/timeline pipeline role)
            "/api/traces": lambda: st.list_spans(
                limit=int(_p("limit", 10000))),
            "/api/critical_path": lambda: st.summarize_critical_path(
                trace_id=_p("trace_id")),
            # unified Perfetto/Chrome-trace export (spans + task phases
            # + lock waits + train steps): save the JSON body and load it
            # in ui.perfetto.dev
            "/api/perfetto": st.export_perfetto,
            "/api/nodes": st.list_nodes,
            "/api/actors": st.list_actors,
            "/api/tasks": st.list_tasks,
            "/api/objects": st.list_objects,
            "/api/workers": st.list_workers,
            "/api/placement_groups": st.list_placement_groups,
            "/api/summary/tasks": st.summarize_tasks,
            "/api/summary/actors": st.summarize_actors,
            "/api/summary/objects": st.summarize_objects,
            # profiling plane (cluster-wide sampling profiler; ?seconds=
            # arms a temporary window, ?fmt=speedscope|collapsed picks
            # the export; default = merged summary with top_self)
            "/api/profile": lambda: _profile_route(st, _p),
            # live cluster-wide python stacks (`ray_tpu stack` py-spy
            # role; needs no arming)
            "/api/stack": lambda: st.stack(
                timeout=float(_p("timeout", 3.0))),
            # object-memory forensics (`ray_tpu memory` analog)
            "/api/memory": lambda: st.memory_summary(
                limit=int(_p("limit", 10000)),
                min_size=int(_p("min_size", 0))),
            # arena occupancy/fragmentation report (native store)
            "/api/store": st.store_report,
            # task-lifecycle flight recorder (recent per-phase records)
            "/api/task_events": st.list_task_events,
            # lock-contention profiler (this process's hot locks)
            "/api/contention": st.summarize_contention,
            # event plane: lifecycle events w/ death postmortems
            "/api/events": lambda: st.list_events(
                limit=int(_p("limit", 1000)),
                filters=([("name", "=", _p("name"))]
                         if _p("name") else None)),
            # log federation: ?worker_id= / ?task_id= / ?actor_id= /
            # ?node_id= resolves to bounded log tails cluster-wide
            "/api/logs": lambda: st.fetch_logs(
                {k: _p(k) for k in ("worker_id", "task_id", "actor_id",
                                    "node_id") if _p(k)},
                timeout=float(_p("timeout", 5.0))),
            # alerting watchdog: currently-raised alerts
            "/api/alerts": st.list_alerts,
            # device plane: compiled-program registry + HBM census,
            # merged cluster-wide
            "/api/devices": st.device_report,
            # job submission REST (list; per-job routes handled below)
            "/api/jobs": _jobs_list,
            # serve REST (reference dashboard/modules/serve role)
            "/api/serve/applications": serve_rest.serve_rest_get,
            # multi-model residency (per-replica models + prefix digests)
            "/api/models": serve_rest.serve_models_get,
            # Chrome-trace task spans (reference timeline view role)
            "/api/timeline": _timeline_events,
        }
        try:
            if path == "/metrics":
                body = _metrics_text().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if path == "/":
                from ray_tpu.dashboard_ui import INDEX_HTML

                body = INDEX_HTML.encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/html; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if path == "/api":
                payload = {"endpoints": sorted(routes) + ["/metrics"]}
            elif path in routes:
                payload = routes[path]()
            elif (m := _JOB_ID_RE.match(path)) and \
                    m.group(2) in (None, "/logs"):
                job_id = m.group(1)
                try:
                    if m.group(2) == "/logs":
                        payload = {"job_id": job_id,
                                   "logs": _jobs().get_job_logs(job_id)}
                    else:
                        payload = vars(_jobs().get_job_info(job_id))
                except ValueError as e:
                    self._json_reply(404, {"error": str(e)})
                    return
            else:
                self.send_response(404)
                self.end_headers()
                return
            body = json.dumps({"result": payload}, default=str).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        except Exception as e:  # noqa: BLE001
            body = json.dumps({"error": str(e)}).encode()
            self.send_response(500)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)


    def _json_reply(self, code: int, payload: dict) -> None:
        body = json.dumps(payload, default=str).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):  # noqa: N802 — job submission REST
        try:
            m = _JOB_ID_RE.match(self.path)
            if self.path == "/api/jobs":
                n = int(self.headers.get("Content-Length", 0) or 0)
                body = json.loads(self.rfile.read(n) or b"{}")
                entrypoint = body.get("entrypoint")
                if not entrypoint:
                    self._json_reply(400,
                                     {"error": "entrypoint is required"})
                    return
                job_id = _jobs().submit_job(
                    entrypoint=entrypoint,
                    runtime_env=body.get("runtime_env"),
                    metadata=body.get("metadata"),
                    submission_id=body.get("submission_id"))
                self._json_reply(200, {"result": {"job_id": job_id}})
            elif m and m.group(2) == "/stop":
                stopped = _jobs().stop_job(m.group(1))
                code = 200 if stopped else 404
                self._json_reply(code, {"result": {"stopped": stopped}})
            else:
                self.send_response(404)
                self.end_headers()
        except Exception as e:  # noqa: BLE001
            self._json_reply(500, {"error": str(e)})

    def do_PUT(self):  # noqa: N802 — declarative serve deploy (REST)
        if self.path != "/api/serve/applications":
            self.send_response(404)
            self.end_headers()
            return
        try:
            from ray_tpu.serve import config_api as serve_rest

            n = int(self.headers.get("Content-Length", 0) or 0)
            cfg = json.loads(self.rfile.read(n) or b"{}")
            self._json_reply(200, {"result": serve_rest.serve_rest_put(cfg)})
        except Exception as e:  # noqa: BLE001
            self._json_reply(500, {"error": str(e)})

    def do_DELETE(self):  # noqa: N802 — serve shutdown (REST)
        if self.path != "/api/serve/applications":
            self.send_response(404)
            self.end_headers()
            return
        try:
            from ray_tpu.serve import config_api as serve_rest

            self._json_reply(200,
                             {"result": serve_rest.serve_rest_delete()})
        except Exception as e:  # noqa: BLE001
            self._json_reply(500, {"error": str(e)})


def _profile_route(st, _p):
    """GET /api/profile: seconds (temporary arming window), component
    filter, fmt=summary|collapsed|speedscope."""
    seconds = _p("seconds")
    seconds = float(seconds) if seconds is not None else None
    fmt = _p("fmt", "summary")
    if fmt == "speedscope":
        return st.export_speedscope(seconds=seconds)
    if fmt == "collapsed":
        return st.profile_collapsed(seconds=seconds)
    return st.profile(seconds=seconds, component=_p("component"))


def _jobs_list():
    """All known jobs (reference GET /api/jobs/)."""
    return [vars(info) for info in _jobs().list_jobs()]


def _timeline_events():
    """Driver timeline (Chrome-trace X events) for the UI's swimlanes."""
    import ray_tpu

    return ray_tpu.timeline()


def _metrics_text() -> str:
    """Federated Prometheus exposition: this process's registry (unlabeled,
    pre-federation format), its workers' pushed samples, and — on a
    cluster head — every peer node's samples pulled from the GCS, all as
    one scrape target with node_id/worker_id/component labels."""
    from ray_tpu.util.metrics import federation, prometheus_text

    extra = federation.export()
    try:
        from ray_tpu.core.runtime import _runtime

        rt = _runtime
        if rt is not None and getattr(rt, "cluster", None) is not None:
            remote = rt.cluster.gcs.call(
                "metrics_get", rt.node_id.binary(), timeout=5)
            if remote:
                extra.extend(remote)
    except Exception:
        pass  # scrape must degrade to local samples, never 500
    return prometheus_text(extra=extra)


class Dashboard:
    def __init__(self, host: str = "127.0.0.1", port: int = 8265):
        self.host = host
        self.port = port
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "Dashboard":
        self._server = ThreadingHTTPServer((self.host, self.port), _Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="rtpu_dashboard")
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server = None


_dashboard: Optional[Dashboard] = None


def start_dashboard(host: str = "127.0.0.1", port: int = 8265) -> Dashboard:
    global _dashboard
    if _dashboard is None:
        _dashboard = Dashboard(host, port).start()
    return _dashboard


def stop_dashboard() -> None:
    global _dashboard, _job_client
    if _dashboard is not None:
        _dashboard.stop()
        _dashboard = None
    with _job_client_lock:
        # drop the job client: its actor handles die with the runtime
        _job_client = None
