"""Dashboard-lite: in-driver HTTP endpoints for state + metrics.

Role analog: the reference dashboard head (``dashboard/head.py``) reduced
to its API surface: JSON state endpoints (nodes/actors/tasks/objects/
workers/placement groups/summaries) and a Prometheus ``/metrics``
exposition, served from the driver process on a background thread.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, *a):  # silent
        pass

    def do_GET(self):  # noqa: N802 — stdlib API
        from ray_tpu.util import state as st
        from ray_tpu.util.metrics import prometheus_text

        routes = {
            "/api/nodes": st.list_nodes,
            "/api/actors": st.list_actors,
            "/api/tasks": st.list_tasks,
            "/api/objects": st.list_objects,
            "/api/workers": st.list_workers,
            "/api/placement_groups": st.list_placement_groups,
            "/api/summary/tasks": st.summarize_tasks,
            "/api/summary/actors": st.summarize_actors,
            "/api/summary/objects": st.summarize_objects,
        }
        try:
            if self.path == "/metrics":
                body = prometheus_text().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if self.path in ("/", "/api"):
                payload = {"endpoints": sorted(routes) + ["/metrics"]}
            elif self.path in routes:
                payload = routes[self.path]()
            else:
                self.send_response(404)
                self.end_headers()
                return
            body = json.dumps({"result": payload}, default=str).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        except Exception as e:  # noqa: BLE001
            body = json.dumps({"error": str(e)}).encode()
            self.send_response(500)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)


class Dashboard:
    def __init__(self, host: str = "127.0.0.1", port: int = 8265):
        self.host = host
        self.port = port
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "Dashboard":
        self._server = ThreadingHTTPServer((self.host, self.port), _Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="rtpu_dashboard")
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server = None


_dashboard: Optional[Dashboard] = None


def start_dashboard(host: str = "127.0.0.1", port: int = 8265) -> Dashboard:
    global _dashboard
    if _dashboard is None:
        _dashboard = Dashboard(host, port).start()
    return _dashboard


def stop_dashboard() -> None:
    global _dashboard
    if _dashboard is not None:
        _dashboard.stop()
        _dashboard = None
