"""Lifecycle events: the cluster's "what happened and why" plane.

Role analog: the reference event subsystem (``src/ray/util/event.cc`` +
the dashboard's event head) and the exit-reason forensics the reference
state API attaches to dead workers/actors. Four planes (metrics, flight
recorder, tracing, profiling) answer "what is slow"; this fifth plane
answers "what happened": every interesting lifecycle transition is a
structured event, and every DEATH event carries a postmortem (exit
code/signal, stderr tail, last USR1 stack dump when one landed in the
log) captured at the reaping site — the same forensics that are folded
into the ``WorkerCrashedError``/``ActorDiedError`` users see.

Recording plane (the event twin of the tracing ring): every process
records events into a bounded in-memory RING (``RTPU_EVENT_RING``
entries; overflow increments ``rtpu_lifecycle_events_dropped_total``).
Collection rides the EXISTING channels — workers push over the control
pipe (like span batches), node daemons' events (their own + their
workers') ride the GCS heartbeat with the TraceStore acked-cursor/dedup
contract, and the GCS itself appends its node-lifecycle events (register
/ heartbeat-timeout death) directly to the head store — landing in the
head-side :class:`ray_tpu.util.event_store.EventStore` served at
``/api/events``, ``state.list_events()`` and ``rtpu events``.

Events are ON by default (they are rare and cheap — lifecycle
transitions, not per-task records); ``RTPU_EVENTS=0`` is the kill
switch, and :func:`disable_events`/:func:`enable_events` flip the plane
cluster-wide at runtime over the failpoints-style KV + pubsub push. The
disabled cost of :func:`emit`/:func:`events_enabled` is one dict get —
no lock, no clock.

Event names (flat ``lower_snake`` vocabulary; the graftlint
``event-name-catalog`` rule keeps this catalog and the ``emit()`` call
sites bidirectionally in sync)::

    worker_spawn          a worker process was launched (zygote or exec)
    worker_death          a worker process died; postmortem attached
    actor_restart         a dead actor is being restarted (restart #)
    actor_death           an actor died permanently (no restarts left)
    node_register         a node registered with the GCS
    node_death            the GCS declared a node dead; postmortem
    gcs_restart           a daemon re-registered after GCS state loss
    object_spill          an object landed on disk instead of shm
    object_restore        a spilled object was promoted back into shm
    serve_replica_death   a serve replica died and was dropped
    serve_reroute         serve handles were told to refresh routing
    serve_drain           a serve replica is draining: live sessions
                          migrate to surviving replicas before the stop
    serve_session_migrated  a live decode session's KV blocks shipped to
                          a surviving replica (no re-prefill)
    checkpoint_resume     training resumed from a persisted checkpoint
    train_world_epoch     elastic membership change: the train gang
                          re-formed at a new world size (shrink on
                          preemption / expand on restored capacity)
    alert_raised          the watchdog raised an alert (util/alerts.py)
    alert_cleared         a raised alert condition went away
    jit_recompile         a registered program recompiled past its first
                          trace; payload carries the signature diff
                          (util/device_plane.py)
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

#: cluster-wide arming rides the GCS KV + pubsub (failpoints pattern)
KV_NAMESPACE = "__events__"
KV_KEY = "spec"
CHANNEL = "events"

#: severity attached to death/alert events (everything else is "info")
_SEVERITY = {
    "worker_death": "error",
    "actor_death": "error",
    "node_death": "error",
    "serve_replica_death": "error",
    "actor_restart": "warning",
    "gcs_restart": "warning",
    "serve_drain": "warning",
    "train_world_epoch": "warning",
    "alert_raised": "warning",
    "alert_cleared": "info",
    "jit_recompile": "warning",
}

_lock = threading.Lock()
# _state["enabled"] doubles as the hot-path cache: None = unresolved,
# read WITHOUT the lock on every emit()/events_enabled() call (a dict
# get under the GIL; tests reset it to None to force re-resolution).
_state: Dict[str, Any] = {"enabled": None}

# bounded event ring (the recording side of the plane)
_ring: "deque[Dict[str, Any]]" = deque()
_ring_cap: Optional[int] = None
_dropped = 0
_dropped_counted = 0  # drops already settled into the builtin counter

# lazily-bound builtin counters; never allowed to fail an emit
_m = {"events": None, "dropped": None, "pushes": None}


def _metric(which: str):
    from ray_tpu.util import metric_defs, metrics

    names = {"events": "rtpu_lifecycle_events_total",
             "dropped": "rtpu_lifecycle_events_dropped_total",
             "pushes": "rtpu_event_push_batches_total"}
    inst = _m[which]
    if inst is None or metrics.registered(names[which]) is not inst:
        inst = _m[which] = metric_defs.get(names[which])
    return inst


def _resolve() -> bool:
    with _lock:
        if _state["enabled"] is None:
            # default ON: RTPU_EVENTS=0 is the kill switch
            _state["enabled"] = os.environ.get("RTPU_EVENTS", "1") != "0"
        return _state["enabled"]


def events_enabled() -> bool:
    e = _state["enabled"]
    if e is None:
        return _resolve()
    return e


def _ring_capacity() -> int:
    global _ring_cap
    if _ring_cap is None:
        try:
            from ray_tpu import config

            _ring_cap = max(16, int(config.get("event_ring")))
        except Exception:
            _ring_cap = 2048
    return _ring_cap


def _retire_zygote() -> None:
    """The zygote fork-server's env snapshot predates an arming flip, so
    retire it — the next spawn relaunches it with the current events env
    (same contract as tracing/profiling arming flips)."""
    try:
        from ray_tpu.core import runtime as _rt_mod

        rt = _rt_mod._runtime
        if rt is not None and getattr(rt, "is_driver", False):
            with rt._zygote_lock:
                if rt._zygote_obj is not None:
                    rt._zygote_obj.close()
                    rt._zygote_obj = None
    except Exception:
        pass


def push_spec() -> Dict[str, Any]:
    """The arming payload shipped to workers/daemons (pipe + pubsub/KV)."""
    return {"enabled": bool(events_enabled())}


def apply_remote(payload: Dict[str, Any]) -> None:
    """Apply a driver-pushed arming payload in THIS process (worker pipe
    message / daemon pubsub / KV late-join sync)."""
    enabled = bool(payload.get("enabled"))
    os.environ["RTPU_EVENTS"] = "1" if enabled else "0"
    with _lock:
        _state["enabled"] = enabled


def broadcast_local(rt, payload: Optional[Dict[str, Any]]) -> None:
    """Push an arming payload to every live worker of ``rt`` and remember
    it so workers spawned later receive it on dial-back (mirrors
    tracing.broadcast_local)."""
    if not getattr(rt, "is_driver", False):
        return
    rt._event_push = payload
    for ws in list(getattr(rt, "workers", {}).values()):
        if ws.status == "dead" or ws.conn is None:
            continue
        try:
            ws.send(("events", payload))
        except Exception:
            pass


def _broadcast(payload: Dict[str, Any]) -> None:
    """Local workers + cluster-wide distribution of an arming flip."""
    _retire_zygote()
    try:
        from ray_tpu.core import runtime as _rt_mod

        rt = _rt_mod._runtime
    except Exception:
        rt = None
    if rt is None or not getattr(rt, "is_driver", False):
        return
    broadcast_local(rt, payload)
    cluster = getattr(rt, "cluster", None)
    if cluster is not None:
        try:
            cluster.kv_op("put", KV_KEY, json.dumps(payload).encode(),
                          KV_NAMESPACE, True)
            cluster.gcs.call("publish", CHANNEL, payload, timeout=10)
        except Exception:
            pass


def enable_events() -> None:
    """Turn on event recording in THIS process, its live workers (control
    pipe push), workers spawned after this call (env), and — in cluster
    mode — every daemon and ITS workers (GCS KV + ``events`` pubsub)."""
    os.environ["RTPU_EVENTS"] = "1"
    with _lock:
        _state["enabled"] = True
    _broadcast(push_spec())


def disable_events() -> None:
    """The runtime counterpart of ``RTPU_EVENTS=0``: stop recording in
    this process and everywhere :func:`enable_events` reaches."""
    os.environ["RTPU_EVENTS"] = "0"
    with _lock:
        _state["enabled"] = False
    _broadcast(push_spec())


def sync_from_kv(kv_get) -> None:
    """Pull + apply the cluster-wide arming payload (late joiners /
    re-registration). ``kv_get(key, namespace) -> Optional[bytes]``."""
    try:
        blob = kv_get(KV_KEY, KV_NAMESPACE)
    except Exception:
        return
    if blob:
        try:
            apply_remote(json.loads(blob.decode()))
        except Exception:
            pass


def record(name: str, severity: Optional[str] = None,
           **fields: Any) -> Optional[Dict[str, Any]]:
    """Build one stamped event record WITHOUT the ring hop — for the
    process that already holds the destination store (the GCS appends
    its node-lifecycle events straight to the head deque). ``name`` is
    cataloged exactly like :func:`emit` call sites. None when the plane
    is killed."""
    if not events_enabled():
        return None
    rec: Dict[str, Any] = {
        "name": name,
        "ts": time.time(),
        "severity": severity or _SEVERITY.get(name, "info"),
    }
    rec.update(fields)
    return rec


def emit(name: str, severity: Optional[str] = None,
         **fields: Any) -> None:
    """Record one lifecycle event into this process's ring.

    ``name`` must be a literal from the Event-names catalog in this
    module's docstring (graftlint ``event-name-catalog``); ``fields``
    are the event's structured payload (ids as short hex strings,
    postmortems under a ``"postmortem"`` key). Disabled cost is one
    dict get."""
    rec = record(name, severity, **fields)
    if rec is None:
        return
    global _dropped
    with _lock:
        if len(_ring) >= _ring_capacity():
            _ring.popleft()
            _dropped += 1
        _ring.append(rec)


def drain_ring(max_n: Optional[int] = None) -> List[Dict[str, Any]]:
    """Pop up to ``max_n`` (default: all) events from this process's ring
    — the collection hop (worker pipe push / daemon heartbeat / head
    query). Events leave the ring exactly once. The recorded/dropped
    counters are settled here, in one batch per drain."""
    global _dropped_counted
    out: List[Dict[str, Any]] = []
    with _lock:
        n = len(_ring) if max_n is None else min(max_n, len(_ring))
        for _ in range(n):
            out.append(_ring.popleft())
        dropped_new = _dropped - _dropped_counted
        _dropped_counted = _dropped
    try:
        if out:
            _metric("events")._inc_key((), len(out))
        if dropped_new:
            _metric("dropped")._inc_key((), dropped_new)
            _metric("events")._inc_key((), dropped_new)
    except Exception:
        pass
    return out


def ring_stats() -> Dict[str, int]:
    with _lock:
        return {"len": len(_ring), "dropped": _dropped,
                "capacity": _ring_capacity()}


def note_push() -> None:
    """Count one shipped event batch (worker pipe / heartbeat)."""
    try:
        _metric("pushes")._inc_key(())
    except Exception:
        pass


def _reset_for_tests() -> None:
    """Restore module state so a test can re-resolve from a patched env."""
    global _ring_cap, _dropped, _dropped_counted
    with _lock:
        _state["enabled"] = None
        _ring.clear()
        _ring_cap = None
        _dropped = 0
        _dropped_counted = 0


# ---------------------------------------------------------------------------
# postmortems: death forensics captured at the reaping site
# ---------------------------------------------------------------------------

#: lines that make it into a postmortem's ``error_lines`` extraction
_ERROR_LINE = re.compile(
    r"Traceback \(most recent call last\)|\bFATAL\b|\bCRITICAL\b"
    r"|^\s*\w*(Error|Exception|Interrupt|Exit)\b.*:|Segmentation fault"
    r"|MemoryError|Killed\b", re.IGNORECASE)

#: head line of a faulthandler USR1 dump (worker.py registers it)
_STACK_HEAD = re.compile(r"^(Current thread|Thread) 0x[0-9a-f]+")


def describe_exit(status: Optional[int]) -> str:
    """Human cause class for a waitpid-style exit code: ``clean_exit``,
    ``exit:<code>`` or ``signal:<NAME>`` (negative codes are signals, the
    Popen/waitstatus_to_exitcode convention)."""
    if status is None:
        return "unknown"
    if status == 0:
        return "clean_exit"
    if status < 0:
        try:
            import signal as _signal

            return f"signal:{_signal.Signals(-status).name}"
        except (ValueError, ImportError):
            return f"signal:{-status}"
    return f"exit:{status}"


def _read_log_tail(log_path: Optional[str], pid: Optional[int],
                   max_bytes: int) -> str:
    """Last ``max_bytes`` of the process's log, falling back to
    ``/proc/<pid>/fd/{1,2}`` when the file was deleted under a live
    process (the known failure mode on this box: a 0-byte or missing
    log with output still readable through the fd)."""
    candidates = []
    if log_path:
        candidates.append(log_path)
    if pid:
        candidates.extend([f"/proc/{pid}/fd/2", f"/proc/{pid}/fd/1"])
    for path in candidates:
        try:
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - max_bytes))
                data = f.read(max_bytes)
            if data:
                return data.decode("utf-8", errors="replace")
        except OSError:
            continue
    return ""


def extract_error_lines(text: str, max_lines: int = 20) -> List[str]:
    """The log lines worth reading first: tracebacks heads, *Error:
    lines, OOM-killer traces — bounded, newest last."""
    out = [ln for ln in text.splitlines() if _ERROR_LINE.search(ln)]
    return out[-max_lines:]


def extract_last_stack(text: str, max_lines: int = 40) -> Optional[str]:
    """The LAST faulthandler dump in the log (a USR1 stack from
    `rtpu stack` / hung-test debugging), when one landed before death."""
    lines = text.splitlines()
    start = None
    for i, ln in enumerate(lines):
        if _STACK_HEAD.match(ln):
            start = i
    if start is None:
        return None
    return "\n".join(lines[start:start + max_lines])


def build_postmortem(exit_status: Optional[int] = None,
                     log_path: Optional[str] = None,
                     pid: Optional[int] = None,
                     max_tail_bytes: int = 4096,
                     **extra: Any) -> Dict[str, Any]:
    """Assemble a death postmortem at the reaping site: exit cause class
    (code/signal), a bounded stderr tail, extracted error lines, and the
    last USR1 stack when one is in the log. Never raises — forensics
    must not break the death path they explain."""
    pm: Dict[str, Any] = {"cause": describe_exit(exit_status)}
    if exit_status is not None:
        pm["exit_status"] = exit_status
    pm.update(extra)
    try:
        tail = _read_log_tail(log_path, pid, max_tail_bytes)
        if tail:
            pm["stderr_tail"] = tail[-max_tail_bytes:]
            err_lines = extract_error_lines(tail)
            if err_lines:
                pm["error_lines"] = err_lines
            stack = extract_last_stack(tail)
            if stack:
                pm["last_stack"] = stack
    except Exception:
        pass
    return pm


def format_postmortem(pm: Optional[Dict[str, Any]],
                      max_chars: int = 1200) -> str:
    """One readable block for folding a postmortem into an error message
    (cause line + the most useful log excerpt), bounded so a crash-loop
    can't bloat every TaskError with megabytes of log."""
    if not pm:
        return ""
    parts = [f"cause: {pm.get('cause', 'unknown')}"]
    if pm.get("error_lines"):
        parts.append("error lines:\n  " + "\n  ".join(pm["error_lines"]))
    elif pm.get("stderr_tail"):
        parts.append("stderr tail:\n  "
                     + "\n  ".join(pm["stderr_tail"].splitlines()[-8:]))
    out = "\n".join(parts)
    return out[-max_chars:]
