"""Shared transient-error retry helper (the conftest ``poll_until`` idiom,
available to library code).

CLAUDE.md round-5 deflake rule: under load on a 2-vCPU box, cluster RPC
calls show ~1 random transient ``ConnectionError``/``TimeoutError`` per
full-suite run that always succeeds on retry. Library polls that ride the
GCS (node views, death-subscription state reads) must absorb those instead
of surfacing them as spurious failures — the elastic-training membership
probe (``train/backend_executor.py``) was the call site that made this a
shared helper instead of one more inline ``try/except`` copy.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Tuple, Type, TypeVar

logger = logging.getLogger(__name__)

T = TypeVar("T")

#: the transient family the conftest ``poll_until`` retries: connection
#: drops, RPC timeouts, and the OSError umbrella (EPIPE/ECONNRESET land
#: there when a peer restarts mid-call)
TRANSIENT_ERRORS: Tuple[Type[BaseException], ...] = (
    ConnectionError, TimeoutError, OSError)


def retry_transient(fn: Callable[[], T], *, attempts: int = 5,
                    delay: float = 0.2,
                    transient: Tuple[Type[BaseException], ...] = None,
                    desc: str = "") -> T:
    """Call ``fn()`` retrying transient errors with a fixed short delay.

    The LAST attempt's exception propagates — this absorbs blips, it does
    not mask a genuinely dead peer. ``desc`` names the call in the debug
    log so a retried probe is attributable.
    """
    if transient is None:
        transient = TRANSIENT_ERRORS
    last: BaseException = None
    for attempt in range(max(int(attempts), 1)):
        try:
            return fn()
        except transient as e:  # noqa: PERF203 — retry loop by design
            last = e
            logger.debug("transient error in %s (attempt %d/%d): %r",
                         desc or getattr(fn, "__name__", "call"),
                         attempt + 1, attempts, e)
            time.sleep(delay)
    raise last
