"""Per-node host utilization snapshot (reference dashboard ``reporter``
module role: ``dashboard/modules/reporter/reporter_agent.py`` samples
cpu/mem per node via psutil and ships it to the dashboard).

Here the snapshot rides the existing node heartbeat — no extra agent
process, no extra RPC: the GCS node table carries the latest sample and
``ray_tpu.nodes()`` / the dashboard nodes view expose it.
"""

from __future__ import annotations

from typing import Any, Dict


def host_stats() -> Dict[str, Any]:
    """Cheap (non-blocking) utilization sample for this host."""
    try:
        import psutil
    except Exception:  # pragma: no cover - psutil is in the image
        return {}
    try:
        vm = psutil.virtual_memory()
        return {
            # interval=None: delta since the previous call — free, and
            # the heartbeat cadence gives it a natural window
            "cpu_percent": psutil.cpu_percent(interval=None),
            "mem_used": int(vm.used),
            "mem_total": int(vm.total),
            "mem_percent": vm.percent,
            "load_1m": psutil.getloadavg()[0],
            "num_cpus": psutil.cpu_count(),
        }
    except Exception:  # pragma: no cover - platform quirks
        return {}
