"""Deterministic failpoint framework (chaos-injection plane).

Role analog: the reference's release-gated chaos tests plus the classic
``SET_FAILPOINT`` pattern (TiKV/RocksDB ``fail::fail_point!``): named
injection *sites* threaded through the core runtime and cluster plane fire
configured *actions* when armed. Everything recovery-related in ray_tpu
(task retries, actor restart, lineage reconstruction, node-death
re-placement, GCS snapshot FT) is driven through these sites by
``tests/test_chaos_matrix.py`` — each past recovery bug keeps its failpoint
armed there as its regression test.

Sites (grep ``failpoints.hit(`` for the live list)::

    worker.exec            before a task/actor call executes   (worker)
    worker.exec.before_result  after execute, before "done"    (worker)
    worker.pipe.send       worker -> driver control message    (worker)
    pipe.send              driver -> worker control message    (driver)
    store.seal             object store put/seal               (any)
    rpc.client.send        cluster RPC request/cast egress     (any)
    rpc.server.dispatch    cluster RPC handler entry           (GCS/daemon)
    gcs.heartbeat          node heartbeat egress               (adapter)
    daemon.lease_grant     peer-forwarded task acceptance      (daemon)
    adapter.pg.before_commit   between PG prepare and commit   (creator)
    data.exchange.ack      reducer-ack retirement              (driver)
    serve.kv_transfer      prefill->decode KV-block ship       (replica)

Spec grammar (one or more comma/semicolon-separated entries)::

    <site>=<action>[:<arg>][@<key>=<val>]...

    actions:  raise[:ExcName]   raise FailpointError (or OSError /
                                ConnectionError / TimeoutError / ValueError)
              delay:<seconds>   sleep, then continue
              drop              return True — the call site drops the
                                message / skips the operation
              kill              SIGKILL this process (crash, no cleanup)
              exit[:code]       os._exit (default 137)
    triggers: after=N           skip the first N hits
              times=K           fire at most K times (per process)
              p=P seed=S        fire with seeded probability P per hit
              arg=V             fire only when the site's payload == V
                                (e.g. RPC method name, task/method name)
              once=PATH         fire at most once ACROSS processes —
                                O_CREAT|O_EXCL on PATH elects the firer;
                                with times=K the budget is global: K
                                fires total, wherever the hits land

Arming:

- per-process: the ``RTPU_FAILPOINTS`` env var carries a spec string
  (inherited by spawned workers/daemons); ``RTPU_FAILPOINTS=0`` is the
  global kill switch — ``hit()`` can never fire and ``arm()`` no-ops.
- cluster-wide from tests: :func:`arm` applies locally, broadcasts to this
  driver's workers over the control pipe, and (in cluster mode) records
  the spec in the GCS KV (``__failpoints__`` namespace, durable through
  snapshots) and publishes on the ``failpoints`` pubsub channel, which
  daemons apply and relay to *their* workers. Late joiners pull the KV at
  registration.

Disabled cost: ``hit(site)`` is one dict ``get`` on a (normally empty)
module dict plus the attribute load — no locks, no allocation.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional

#: KV namespace + key used for cluster-wide arming
KV_NAMESPACE = "__failpoints__"
KV_KEY = "specs"
#: pubsub channel daemons subscribe to
CHANNEL = "failpoints"


class FailpointError(RuntimeError):
    """Raised by a ``raise``-action failpoint (default exception type)."""


_EXC_TYPES = {
    "failpointerror": FailpointError,
    "oserror": OSError,
    "connectionerror": ConnectionError,
    "timeouterror": TimeoutError,
    "valueerror": ValueError,
    "runtimeerror": RuntimeError,
}

# the global kill switch: parsed once at import. "0"/"false"/... disables
# the whole plane for this process (and, via env inheritance, its children).
_raw_env = os.environ.get("RTPU_FAILPOINTS", "")
ENABLED = _raw_env.strip().lower() not in ("0", "false", "no", "off")

#: site -> _Failpoint. THE hot-path structure: empty when nothing is armed,
#: so ``hit()`` is a single failed dict lookup.
_armed: Dict[str, "_Failpoint"] = {}
_arm_lock = threading.Lock()


def _fired_metric():
    from ray_tpu.util import metric_defs

    return metric_defs.get("rtpu_failpoints_fired_total")


class _Failpoint:
    __slots__ = ("site", "action", "arg", "after", "times", "prob", "rng",
                 "match", "once_path", "hits", "fired", "lock", "spec")

    def __init__(self, site: str, action: str, arg: Optional[str],
                 opts: Dict[str, str], spec: str):
        self.site = site
        self.action = action
        self.arg = arg
        self.spec = spec
        self.after = int(opts.get("after", 0))
        self.times = int(opts["times"]) if "times" in opts else None
        self.prob = float(opts["p"]) if "p" in opts else None
        if self.prob is not None:
            import random

            self.rng = random.Random(int(opts.get("seed", 0)))
        else:
            self.rng = None
        self.match = opts.get("arg")
        self.once_path = opts.get("once")
        self.hits = 0
        self.fired = 0
        self.lock = threading.Lock()

    def _should_fire(self, payload) -> bool:
        if self.match is not None and str(payload) != self.match:
            return False
        with self.lock:
            self.hits += 1
            if self.hits <= self.after:
                return False
            if self.times is not None and self.fired >= self.times:
                return False
            if self.prob is not None and self.rng.random() >= self.prob:
                return False
            if self.once_path is not None:
                # cross-process at-most-once election: O_CREAT|O_EXCL is
                # atomic on a shared filesystem — exactly one process (and
                # one hit in it) wins each slot. With times=K the budget
                # is GLOBAL (K slots: PATH.0..PATH.K-1) instead of
                # per-process — "fail the first K executions, wherever
                # they land".
                budget = self.times if self.times is not None else 1
                won = False
                for slot in range(budget):
                    path = (self.once_path if budget == 1
                            else f"{self.once_path}.{slot}")
                    try:
                        fd = os.open(path,
                                     os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                        os.close(fd)
                        won = True
                        break
                    except OSError:
                        continue
                if not won:
                    return False
            self.fired += 1
        return True

    def fire(self, payload) -> bool:
        if not self._should_fire(payload):
            return False
        try:
            _fired_metric().inc(tags={"site": self.site})
        except Exception:
            pass
        act = self.action
        if act == "delay":
            import time

            time.sleep(float(self.arg or 0.1))
            return False
        if act == "drop":
            return True
        if act == "raise":
            exc = _EXC_TYPES.get((self.arg or "").lower(), FailpointError)
            raise exc(f"failpoint {self.site} fired"
                      + (f" (payload={payload!r})" if payload else ""))
        if act == "kill":
            import signal

            os.kill(os.getpid(), signal.SIGKILL)
            return True  # unreachable
        if act == "exit":
            os._exit(int(self.arg or 137))
        raise ValueError(f"unknown failpoint action {act!r}")


def hit(site: str, payload: Any = None) -> bool:
    """The injection hook. Returns True when a ``drop`` action fired (the
    call site is responsible for dropping the message / skipping the
    operation); raises / sleeps / kills for the other actions. One dict
    lookup when nothing is armed at this site."""
    fp = _armed.get(site)
    if fp is None:
        return False
    return fp.fire(payload)


def parse_specs(spec_str: str) -> List[_Failpoint]:
    out = []
    for entry in spec_str.replace(";", ",").split(","):
        entry = entry.strip()
        if not entry:
            continue
        site, _, rhs = entry.partition("=")
        if not rhs:
            raise ValueError(f"bad failpoint spec {entry!r} "
                             "(want site=action[:arg][@k=v...])")
        parts = rhs.split("@")
        action, _, arg = parts[0].partition(":")
        action = action.strip()
        arg = arg.strip() or None
        # validate HERE, not at the hit site: a typo'd spec must fail the
        # arm() call, never detonate cluster-wide at every injection point
        if action not in ("raise", "delay", "drop", "kill", "exit"):
            raise ValueError(f"unknown failpoint action {action!r} "
                             f"in {entry!r}")
        if action == "delay":
            float(arg or 0.1)
        if action == "exit":
            int(arg or 137)
        opts: Dict[str, str] = {}
        for kv in parts[1:]:
            k, _, v = kv.partition("=")
            opts[k.strip()] = v.strip()
        out.append(_Failpoint(site.strip(), action, arg, opts, entry))
    return out


def apply_spec(spec_str: str) -> None:
    """Arm the given specs in THIS process only (no propagation).

    Re-applying a spec IDENTICAL to the one already armed at a site is a
    no-op that keeps the live trigger counters: cluster arming delivers
    the same spec more than once (pubsub echo to the arming driver, KV
    sync racing the pubsub push on daemons, ready-push racing the relay
    on workers), and a re-delivery must never reset an after=/times=
    budget mid-test. Re-arm a site with a *different* spec (or disarm
    first) to reset it."""
    if not ENABLED:
        return
    for fp in parse_specs(spec_str):
        with _arm_lock:
            cur = _armed.get(fp.site)
            if cur is not None and cur.spec == fp.spec:
                continue
            _armed[fp.site] = fp


def clear(site: Optional[str] = None) -> None:
    """Disarm one site (or all) in THIS process only."""
    with _arm_lock:
        if site is None:
            _armed.clear()
        else:
            _armed.pop(site, None)


def active_specs() -> List[str]:
    with _arm_lock:
        return [fp.spec for fp in _armed.values()]


def _runtime():
    from ray_tpu.core import runtime as rt

    return rt._runtime


def arm(spec_str: str) -> None:
    """Arm failpoints from a test/driver: applies locally, pushes to this
    runtime's workers over the control pipe, and broadcasts cluster-wide
    (GCS KV + pubsub) when a cluster adapter is attached. No-op under the
    ``RTPU_FAILPOINTS=0`` kill switch."""
    if not ENABLED:
        return
    parse_specs(spec_str)  # validate before shipping anywhere
    apply_spec(spec_str)
    rt = _runtime()
    if rt is None:
        return
    _broadcast_local(rt, spec_str)
    cluster = getattr(rt, "cluster", None)
    if cluster is not None:
        try:
            prev = cluster.kv_op("get", KV_KEY, KV_NAMESPACE)
            merged = ((prev.decode() + ",") if prev else "") + spec_str
            cluster.kv_op("put", KV_KEY, merged.encode(), KV_NAMESPACE, True)
            cluster.gcs.call("fp_arm", spec_str, timeout=10)
            cluster.gcs.call("publish", CHANNEL,
                             {"op": "arm", "spec": spec_str}, timeout=10)
        except Exception:
            pass


def disarm() -> None:
    """Disarm everything, everywhere this driver can reach."""
    clear()
    rt = _runtime()
    if rt is None:
        return
    _broadcast_local(rt, None)
    cluster = getattr(rt, "cluster", None)
    if cluster is not None:
        try:
            cluster.kv_op("del", KV_KEY, KV_NAMESPACE)
            cluster.gcs.call("fp_disarm", timeout=10)
            cluster.gcs.call("publish", CHANNEL, {"op": "disarm"},
                             timeout=10)
        except Exception:
            pass


def _broadcast_local(rt, spec_str: Optional[str]) -> None:
    """Push an arm/disarm to every worker of this runtime; remember the
    armed specs so workers spawned later get them on dial-back."""
    if not getattr(rt, "is_driver", False):
        return
    if spec_str is None:
        rt._fp_specs = None
    else:
        # accumulate across arm() calls (mirrors the GCS KV merge):
        # workers spawned later must receive EVERY armed spec, not just
        # the most recent one. Entry-dedupe so re-deliveries (pubsub
        # echo) don't grow the string unboundedly.
        prev = getattr(rt, "_fp_specs", None)
        entries = prev.split(",") if prev else []
        for e in spec_str.split(","):
            if e and e not in entries:
                entries.append(e)
        rt._fp_specs = ",".join(entries) or None
    for ws in list(getattr(rt, "workers", {}).values()):
        if ws.status == "dead" or ws.conn is None:
            continue
        try:
            ws.send(("fp", spec_str))
        except Exception:
            pass


def sync_from_kv(kv_get) -> None:
    """Pull + apply the cluster-wide spec (late joiners / re-registration).
    ``kv_get(key, namespace) -> Optional[bytes]``."""
    if not ENABLED:
        return
    try:
        blob = kv_get(KV_KEY, KV_NAMESPACE)
    except Exception:
        return
    if blob:
        try:
            apply_spec(blob.decode())
        except Exception:
            pass


# arm anything the environment carries (worker/daemon processes inherit
# the driver's env; tests export RTPU_FAILPOINTS for subprocesses)
if ENABLED and _raw_env.strip().lower() not in ("", "1", "true", "yes",
                                                "on"):
    try:
        apply_spec(_raw_env)
    except ValueError:
        pass
