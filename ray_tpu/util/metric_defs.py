"""Built-in core-runtime metric definitions — ONE central registry.

Role analog: ``src/ray/stats/metric_defs.cc`` (the reference's ~90
built-in gauges/counters/histograms for scheduler, object store, GCS,
pull/push managers, worker pools). Every metric the runtime itself
records is DEFINED here and instantiated via :func:`get`; core modules
never call ``Counter(...)``/``Gauge(...)``/``Histogram(...)`` directly
(``tests/test_invariants.py`` greps for violations). That single-source
rule is what keeps the invariants testable: every built-in has help
text, the ``rtpu_`` prefix, and exactly one definition — and the README
"Built-in metrics reference" table is GENERATED from this module
(``python -m ray_tpu.util.metric_defs --markdown``), so it cannot
drift.

Conventions (Prometheus):
- counters end in ``_total`` (or ``_bytes_total``);
- histograms/gauges carry a unit suffix (``_seconds``, ``_bytes``);
- every name starts with ``rtpu_`` so one scrape config covers the
  whole runtime.

Which process records what: scheduler/pipe/refcount metrics live in the
driver (and each node daemon — a daemon IS a DriverRuntime); store
metrics in whichever process touches the store (driver, workers,
daemons); GCS metrics in the GCS server process; RPC metrics in every
process that speaks cluster RPC. Federation (util/metrics.py) merges
them all onto the head ``/metrics`` with origin labels.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple


class MetricDef(NamedTuple):
    name: str
    kind: str                       # "counter" | "gauge" | "histogram"
    help: str
    tag_keys: Tuple[str, ...]
    boundaries: Optional[Tuple[float, ...]]
    component: str                  # subsystem, for docs/grouping


_DEFS: "OrderedDict[str, MetricDef]" = OrderedDict()


def _def(name: str, kind: str, help: str, *,
         tag_keys: Sequence[str] = (),
         boundaries: Optional[Sequence[float]] = None,
         component: str = "") -> None:
    assert name.startswith("rtpu_"), f"built-in metric {name} lacks rtpu_"
    assert help.strip(), f"built-in metric {name} has no help text"
    assert name not in _DEFS, f"duplicate metric definition {name}"
    assert kind in ("counter", "gauge", "histogram"), kind
    if kind == "counter":
        assert name.endswith("_total"), f"counter {name} must end _total"
    _DEFS[name] = MetricDef(name, kind, help, tuple(tag_keys),
                            tuple(boundaries) if boundaries else None,
                            component)


# latency boundary presets (seconds)
_LAT_FAST = (1e-5, 5e-5, 1e-4, 5e-4, 1e-3, 5e-3, 0.01, 0.05, 0.1, 0.5,
             1.0, 5.0)                      # locks, RPC handlers, store ops
_LAT_TASK = (1e-4, 5e-4, 1e-3, 5e-3, 0.01, 0.05, 0.1, 0.5, 1, 5, 10, 60)
_LAT_SPAWN = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2, 5, 10, 30)

# ---------------------------------------------------------------------------
# scheduler / driver runtime (core/runtime.py)
# ---------------------------------------------------------------------------

_def("rtpu_scheduler_tasks_submitted_total", "counter",
     "task specs submitted to this node's scheduler",
     tag_keys=("type",), component="scheduler")
_def("rtpu_scheduler_tasks_dispatched_total", "counter",
     "tasks leased to a worker (lease grants)", component="scheduler")
_def("rtpu_tasks_finished_total", "counter",
     "tasks finished on this node's scheduler",
     tag_keys=("status",), component="scheduler")
_def("rtpu_task_phase_seconds", "histogram",
     "task lifecycle phase latency (submit->queue->lease->arg_fetch->"
     "deserialize->execute->store_result)",
     tag_keys=("phase",), boundaries=_LAT_TASK, component="scheduler")
_def("rtpu_scheduler_ready_queue_depth", "gauge",
     "tasks ready to run but not yet leased to a worker (sampled)",
     component="scheduler")
_def("rtpu_scheduler_inflight_tasks", "gauge",
     "tasks currently executing on this node's workers (sampled)",
     component="scheduler")
_def("rtpu_scheduler_actor_pending_calls", "gauge",
     "actor method calls queued behind busy actors (sampled)",
     component="scheduler")
_def("rtpu_refcount_entries", "gauge",
     "objects with a nonzero local pin count in the driver's reference "
     "table (sampled)", component="scheduler")
_def("rtpu_refcount_arg_pin_entries", "gauge",
     "submitted-task argument pin sets held until first return is "
     "terminal (sampled)", component="scheduler")
_def("rtpu_lineage_entries", "gauge",
     "task specs retained for object reconstruction (sampled)",
     component="scheduler")
_def("rtpu_lineage_bytes", "gauge",
     "approximate bytes retained by the lineage table (sampled)",
     component="scheduler")

# worker control pipe (driver side of every worker connection)
_def("rtpu_pipe_sent_bytes_total", "counter",
     "bytes the driver sent over worker control pipes (framed message "
     "payloads)", component="scheduler")
_def("rtpu_pipe_recv_bytes_total", "counter",
     "bytes the driver received over worker control pipes",
     component="scheduler")
_def("rtpu_pipe_messages_total", "counter",
     "control-pipe messages by direction (sent/recv, driver side)",
     tag_keys=("direction",), component="scheduler")
_def("rtpu_pipe_batch_messages", "histogram",
     "control messages per coalesced pipe frame (worker-side Nagle "
     "window RTPU_PIPE_COALESCE_US + piggybacked urgent sends; observed "
     "at driver receive)",
     boundaries=(2, 3, 5, 8, 13, 21, 34, 55, 89), component="scheduler")

# native pipe engine (driver side; see native/pipe.cc + _native.NativePipe)
_def("rtpu_pipe_native_send_seconds", "histogram",
     "driver-side enqueue latency per control message handed to the "
     "GIL-free pipe engine (framing + write happen on its sender thread)",
     boundaries=_LAT_FAST, component="scheduler")
_def("rtpu_pipe_native_drain_messages", "histogram",
     "records per native-engine drain wake on a driver reader thread "
     "(one GIL acquisition services this many worker messages)",
     boundaries=(1, 2, 3, 5, 8, 13, 21, 34, 55, 89),
     component="scheduler")
_def("rtpu_pipe_native_frames", "gauge",
     "frames the native pipe engines wrote/read across live worker "
     "connections, by direction (monotonic, sampled)",
     tag_keys=("direction",), component="scheduler")
_def("rtpu_pipe_native_messages", "gauge",
     "messages packed into / split out of native pipe frames, by "
     "direction (monotonic, sampled; messages/frames = the coalescing "
     "factor)", tag_keys=("direction",), component="scheduler")
_def("rtpu_pipe_native_refpin_transitions", "gauge",
     "net 0<->1 borrow transitions the native refcount tables surfaced "
     "to Python (deltas beyond these never touched the interpreter; "
     "monotonic, sampled)", component="scheduler")

# compiled execution plane (dag/compiled_dag.py + experimental/channel.py)
_def("rtpu_dag_executions_total", "counter",
     "compiled-DAG invocations admitted (execute/execute_async)",
     component="dag")
_def("rtpu_dag_inflight", "gauge",
     "compiled-DAG invocations admitted but not yet resolved to their "
     "future (delta-updated; aggregates across every DAG in the "
     "process)", component="dag")
_def("rtpu_channel_read_wait_seconds", "histogram",
     "time a compiled-DAG channel read waited past its spin budget for "
     "the next ring slot (recorded only when a wait backed off)",
     boundaries=_LAT_FAST, component="dag")
_def("rtpu_channel_write_wait_seconds", "histogram",
     "time a compiled-DAG channel write waited for ring backpressure "
     "(slowest reader cursor) to clear",
     boundaries=_LAT_FAST, component="dag")

# worker pool / zygote (spawn path)
_def("rtpu_worker_pool_size", "gauge",
     "worker processes attached to this node's pool by state (sampled)",
     tag_keys=("state",), component="worker_pool")
_def("rtpu_worker_spawns_total", "counter",
     "worker processes spawned, by mode (zygote fork vs interpreter "
     "exec)", tag_keys=("mode",), component="worker_pool")
_def("rtpu_worker_spawn_seconds", "histogram",
     "worker launch latency: spawn decision to the worker's ready "
     "message", tag_keys=("mode",), boundaries=_LAT_SPAWN,
     component="worker_pool")
_def("rtpu_worker_deaths_total", "counter",
     "worker processes that died (crash, kill, or shutdown race)",
     component="worker_pool")
_def("rtpu_zygote_restarts_total", "counter",
     "fork-server (zygote) restarts after death", component="worker_pool")

# worker-process built-ins (recorded inside each worker, federated up)
_def("rtpu_worker_tasks_total", "counter",
     "tasks executed by this worker process", component="worker")
_def("rtpu_worker_task_exec_seconds", "histogram",
     "user-code execution time in this worker",
     boundaries=(0.001, 0.01, 0.1, 1, 10, 60, 600), component="worker")

# ---------------------------------------------------------------------------
# object store (core/object_store.py)
# ---------------------------------------------------------------------------

_def("rtpu_object_store_put_seconds", "histogram",
     "store write latency (serialize excluded; segment/arena/inline "
     "write + seal)", boundaries=_LAT_FAST, component="object_store")
_def("rtpu_object_store_get_seconds", "histogram",
     "store read latency (map + deserialize)", boundaries=_LAT_FAST,
     component="object_store")
_def("rtpu_object_store_puts_total", "counter",
     "store writes by landing path (inline/arena/file/spill)",
     tag_keys=("path",), component="object_store")
_def("rtpu_object_store_put_bytes_total", "counter",
     "serialized bytes written to the store (all paths)",
     component="object_store")
_def("rtpu_object_store_bytes_used", "gauge",
     "bytes this process accounts in shm (arena used + its file "
     "segments; sampled)", component="object_store")
_def("rtpu_object_store_capacity_bytes", "gauge",
     "configured arena capacity (sampled)", component="object_store")
_def("rtpu_object_store_pins", "gauge",
     "segments pinned by live deserialized views in this process "
     "(sampled)", component="object_store")
_def("rtpu_object_store_prefault_bytes", "gauge",
     "arena bytes pre-faulted by the background populate thread",
     component="object_store")
_def("rtpu_object_store_spilled_bytes_total", "counter",
     "bytes written to the disk spill directory", component="object_store")
_def("rtpu_object_store_spilled_objects_total", "counter",
     "objects written to the disk spill directory",
     component="object_store")
_def("rtpu_object_store_restored_bytes_total", "counter",
     "spilled bytes promoted back into shared memory",
     component="object_store")
_def("rtpu_object_store_restored_objects_total", "counter",
     "spilled objects promoted back into shared memory",
     component="object_store")
_def("rtpu_object_store_spill_read_bytes_total", "counter",
     "bytes served directly from spill files (reads + remote pulls that "
     "did not restore first)", component="object_store")
_def("rtpu_object_store_spill_compressed_bytes_total", "counter",
     "physical (compressed) bytes written to spill files — compare with "
     "rtpu_object_store_spilled_bytes_total (logical) for the overall "
     "spill compression factor", component="object_store")
_def("rtpu_object_store_spill_compression_ratio", "histogram",
     "logical/physical size ratio per compressed spill write (1.0 = "
     "stored raw: incompressible or codec off)",
     boundaries=(1.0, 1.1, 1.25, 1.5, 2, 3, 5, 10, 25),
     component="object_store")
_def("rtpu_object_store_parallel_copy_bytes_total", "counter",
     "payload bytes moved by the native multi-threaded memcpy path "
     "(large put/get slices past RTPU_STORE_PARALLEL_COPY_BYTES)",
     component="object_store")
_def("rtpu_object_store_parallel_copy_seconds", "histogram",
     "wall time of native multi-threaded copies (bytes/seconds = "
     "achieved aggregate memcpy bandwidth)",
     boundaries=_LAT_FAST, component="object_store")
_def("rtpu_object_store_spill_dir_bytes", "gauge",
     "bytes currently spilled to disk on this node (sampled)",
     component="object_store")

# ---------------------------------------------------------------------------
# GCS server (cluster/gcs_server.py — recorded in the GCS process,
# exported to the head /metrics via rpc_metrics_get with component=gcs)
# ---------------------------------------------------------------------------

_def("rtpu_gcs_rpc_total", "counter",
     "GCS RPCs handled, by method", tag_keys=("method",), component="gcs")
_def("rtpu_gcs_rpc_seconds", "histogram",
     "GCS RPC handler latency, by method", tag_keys=("method",),
     boundaries=_LAT_FAST, component="gcs")
_def("rtpu_gcs_pubsub_messages_total", "counter",
     "pubsub deliveries pushed to subscribers (fanout: one per "
     "subscriber per publish)", tag_keys=("channel",), component="gcs")
_def("rtpu_gcs_table_size", "gauge",
     "GCS table entry counts (objects/nodes/actors/kv/functions/pgs/"
     "task_events/trace_events/profile_events/free_candidates/"
     "tombstones; sampled)",
     tag_keys=("table",), component="gcs")
_def("rtpu_gcs_nodes_alive", "gauge",
     "cluster nodes currently alive (sampled)", component="gcs")
_def("rtpu_gcs_heartbeat_gap_seconds", "histogram",
     "observed gap between consecutive heartbeats of a node (nominal "
     "0.5s; tail growth = control-plane or sender contention)",
     boundaries=(0.25, 0.5, 0.75, 1, 1.5, 2, 3, 5, 8, 15, 30),
     component="gcs")

# ---------------------------------------------------------------------------
# cluster RPC transport (cluster/rpc.py)
# ---------------------------------------------------------------------------

_def("rtpu_rpc_sent_bytes_total", "counter",
     "framed bytes sent over cluster RPC connections (client calls/casts "
     "+ server replies/pushes)", component="rpc")
_def("rtpu_rpc_recv_bytes_total", "counter",
     "framed bytes received over cluster RPC connections", component="rpc")
_def("rtpu_rpc_server_requests_total", "counter",
     "requests accepted by RPC servers in this process, by kind "
     "(req/cast)", tag_keys=("kind",), component="rpc")
_def("rtpu_rpc_server_queue_wait_seconds", "histogram",
     "time a request waited between socket read and handler start (the "
     "server thread-pool queue — the GCS accept-loop contention signal)",
     boundaries=_LAT_FAST, component="rpc")
_def("rtpu_rpc_client_reconnects_total", "counter",
     "successful RPC client reconnects after a connection drop",
     component="rpc")
_def("rtpu_rpc_client_reconnect_attempts_total", "counter",
     "RPC client reconnect attempts (including failed retries)",
     component="rpc")
_def("rtpu_rpc_client_timeouts_total", "counter",
     "RPC calls that hit their caller-side timeout", component="rpc")

# ---------------------------------------------------------------------------
# cluster adapter / node daemon (cluster/adapter.py, node_daemon.py)
# ---------------------------------------------------------------------------

_def("rtpu_cluster_tasks_forwarded_total", "counter",
     "task/actor specs forwarded to a peer node, by spillback reason "
     "(resources/locality/strategy/pg/actor_route)",
     tag_keys=("reason",), component="cluster")
_def("rtpu_cluster_object_pull_bytes_total", "counter",
     "object bytes pulled from peer nodes", component="cluster")
_def("rtpu_cluster_object_serve_bytes_total", "counter",
     "object bytes served to peer nodes", component="cluster")
_def("rtpu_cluster_heartbeats_total", "counter",
     "heartbeats this node sent to the GCS", component="cluster")
_def("rtpu_cluster_heartbeat_rtt_seconds", "histogram",
     "round-trip of the node_heartbeat RPC as seen by the sender",
     boundaries=_LAT_FAST, component="cluster")
_def("rtpu_daemon_uptime_seconds", "gauge",
     "node daemon uptime (sampled)", component="cluster")

# ---------------------------------------------------------------------------
# failpoints (util/failpoints.py)
# ---------------------------------------------------------------------------

_def("rtpu_failpoints_fired_total", "counter",
     "chaos failpoints that fired in this process (test/chaos plane; "
     "always 0 in production unless RTPU_FAILPOINTS arms a site)",
     tag_keys=("site",), component="failpoints")

# ---------------------------------------------------------------------------
# trace plane (util/tracing.py -> util/trace_store.py)
# ---------------------------------------------------------------------------

_def("rtpu_trace_spans_total", "counter",
     "spans recorded into this process's trace ring (0 unless "
     "RTPU_TRACING armed)", component="tracing")
_def("rtpu_trace_spans_dropped_total", "counter",
     "spans evicted from the bounded trace ring before collection "
     "(raise RTPU_TRACE_RING or shorten the push interval)",
     component="tracing")
_def("rtpu_trace_push_batches_total", "counter",
     "span batches shipped toward the head (worker control-pipe pushes "
     "+ node heartbeat rides)", component="tracing")

# ---------------------------------------------------------------------------
# profiling plane (util/profiling.py)
# ---------------------------------------------------------------------------

_def("rtpu_profile_samples_total", "counter",
     "stack samples aggregated into this process's profile table "
     "(busy + idle; 0 unless RTPU_PROFILING armed)",
     component="profiling")
_def("rtpu_profile_samples_dropped_total", "counter",
     "samples dropped because the bounded profile table was full of "
     "unique stacks (raise RTPU_PROFILE_TABLE_MAX or shorten the push "
     "interval)", component="profiling")
_def("rtpu_profile_push_batches_total", "counter",
     "profile batches shipped toward the head (worker control-pipe "
     "pushes + node heartbeat rides)", component="profiling")

# ---------------------------------------------------------------------------
# event plane (util/events.py -> util/event_store.py)
# ---------------------------------------------------------------------------

_def("rtpu_lifecycle_events_total", "counter",
     "lifecycle events recorded into this process's event ring "
     "(worker/actor/node deaths, spills, serve re-routes, alerts; "
     "0 when RTPU_EVENTS=0)", component="events")
_def("rtpu_lifecycle_events_dropped_total", "counter",
     "events evicted from the bounded event ring before collection "
     "(raise RTPU_EVENT_RING or shorten the push interval)",
     component="events")
_def("rtpu_event_push_batches_total", "counter",
     "lifecycle-event batches shipped toward the head (worker "
     "control-pipe pushes + node heartbeat rides)", component="events")

# ---------------------------------------------------------------------------
# alerting watchdog (util/alerts.py)
# ---------------------------------------------------------------------------

_def("rtpu_alerts_active", "gauge",
     "alert rules currently raised by the head watchdog, by severity "
     "(0 everywhere = healthy; RTPU_ALERTS=0 disables evaluation)",
     tag_keys=("severity",), component="alerts")

# ---------------------------------------------------------------------------
# log federation (util/events.py log fetch rendezvous)
# ---------------------------------------------------------------------------

_def("rtpu_log_fetches_total", "counter",
     "cluster-wide log fetches served by this process (`rtpu logs` / "
     "/api/logs rendezvous replies, including /proc fd fallbacks)",
     component="logs")
_def("rtpu_log_fetch_bytes_total", "counter",
     "log bytes shipped in fetch replies (bounded per fetch by "
     "RTPU_LOG_TAIL_BYTES)", component="logs")

# ---------------------------------------------------------------------------
# lock contention profiler (util/contention.py)
# ---------------------------------------------------------------------------

_def("rtpu_lock_wait_seconds", "histogram",
     "time spent waiting to acquire an instrumented runtime lock "
     "(contended acquisitions only; uncontended fast path records "
     "nothing here)", tag_keys=("lock",), boundaries=_LAT_FAST,
     component="contention")
_def("rtpu_lock_acquisitions", "gauge",
     "total acquisitions of an instrumented lock (monotonic, sampled "
     "from unlocked accumulators)", tag_keys=("lock",),
     component="contention")
_def("rtpu_lock_contended", "gauge",
     "acquisitions that had to wait (monotonic, sampled)",
     tag_keys=("lock",), component="contention")
_def("rtpu_lock_wait_seconds_sum", "gauge",
     "cumulative seconds spent waiting on an instrumented lock "
     "(monotonic, sampled)", tag_keys=("lock",), component="contention")

# ---------------------------------------------------------------------------
# data streaming exchange (data/streaming.py)
# ---------------------------------------------------------------------------

_def("rtpu_data_exchange_blocks_in_flight", "gauge",
     "partition-output blocks not yet consumed by a reducer",
     component="data")
_def("rtpu_data_exchange_reducer_queue_depth", "gauge",
     "forwarded-but-unacked blocks per reducer actor",
     tag_keys=("reducer",), component="data")
_def("rtpu_data_exchange_bytes_total", "counter",
     "block bytes that crossed the exchange", tag_keys=("kind",),
     component="data")
_def("rtpu_data_exchange_blocks_total", "counter",
     "blocks that crossed the exchange", tag_keys=("kind",),
     component="data")

# ---------------------------------------------------------------------------
# train / TPU telemetry (train/telemetry.py)
# ---------------------------------------------------------------------------

_def("rtpu_train_step_seconds", "histogram",
     "wall time per optimizer step",
     boundaries=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10, 60, 600),
     component="train")
_def("rtpu_train_steps_total", "counter", "optimizer steps recorded",
     component="train")
_def("rtpu_train_tokens_per_s", "gauge", "training throughput",
     component="train")
_def("rtpu_train_mfu", "gauge",
     "measured model FLOPs utilization (0..1)", component="train")
_def("rtpu_train_loss", "gauge", "last reported loss", component="train")
_def("rtpu_train_compile_total", "counter", "XLA (re)compilation events",
     component="train")
_def("rtpu_train_compile_seconds", "histogram",
     "wall time of compile events (first call of a fresh program; "
     "includes its first execution)",
     boundaries=(0.1, 1, 5, 10, 30, 60, 300, 1200), component="train")
_def("rtpu_tpu_hbm_used_bytes", "gauge",
     "HBM bytes in use (local devices)", component="train")
_def("rtpu_tpu_hbm_limit_bytes", "gauge",
     "HBM capacity (local devices)", component="train")

# ---------------------------------------------------------------------------
# device plane (util/device_plane.py — the compiled-program registry)
# ---------------------------------------------------------------------------

_def("rtpu_jit_compiles_total", "counter",
     "XLA compiles of registered programs (a fresh abstract signature "
     "or a fresh jit instance); the jit_compile_storm alert watches "
     "retraces, not this warmup-inclusive count", tag_keys=("program",),
     component="device")
_def("rtpu_jit_retraces_total", "counter",
     "recompiles past a program's FIRST signature (each also emits one "
     "jit_recompile lifecycle event carrying the signature diff)",
     tag_keys=("program",), component="device")
_def("rtpu_jit_compile_seconds", "histogram",
     "wall time of registered-program compile calls (dispatch + first "
     "execution, the record_compile convention)",
     tag_keys=("program",),
     boundaries=(0.01, 0.1, 1, 5, 10, 30, 60, 300, 1200),
     component="device")
_def("rtpu_device_programs", "gauge",
     "registered compiled programs in this process's registry "
     "(sampled per device-plane snapshot)", component="device")
_def("rtpu_device_live_buffers", "gauge",
     "live device arrays in this process (jax.live_arrays census, "
     "sampled per snapshot)", component="device")
_def("rtpu_device_live_buffer_bytes", "gauge",
     "bytes held by live device arrays in this process (census "
     "sample)", component="device")
_def("rtpu_device_achieved_flops_per_s", "gauge",
     "achieved FLOP/s attributed from registry cost-analysis flops "
     "and caller-measured step time (cost-model flops count every "
     "executed flop, remat recompute included)",
     tag_keys=("program",), component="device")


# ---------------------------------------------------------------------------
# LLM serving tier (serve/llm.py — recorded in each replica's process,
# federated to the head /metrics like every worker-side metric)
# ---------------------------------------------------------------------------

_def("rtpu_serve_kv_blocks_free", "gauge",
     "paged-KV blocks on this replica's free list (sampled per engine "
     "step). Drained-replica invariant: free + prefix-cache blocks == "
     "total — the prefix trie legitimately retains finished prompts, "
     "so free alone does NOT return to total on a warm idle replica",
     component="serve")
_def("rtpu_serve_kv_blocks_used", "gauge",
     "paged-KV blocks held by live requests and the prefix cache "
     "(sampled per engine step)", component="serve")
_def("rtpu_serve_prefix_cache_hits_total", "counter",
     "prompt lookups that reused at least one cached prefix block",
     component="serve")
_def("rtpu_serve_prefix_cache_misses_total", "counter",
     "prompt lookups that found no cached prefix", component="serve")
_def("rtpu_serve_prefix_hit_tokens_total", "counter",
     "prompt tokens served from the prefix cache instead of prefill "
     "compute (the tokens/s win of prefix reuse)", component="serve")
_def("rtpu_serve_admission_sheds_total", "counter",
     "requests shed by the SLO admission controller, by gate "
     "(ttft/tpot/queue/deadline)", tag_keys=("reason",),
     component="serve")
_def("rtpu_serve_ttft_seconds", "histogram",
     "time from request submission to its first generated token "
     "(admission queue + prefill — the latency the TTFT SLO declares)",
     boundaries=_LAT_TASK, component="serve")
_def("rtpu_serve_tpot_seconds", "histogram",
     "time between consecutive generated tokens of one stream (decode "
     "cadence — the latency the TPOT SLO declares)",
     boundaries=_LAT_FAST, component="serve")

# disaggregated prefill/decode (ISSUE 13): per-pool occupancy + the
# KV-block transfer plane between the pools
_def("rtpu_serve_pool_inflight", "gauge",
     "requests occupying engine slots, by pool role "
     "(prefill/decode/colocated; sampled per engine step)",
     tag_keys=("role",), component="serve")
_def("rtpu_serve_pool_queued", "gauge",
     "admitted requests waiting for an engine slot, by pool role "
     "(sampled per engine step)", tag_keys=("role",), component="serve")
_def("rtpu_serve_pool_kv_used_fraction", "gauge",
     "fraction of this replica's paged-KV blocks in use, by pool role "
     "(sampled per engine step)", tag_keys=("role",), component="serve")
_def("rtpu_serve_kv_transfer_bytes_total", "counter",
     "KV-block payload bytes shipped prefill -> decode, by path "
     "(channel = same-host DeviceChannel ring slot; store = cross-node "
     "object-store chunked pull)", tag_keys=("path",), component="serve")
_def("rtpu_serve_kv_transfers_total", "counter",
     "KV-block batches shipped prefill -> decode, by path",
     tag_keys=("path",), component="serve")
_def("rtpu_serve_kv_transfer_seconds", "histogram",
     "wall time of one KV-block batch transfer (prefill-side ship for "
     "send, decode-side fetch for recv), by path",
     tag_keys=("path",), boundaries=_LAT_FAST, component="serve")

# multi-model serving plane (ISSUE 16): arena-paged model multiplexing
# + speculative decoding
_def("rtpu_serve_model_swaps_total", "counter",
     "model weight-set page events on this replica's ModelRegistry, by "
     "direction (in = materialized from the arena store; out = LRU-"
     "evicted under the resident-byte budget) — the lazy-paging proof "
     "the multiplexing A/B asserts on", tag_keys=("direction",),
     component="serve")
_def("rtpu_serve_model_resident", "gauge",
     "registered models on this replica by residency tier (hbm = "
     "materialized params; host = cold weights in the arena store; "
     "spilled = aged to the store's on-disk tier; sampled per registry "
     "snapshot)", tag_keys=("state",), component="serve")
_def("rtpu_serve_model_resident_bytes", "gauge",
     "bytes of materialized model params counted against this "
     "replica's serve_model_budget_bytes (delta variants charge only "
     "their unique leaves)", component="serve")
_def("rtpu_spec_rounds_total", "counter",
     "speculative-decoding verify rounds that carried at least one "
     "draft token (one batched verify_step_paged call per round)",
     component="serve")
_def("rtpu_spec_proposed_tokens_total", "counter",
     "draft tokens proposed to the target verifier", component="serve")
_def("rtpu_spec_accepted_tokens_total", "counter",
     "draft tokens accepted (equal to the target's own greedy chain); "
     "each round also emits one free target token, so tokens/round = "
     "accepted/rounds + 1", component="serve")
_def("rtpu_spec_fallbacks_total", "counter",
     "requests whose draft-acceptance EWMA collapsed below "
     "spec_accept_floor and fell back to plain decode permanently",
     component="serve")


# ---------------------------------------------------------------------------
# instantiation
# ---------------------------------------------------------------------------

_instances_lock = threading.Lock()
_instances: Dict[str, object] = {}


def get(name: str):
    """The live metric instance for a built-in definition.

    Instances are cached per process; if the registry was cleared since
    (tests), a fresh instance is created and re-registered — the merge
    semantics in util/metrics make concurrent creators share storage.
    Hot paths should cache the returned object (and pre-sorted tag keys)
    themselves; this lookup is for wiring, not per-event use.
    """
    from ray_tpu.util import metrics

    d = _DEFS[name]
    inst = _instances.get(name)
    if inst is not None and metrics.registered(name) is inst:
        return inst
    with _instances_lock:
        inst = _instances.get(name)
        if inst is not None and metrics.registered(name) is inst:
            return inst
        if d.kind == "counter":
            inst = metrics.Counter(name, d.help, tag_keys=d.tag_keys)
        elif d.kind == "gauge":
            inst = metrics.Gauge(name, d.help, tag_keys=d.tag_keys)
        else:
            inst = metrics.Histogram(name, d.help,
                                     boundaries=list(d.boundaries or ()),
                                     tag_keys=d.tag_keys)
        _instances[name] = inst
        return inst


def all_defs() -> List[MetricDef]:
    return list(_DEFS.values())


def lookup(name: str) -> Optional[MetricDef]:
    return _DEFS.get(name)


# ---------------------------------------------------------------------------
# docs generation (README "Built-in metrics reference")
# ---------------------------------------------------------------------------

MD_BEGIN = "<!-- metric-defs:begin (generated; do not edit by hand) -->"
MD_END = "<!-- metric-defs:end -->"


def markdown_table() -> str:
    """The generated metrics reference, fenced by markers so a test can
    assert the README copy matches this registry exactly."""
    lines = [MD_BEGIN,
             f"{len(_DEFS)} built-in metrics "
             "(generated by `python -m ray_tpu.util.metric_defs "
             "--markdown`):", "",
             "| Metric | Type | Labels | Help |",
             "|---|---|---|---|"]
    for d in _DEFS.values():
        labels = ", ".join(d.tag_keys) if d.tag_keys else "—"
        lines.append(f"| `{d.name}` | {d.kind} | {labels} | "
                     f"{d.help} |")
    lines.append(MD_END)
    return "\n".join(lines)


def _main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        description="built-in metric registry tools")
    p.add_argument("--markdown", action="store_true",
                   help="print the generated metrics reference table")
    p.add_argument("--check", metavar="README",
                   help="verify README's fenced table matches the "
                        "registry (exit 1 on drift)")
    p.add_argument("--update", metavar="README",
                   help="rewrite README's fenced table in place")
    args = p.parse_args(argv)
    table = markdown_table()
    if args.markdown:
        print(table)
        return 0
    if args.check or args.update:
        path = args.check or args.update
        with open(path) as f:
            text = f.read()
        start, end = text.find(MD_BEGIN), text.find(MD_END)
        if start == -1 or end == -1:
            print(f"{path}: no generated-table markers found")
            return 1
        current = text[start:end + len(MD_END)]
        if args.check:
            if current != table:
                print(f"{path}: metrics reference table is stale — run "
                      f"python -m ray_tpu.util.metric_defs --update "
                      f"{path}")
                return 1
            print(f"{path}: metrics reference table is up to date")
            return 0
        with open(path, "w") as f:
            f.write(text[:start] + table + text[end + len(MD_END):])
        print(f"{path}: metrics reference table rewritten "
              f"({len(_DEFS)} metrics)")
        return 0
    p.print_help()
    return 2


if __name__ == "__main__":
    raise SystemExit(_main())
