"""Distributed tracing: W3C-propagated spans + the per-process span ring.

Role analog: ``python/ray/util/tracing/tracing_helper.py`` — the reference
wraps task submission/execution in OpenTelemetry spans and propagates the
context inside the task spec (``_DictPropagator``). This image ships only
the ``opentelemetry`` API (no SDK), so spans are recorded natively in the
OTLP-compatible shape (trace_id/span_id/parent hex ids, epoch-nano
timestamps, attributes).

Recording plane (the trace analog of the metrics federation): every
process records finished spans into a bounded in-memory RING
(``RTPU_TRACE_RING`` entries; overflow increments
``rtpu_trace_spans_dropped_total``). Collection drains the ring in
batches that ride the EXISTING channels — workers push over the control
pipe (like the metric delta push), node daemons' spans (their own + their
workers') ride the GCS heartbeat, and the head pulls at query/export time
— landing in the head-side :class:`ray_tpu.util.trace_store.TraceStore`
served at ``/api/traces`` and ``state.list_spans()``. When
``RTPU_TRACE_FILE`` is set explicitly, spans are ALSO appended there as
JSON lines (debug / single-process use); there is no default scattered
``traces.jsonl`` anymore. A configured OTel SDK still receives every span
through ``opentelemetry.trace``.

Enable: ``ray_tpu.util.tracing.enable_tracing()`` on the driver — live
workers learn over their control pipe, daemons/GCS over the cluster
KV + ``tracing`` pubsub channel (failpoints-style push; late joiners pull
the KV at registration) — or the ``RTPU_TRACING=1`` env var before
spawn. ``RTPU_TRACING=0`` is the kill switch. Disabled cost of
``span()``/``tracing_enabled()`` is one dict get — no lock, no clock.

Span names (``<layer>::<what>``; the graftlint ``tracing-span-names``
rule keeps this catalog and the call sites bidirectionally in sync —
``<...>`` marks a dynamic suffix behind a literal prefix)::

    submit::<task>          task/actor-call submission, origin process
    driver.submit::<task>   driver control-plane CPU handling a submit
    execute::<task>         worker-side task/actor-method execution
    dag::execute            compiled-DAG invocation admission (driver)
    dag::stage              one compiled-DAG stage method inside an actor
    serve.handle::request   end-to-end serve request (manual span)
    serve.handle::route     replica selection + dispatch in the handle
    serve.replica::execute  user callable execution inside the replica
    serve.proxy::request    HTTP proxy unary request (manual span)
    serve.proxy::stream     HTTP proxy streaming response (manual span)
    serve.llm::queue        LLM admission wait to first token (manual)
    serve.llm::stream       LLM token-stream lifetime (manual span)
    serve.disagg::request   end-to-end disaggregated request (manual)
    serve.disagg::prefill   prefill-pool call + KV-block ship (manual)
    serve.disagg::decode    decode-pool adopt + token stream (manual)
    data.exchange::map      streaming-exchange partition task body
    data.exchange::reduce   streaming-exchange reducer block ingest
    train::step             one optimizer step (manual span)
    train::compile          one XLA compile event (manual span)
    device::compile         one registered-program XLA compile/retrace
    serve::step             one serve engine decode step (manual span)
    rllib::update           one learner update dispatch (manual span)
    lock::<name>            contended lock wait >= 1 ms (manual span)
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

# Span-id generation + pid stamping WITHOUT per-span syscalls: on this
# class of box (gVisor-style kernel) every syscall costs ~30 µs, so
# secrets.token_hex (urandom) and os.getpid per span would triple the
# span cost all by themselves. Trace ids need uniqueness, not
# cryptographic strength: one urandom seeds a process-local PRNG, the
# pid is cached, and an at-fork hook resets both so forked children
# (zygote workers) can never replay the parent's id stream.
_idgen: Dict[str, Any] = {"rng": None, "pid": 0}


def _idgen_init() -> None:
    import random as _random

    pid = os.getpid()
    seed = (int.from_bytes(os.urandom(16), "big")
            ^ (pid << 64) ^ time.time_ns())
    _idgen["rng"] = _random.Random(seed)
    _idgen["pid"] = pid


if hasattr(os, "register_at_fork"):
    os.register_at_fork(
        after_in_child=lambda: _idgen.update(rng=None, pid=0))


def _rand_hex(nbytes: int) -> str:
    rng = _idgen["rng"]
    if rng is None:
        _idgen_init()
        rng = _idgen["rng"]
    return "%0*x" % (nbytes * 2, rng.getrandbits(nbytes * 8))


def _pid() -> int:
    if _idgen["rng"] is None:
        _idgen_init()
    return _idgen["pid"]

#: cluster-wide arming rides the GCS KV + pubsub (failpoints pattern)
KV_NAMESPACE = "__tracing__"
KV_KEY = "spec"
CHANNEL = "tracing"

_lock = threading.Lock()
# _state["enabled"] doubles as the hot-path cache: None = unresolved,
# read WITHOUT the lock on every span()/tracing_enabled() call (a dict
# get under the GIL; tests reset it to None to force re-resolution).
_state = {"enabled": None, "path": None, "fd": None}
_ctx = threading.local()  # current (trace_id, span_id)

# bounded span ring (the recording side of the trace plane)
_ring: "deque[Dict[str, Any]]" = deque()
_ring_cap: Optional[int] = None
_dropped = 0
_dropped_counted = 0  # drops already settled into the builtin counter

# lazily-bound builtin counters; never allowed to fail a span
_m = {"spans": None, "dropped": None, "pushes": None}


def _metric(which: str):
    from ray_tpu.util import metric_defs, metrics

    names = {"spans": "rtpu_trace_spans_total",
             "dropped": "rtpu_trace_spans_dropped_total",
             "pushes": "rtpu_trace_push_batches_total"}
    inst = _m[which]
    if inst is None or metrics.registered(names[which]) is not inst:
        inst = _m[which] = metric_defs.get(names[which])
    return inst


def _resolve() -> bool:
    with _lock:
        if _state["enabled"] is None:
            _state["enabled"] = os.environ.get("RTPU_TRACING", "0") == "1"
            if _state["enabled"]:
                _state["path"] = os.environ.get("RTPU_TRACE_FILE", "")
        return _state["enabled"]


def tracing_enabled() -> bool:
    e = _state["enabled"]
    if e is None:
        return _resolve()
    return e


def _ring_capacity() -> int:
    global _ring_cap
    if _ring_cap is None:
        try:
            from ray_tpu import config

            _ring_cap = max(16, int(config.get("trace_ring")))
        except Exception:
            _ring_cap = 8192
    return _ring_cap


def _retire_zygote() -> None:
    """The zygote fork-server's env snapshot predates an arming flip, so
    retire it — the next spawn relaunches it with the current tracing env
    (otherwise forked workers would silently never record / keep
    recording)."""
    try:
        from ray_tpu.core import runtime as _rt_mod

        rt = _rt_mod._runtime
        if rt is not None and getattr(rt, "is_driver", False):
            with rt._zygote_lock:
                if rt._zygote_obj is not None:
                    rt._zygote_obj.close()
                    rt._zygote_obj = None
    except Exception:
        pass


def push_spec() -> Dict[str, Any]:
    """The arming payload shipped to workers/daemons (pipe + pubsub/KV)."""
    return {"enabled": bool(tracing_enabled()),
            "file": os.environ.get("RTPU_TRACE_FILE", "")}


def apply_remote(payload: Dict[str, Any]) -> None:
    """Apply a driver-pushed arming payload in THIS process (worker pipe
    message / daemon pubsub / KV late-join sync)."""
    enabled = bool(payload.get("enabled"))
    os.environ["RTPU_TRACING"] = "1" if enabled else "0"
    f = payload.get("file") or ""
    if f:
        os.environ["RTPU_TRACE_FILE"] = f
    with _lock:
        _state["enabled"] = enabled
        _state["path"] = f or os.environ.get("RTPU_TRACE_FILE", "")
        _state["fd"] = None


def broadcast_local(rt, payload: Optional[Dict[str, Any]]) -> None:
    """Push an arming payload to every live worker of ``rt`` and remember
    it so workers spawned later receive it on dial-back (mirrors
    failpoints._broadcast_local)."""
    if not getattr(rt, "is_driver", False):
        return
    rt._trace_push = payload
    for ws in list(getattr(rt, "workers", {}).values()):
        if ws.status == "dead" or ws.conn is None:
            continue
        try:
            ws.send(("trace", payload))
        except Exception:
            pass


def _broadcast(payload: Dict[str, Any]) -> None:
    """Local workers + cluster-wide distribution of an arming flip."""
    _retire_zygote()
    try:
        from ray_tpu.core import runtime as _rt_mod

        rt = _rt_mod._runtime
    except Exception:
        rt = None
    if rt is None or not getattr(rt, "is_driver", False):
        return
    broadcast_local(rt, payload)
    cluster = getattr(rt, "cluster", None)
    if cluster is not None:
        try:
            cluster.kv_op("put", KV_KEY, json.dumps(payload).encode(),
                          KV_NAMESPACE, True)
            cluster.gcs.call("publish", CHANNEL, payload, timeout=10)
        except Exception:
            pass


def enable_tracing(trace_file: Optional[str] = None) -> None:
    """Turn on span recording in THIS process, its live workers (control
    pipe push), workers spawned after this call (env), and — in cluster
    mode — every daemon and ITS workers (GCS KV + ``tracing`` pubsub;
    late joiners pull the KV at registration)."""
    os.environ["RTPU_TRACING"] = "1"
    if trace_file:
        os.environ["RTPU_TRACE_FILE"] = trace_file
    with _lock:
        _state["enabled"] = True
        _state["path"] = os.environ.get("RTPU_TRACE_FILE", "")
        _state["fd"] = None
    _broadcast(push_spec())


def disable_tracing() -> None:
    """The runtime counterpart of ``RTPU_TRACING=0``: stop recording in
    this process and everywhere :func:`enable_tracing` reaches."""
    os.environ["RTPU_TRACING"] = "0"
    with _lock:
        _state["enabled"] = False
        _state["fd"] = None
    _broadcast(push_spec())


def sync_from_kv(kv_get) -> None:
    """Pull + apply the cluster-wide arming payload (late joiners /
    re-registration). ``kv_get(key, namespace) -> Optional[bytes]``."""
    try:
        blob = kv_get(KV_KEY, KV_NAMESPACE)
    except Exception:
        return
    if blob:
        try:
            apply_remote(json.loads(blob.decode()))
        except Exception:
            pass


def _trace_path() -> str:
    return _state["path"] or ""


def _record(rec: Dict[str, Any]) -> None:
    """Land one finished span: ring (always), explicit trace file (when
    configured), OTel mirror (when an SDK is installed). The builtin
    counters are batched into :func:`drain_ring` — a per-span metric-lock
    hop would double the span cost for a number nobody reads per-span."""
    global _dropped
    with _lock:
        if len(_ring) >= _ring_capacity():
            _ring.popleft()
            _dropped += 1
        _ring.append(rec)
    if _state["path"]:
        _emit_file(rec)
    _mirror_to_otel(rec["name"], rec)


def _emit_file(rec: Dict[str, Any]) -> None:
    line = json.dumps(rec) + "\n"
    try:
        with _lock:
            fd = _state["fd"]
            if fd is None:
                fd = os.open(_trace_path(),
                             os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
                _state["fd"] = fd
        os.write(fd, line.encode())  # O_APPEND: atomic for short lines
    except Exception:
        pass


def drain_ring(max_n: Optional[int] = None) -> List[Dict[str, Any]]:
    """Pop up to ``max_n`` (default: all) spans from this process's ring —
    the collection hop (worker pipe push / daemon heartbeat / head query).
    Spans leave the ring exactly once. The recorded/dropped counters are
    settled here, in one batch per drain."""
    global _dropped_counted
    out: List[Dict[str, Any]] = []
    with _lock:
        n = len(_ring) if max_n is None else min(max_n, len(_ring))
        for _ in range(n):
            out.append(_ring.popleft())
        dropped_new = _dropped - _dropped_counted
        _dropped_counted = _dropped
    try:
        if out:
            _metric("spans")._inc_key((), len(out))
        if dropped_new:
            _metric("dropped")._inc_key((), dropped_new)
            _metric("spans")._inc_key((), dropped_new)
    except Exception:
        pass
    return out


def ring_stats() -> Dict[str, int]:
    with _lock:
        return {"len": len(_ring), "dropped": _dropped,
                "capacity": _ring_capacity()}


def note_push() -> None:
    """Count one shipped span batch (worker pipe / heartbeat)."""
    try:
        _metric("pushes")._inc_key(())
    except Exception:
        pass


def _reset_for_tests() -> None:
    """Restore module state so a test can re-resolve from a patched env."""
    global _ring_cap, _dropped, _dropped_counted
    with _lock:
        _state["enabled"] = None
        _state["path"] = None
        _state["fd"] = None
        _ring.clear()
        _ring_cap = None
        _dropped = 0
        _dropped_counted = 0
    _ctx.ids = None


def current_traceparent() -> Optional[str]:
    """W3C traceparent for the active span ('00-<trace>-<span>-01')."""
    cur = getattr(_ctx, "ids", None)
    if not cur:
        return None
    return f"00-{cur[0]}-{cur[1]}-01"


def _parse_traceparent(tp: Optional[str]):
    if not tp:
        return None, None
    parts = tp.split("-")
    if len(parts) != 4:
        return None, None
    return parts[1], parts[2]


def _resolve_parent(parent: Optional[str]):
    """(trace_id, parent_span_id) from an explicit traceparent or this
    thread's active span; fresh trace when neither exists."""
    if parent is not None:
        trace_id, parent_span = _parse_traceparent(parent)
    else:
        cur = getattr(_ctx, "ids", None)
        trace_id, parent_span = (cur if cur else (None, None))
    if trace_id is None:
        trace_id = _rand_hex(16)
    return trace_id, parent_span


@contextmanager
def span(name: str, attributes: Optional[Dict[str, Any]] = None,
         parent: Optional[str] = None):
    """Record one span. ``parent``: a traceparent string from another
    process (task spec propagation); defaults to this thread's active
    span. Yields the span's traceparent for manual propagation.

    The span context is THREAD-LOCAL: never hold this context manager
    open across a ``yield`` or hand its body to another thread — use
    :func:`manual_span` / :func:`record_span` there (the graftlint
    ``tracing-context-capture`` rule enforces this)."""
    if not tracing_enabled():
        yield None
        return
    trace_id, parent_span = _resolve_parent(parent)
    span_id = _rand_hex(8)
    prev = getattr(_ctx, "ids", None)
    _ctx.ids = (trace_id, span_id)
    start = time.time_ns()
    err = None
    try:
        yield f"00-{trace_id}-{span_id}-01"
    except BaseException as e:
        err = repr(e)
        raise
    finally:
        _ctx.ids = prev
        rec = {
            "name": name,
            "trace_id": trace_id,
            "span_id": span_id,
            "parent_span_id": parent_span,
            "start_time_unix_nano": start,
            "end_time_unix_nano": time.time_ns(),
            "attributes": {**(attributes or {}),
                           "process.pid": _pid()},
        }
        if err:
            rec["status"] = {"code": "ERROR", "message": err[:300]}
        _record(rec)


class ManualSpan:
    """A long-lived span finished explicitly — for request lifetimes that
    cross threads/yields where the thread-local ``span()`` context cannot
    be held open (serve request end-to-end, LLM token streams)."""

    __slots__ = ("name", "trace_id", "span_id", "parent_span_id",
                 "start", "attributes", "_done")

    def __init__(self, name: str, attributes: Optional[Dict[str, Any]],
                 parent: Optional[str]):
        self.name = name
        self.trace_id, self.parent_span_id = _resolve_parent(parent)
        self.span_id = _rand_hex(8)
        self.start = time.time_ns()
        self.attributes = dict(attributes or {})
        self._done = False

    @property
    def traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"

    def finish(self, attributes: Optional[Dict[str, Any]] = None,
               error: Optional[str] = None) -> None:
        if self._done:
            return
        self._done = True
        rec = {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_span_id": self.parent_span_id,
            "start_time_unix_nano": self.start,
            "end_time_unix_nano": time.time_ns(),
            "attributes": {**self.attributes, **(attributes or {}),
                           "process.pid": _pid()},
        }
        if error:
            rec["status"] = {"code": "ERROR", "message": error[:300]}
        _record(rec)


@contextmanager
def context(parent: Optional[str]):
    """Adopt an existing traceparent as this thread's active span context
    WITHOUT recording a new span — the blessed re-entry point for work
    continued on another thread or after a manual span (a serve proxy
    parenting the handle's request span under its own, a generator
    resuming inside its stream's trace)."""
    if parent is None or not tracing_enabled():
        yield
        return
    trace_id, span_id = _parse_traceparent(parent)
    if trace_id is None:
        yield
        return
    prev = getattr(_ctx, "ids", None)
    _ctx.ids = (trace_id, span_id)
    try:
        yield
    finally:
        _ctx.ids = prev


def manual_span(name: str, attributes: Optional[Dict[str, Any]] = None,
                parent: Optional[str] = None) -> Optional[ManualSpan]:
    """Start a :class:`ManualSpan` (None when tracing is disabled — the
    disabled path stays one dict get)."""
    if not tracing_enabled():
        return None
    return ManualSpan(name, attributes, parent)


def record_span(name: str, start_ns: int, end_ns: int,
                attributes: Optional[Dict[str, Any]] = None,
                parent: Optional[str] = None) -> None:
    """One-shot span with caller-supplied timestamps (train telemetry,
    lock-contention slices — places that know the duration after the
    fact)."""
    if not tracing_enabled():
        return
    trace_id, parent_span = _resolve_parent(parent)
    rec = {
        "name": name,
        "trace_id": trace_id,
        "span_id": _rand_hex(8),
        "parent_span_id": parent_span,
        "start_time_unix_nano": int(start_ns),
        "end_time_unix_nano": int(end_ns),
        "attributes": {**(attributes or {}), "process.pid": _pid()},
    }
    _record(rec)


_otel_tracer: Any = None  # None = unresolved; False = unavailable/no-op


def _mirror_to_otel(name: str, rec: Dict[str, Any]) -> None:
    """If a real OTel SDK is configured in this process, replay the span
    (with the REAL timestamps) so external exporters see the same data.
    The tracer is resolved once — a failed import must not tax every span."""
    global _otel_tracer
    if _otel_tracer is False:
        return
    if _otel_tracer is None:
        try:
            from opentelemetry import trace as ot

            tracer = ot.get_tracer("ray_tpu")
            # API-without-SDK yields NonRecording spans: disable the mirror
            probe = tracer.start_span("rtpu-probe")
            recording = probe.is_recording()
            probe.end()
            _otel_tracer = tracer if recording else False
        except Exception:
            _otel_tracer = False
        if _otel_tracer is False:
            return
    try:
        s = _otel_tracer.start_span(
            name, start_time=rec["start_time_unix_nano"])
        for k, v in rec["attributes"].items():
            s.set_attribute(k, v)
        s.end(end_time=rec["end_time_unix_nano"])
    except Exception:
        pass


def read_trace_file(path: Optional[str] = None) -> list:
    out = []
    try:
        with open(path or _trace_path()) as f:
            for line in f:
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    except OSError:
        pass
    return out
