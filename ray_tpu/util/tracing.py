"""Distributed tracing: W3C-propagated spans for tasks and actor calls.

Role analog: ``python/ray/util/tracing/tracing_helper.py`` — the reference
wraps task submission/execution in OpenTelemetry spans and propagates the
context inside the task spec (``_DictPropagator``). This image ships only
the ``opentelemetry`` API (no SDK), so spans are recorded natively in the
OTLP-compatible shape (trace_id/span_id/parent hex ids, epoch-nano
timestamps, attributes) and written as JSON lines to
``<session_dir>/traces.jsonl``; the W3C ``traceparent`` string rides the
task spec, so worker-side execute spans join the driver's trace across
process boundaries. When a full OTel SDK IS installed, the same spans are
mirrored through ``opentelemetry.trace`` so any configured exporter
receives them.

Enable: ``ray_tpu.util.tracing.enable_tracing()`` on the driver (workers
inherit via ``RTPU_TRACING``), or the env var alone.
"""

from __future__ import annotations

import json
import os
import secrets
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Optional

_lock = threading.Lock()
_state = {"enabled": None, "path": None, "fd": None}
_ctx = threading.local()  # current (trace_id, span_id)


def _resolve() -> bool:
    with _lock:
        if _state["enabled"] is None:
            _state["enabled"] = os.environ.get("RTPU_TRACING", "0") == "1"
            if _state["enabled"]:
                _state["path"] = os.environ.get("RTPU_TRACE_FILE", "")
        return _state["enabled"]


def enable_tracing(trace_file: Optional[str] = None) -> None:
    """Turn on span recording in THIS process and (via env) in workers
    spawned after this call. If the zygote fork-server is already up its
    env snapshot predates this call, so it is retired here — the next
    spawn relaunches it with tracing env (otherwise forked workers would
    silently never record)."""
    os.environ["RTPU_TRACING"] = "1"
    if trace_file:
        os.environ["RTPU_TRACE_FILE"] = trace_file
    with _lock:
        _state["enabled"] = True
        _state["path"] = os.environ.get("RTPU_TRACE_FILE", "")
        _state["fd"] = None
    try:
        from ray_tpu.core import runtime as _rt_mod

        rt = _rt_mod._runtime
        if rt is not None:
            with rt._zygote_lock:
                if rt._zygote_obj is not None:
                    rt._zygote_obj.close()
                    rt._zygote_obj = None
    except Exception:
        pass


def tracing_enabled() -> bool:
    return bool(_resolve())


def _trace_path() -> str:
    if _state["path"]:
        return _state["path"]
    # default: the session dir when a runtime is up, else /tmp
    try:
        from ray_tpu.core.runtime import _get_runtime

        rt = _get_runtime()
        base = getattr(rt, "session_dir", None) or f"/tmp/rtpu-{rt.session}"
    except Exception:
        base = "/tmp"
    return os.path.join(base, "traces.jsonl")


def _emit(rec: Dict[str, Any]) -> None:
    line = json.dumps(rec) + "\n"
    try:
        with _lock:
            fd = _state["fd"]
            if fd is None:
                fd = os.open(_trace_path(),
                             os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
                _state["fd"] = fd
        os.write(fd, line.encode())  # O_APPEND: atomic for short lines
    except Exception:
        pass


def current_traceparent() -> Optional[str]:
    """W3C traceparent for the active span ('00-<trace>-<span>-01')."""
    cur = getattr(_ctx, "ids", None)
    if not cur:
        return None
    return f"00-{cur[0]}-{cur[1]}-01"


def _parse_traceparent(tp: Optional[str]):
    if not tp:
        return None, None
    parts = tp.split("-")
    if len(parts) != 4:
        return None, None
    return parts[1], parts[2]


@contextmanager
def span(name: str, attributes: Optional[Dict[str, Any]] = None,
         parent: Optional[str] = None):
    """Record one span. ``parent``: a traceparent string from another
    process (task spec propagation); defaults to this thread's active
    span. Yields the span's traceparent for manual propagation."""
    if not _resolve():
        yield None
        return
    if parent is not None:
        trace_id, parent_span = _parse_traceparent(parent)
    else:
        cur = getattr(_ctx, "ids", None)
        trace_id, parent_span = (cur if cur else (None, None))
    if trace_id is None:
        trace_id = secrets.token_hex(16)
    span_id = secrets.token_hex(8)
    prev = getattr(_ctx, "ids", None)
    _ctx.ids = (trace_id, span_id)
    start = time.time_ns()
    err = None
    try:
        yield f"00-{trace_id}-{span_id}-01"
    except BaseException as e:
        err = repr(e)
        raise
    finally:
        _ctx.ids = prev
        rec = {
            "name": name,
            "trace_id": trace_id,
            "span_id": span_id,
            "parent_span_id": parent_span,
            "start_time_unix_nano": start,
            "end_time_unix_nano": time.time_ns(),
            "attributes": {**(attributes or {}),
                           "process.pid": os.getpid()},
        }
        if err:
            rec["status"] = {"code": "ERROR", "message": err[:300]}
        _emit(rec)
        _mirror_to_otel(name, rec)


_otel_tracer: Any = None  # None = unresolved; False = unavailable/no-op


def _mirror_to_otel(name: str, rec: Dict[str, Any]) -> None:
    """If a real OTel SDK is configured in this process, replay the span
    (with the REAL timestamps) so external exporters see the same data.
    The tracer is resolved once — a failed import must not tax every span."""
    global _otel_tracer
    if _otel_tracer is False:
        return
    if _otel_tracer is None:
        try:
            from opentelemetry import trace as ot

            tracer = ot.get_tracer("ray_tpu")
            # API-without-SDK yields NonRecording spans: disable the mirror
            probe = tracer.start_span("rtpu-probe")
            recording = probe.is_recording()
            probe.end()
            _otel_tracer = tracer if recording else False
        except Exception:
            _otel_tracer = False
        if _otel_tracer is False:
            return
    try:
        s = _otel_tracer.start_span(
            name, start_time=rec["start_time_unix_nano"])
        for k, v in rec["attributes"].items():
            s.set_attribute(k, v)
        s.end(end_time=rec["end_time_unix_nano"])
    except Exception:
        pass


def read_trace_file(path: Optional[str] = None) -> list:
    out = []
    try:
        with open(path or _trace_path()) as f:
            for line in f:
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    except OSError:
        pass
    return out
