from ray_tpu.util.placement_group import (
    placement_group,
    remove_placement_group,
    PlacementGroup,
)

__all__ = ["placement_group", "remove_placement_group", "PlacementGroup"]
