"""Head-side trace store + cross-process analyzers (timeline, critical path).

The receiver half of the trace plane (``util/tracing.py`` is the
recording half): every collected span lands here with origin labels
(``node_id`` / ``worker_id`` / ``component``), the exact shape the
metrics :class:`~ray_tpu.util.metrics.FederationStore` gives samples.
Reference role: the GcsTaskManager/timeline pipeline plus the Ray
paper's end-to-end task timeline (arxiv 1712.05889) — one queryable
store that can answer "where did this request's wall time go?" across
process boundaries.

Three consumers:

- ``state.list_spans()`` / ``/api/traces`` — raw span query;
- :func:`build_perfetto` — the unified Chrome-trace/Perfetto document
  (spans + flight-recorder task slices + lock-contention waits + TPU
  step telemetry, one track per node/worker) for ``ray_tpu timeline
  --perfetto``;
- :func:`critical_path_for_trace` / :func:`critical_path_for_tasks` —
  ``state.summarize_critical_path()`` / ``/api/critical_path``:
  attribute end-to-end wall time to per-process segments so the
  multi-client control-plane cost prints as a breakdown instead of a
  bench inference.
"""

from __future__ import annotations

import threading
from collections import deque
from itertools import islice
from typing import Any, Dict, List, Optional, Tuple


class TraceStore:
    """Bounded store of collected spans with origin labels.

    Appends carry an absolute sequence number so the cluster adapter can
    ship deltas over the heartbeat with an acked cursor (the same
    cursor+dedup contract the task-event pipeline uses); eviction past
    the cap silently advances the readable window."""

    def __init__(self, cap: Optional[int] = None):
        if cap is None:
            try:
                from ray_tpu import config

                cap = int(config.get("trace_store_max"))
            except Exception:
                cap = 65536
        self._lock = threading.Lock()
        self._dq: "deque[Dict[str, Any]]" = deque(maxlen=max(64, cap))
        self._total = 0  # spans ever appended (absolute sequence)

    def ingest(self, spans: List[Dict[str, Any]],
               labels: Optional[Dict[str, str]] = None) -> None:
        if not spans:
            return
        with self._lock:
            for s in spans:
                if labels:
                    s = dict(s)
                    for k, v in labels.items():
                        s.setdefault(k, v)
                self._dq.append(s)
                self._total += 1

    def snapshot(self, limit: Optional[int] = None) -> List[Dict[str, Any]]:
        with self._lock:
            out = list(self._dq)
        return out[-limit:] if limit else out

    def since(self, cursor: int, max_n: int = 1000
              ) -> Tuple[List[Dict[str, Any]], int]:
        """(batch, start) where ``start`` is the absolute index of
        batch[0] (>= cursor when eviction skipped spans). Advance the
        cursor to ``start + len(batch)`` only after the receiver acked."""
        with self._lock:
            start_abs = self._total - len(self._dq)
            i = max(0, cursor - start_abs)
            batch = list(islice(self._dq, i, i + max_n))
            return batch, start_abs + i

    def __len__(self) -> int:
        with self._lock:
            return len(self._dq)

    def clear(self) -> None:
        with self._lock:
            self._dq.clear()


# ---------------------------------------------------------------------------
# critical-path analysis
# ---------------------------------------------------------------------------


def _span_proc(s: Dict[str, Any]) -> str:
    """Stable per-process label for a span's origin."""
    wid = s.get("worker_id")
    if wid:
        return f"worker:{wid}"
    nid = s.get("node_id")
    comp = s.get("component") or "driver"
    if nid:
        return f"{comp}:{nid}"
    pid = (s.get("attributes") or {}).get("process.pid")
    return f"pid:{pid}" if pid else comp


def critical_path_for_trace(spans: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Attribute one trace's end-to-end wall time to per-process segments.

    Sweep over the union of span boundaries; each elementary interval is
    charged to the DEEPEST (latest-starting) span covering it, labeled
    ``<name>@<process>``; intervals no span covers are transit/queue gaps,
    labeled after the spans they sit between. Segment times sum EXACTLY to
    the end-to-end time, so a serve request's route->queue->execute->stream
    chain reconciles against its measured latency."""
    spans = [s for s in spans
             if s.get("start_time_unix_nano") is not None
             and s.get("end_time_unix_nano") is not None]
    if not spans:
        return {"spans": 0, "end_to_end_ms": 0.0, "segments": {},
                "dominant": None}
    spans.sort(key=lambda s: s["start_time_unix_nano"])
    t0 = min(s["start_time_unix_nano"] for s in spans)
    t1 = max(s["end_time_unix_nano"] for s in spans)
    bounds = sorted({b for s in spans
                     for b in (s["start_time_unix_nano"],
                               s["end_time_unix_nano"])})
    segments: Dict[str, float] = {}
    last_named = None
    for a, b in zip(bounds, bounds[1:]):
        if b <= a:
            continue
        covering = [s for s in spans
                    if s["start_time_unix_nano"] <= a
                    and s["end_time_unix_nano"] >= b]
        if covering:
            # deepest = latest start, then shortest extent
            s = max(covering, key=lambda s: (s["start_time_unix_nano"],
                                             -s["end_time_unix_nano"]))
            label = f"{s['name']}@{_span_proc(s)}"
            last_named = s["name"]
        else:
            nxt = next((s["name"] for s in spans
                        if s["start_time_unix_nano"] >= b), None)
            label = f"gap:{last_named or 'start'}->{nxt or 'end'}"
        segments[label] = segments.get(label, 0.0) + (b - a) / 1e6
    total_ms = (t1 - t0) / 1e6
    ordered = dict(sorted(segments.items(), key=lambda kv: -kv[1]))
    out = {
        "trace_id": spans[0].get("trace_id"),
        "spans": len(spans),
        "end_to_end_ms": round(total_ms, 3),
        "segments": {k: {"ms": round(v, 3),
                         "pct": round(100.0 * v / total_ms, 1)
                         if total_ms else 0.0}
                     for k, v in ordered.items()},
        "dominant": next(iter(ordered), None),
    }
    return out


#: flight-recorder phases in lifecycle order (transit is the residual)
_TASK_PHASES = ("queue", "lease", "arg_fetch", "deserialize", "execute",
                "store_result")


def critical_path_for_tasks(ring_events: List[Dict[str, Any]],
                            spans: Optional[List[Dict[str, Any]]] = None
                            ) -> Dict[str, Any]:
    """Aggregate per-task critical path over the flight-recorder ring,
    augmented with driver-side control-plane CPU from submit spans when
    tracing was armed.

    Segments per task: ``driver_submit`` (submit::/driver.submit:: span
    self-time — the GIL-serialized driver CPU the multi-client inversion
    pays), the recorder's queue/lease/worker phases, and ``transit``
    (total minus everything attributed: pipe transit + driver done-path
    CPU). Means are per task; pct is of mean end-to-end."""
    if not ring_events:
        return {"mode": "tasks", "tasks": 0, "segments": {},
                "dominant": None}
    submit_ms: Dict[str, float] = {}
    for s in spans or ():
        name = s.get("name") or ""
        if not (name.startswith("submit::")
                or name.startswith("driver.submit::")):
            continue
        tid = (s.get("attributes") or {}).get("task_id")
        if not tid:
            continue
        dur = (s.get("end_time_unix_nano", 0)
               - s.get("start_time_unix_nano", 0)) / 1e6
        submit_ms[tid] = submit_ms.get(tid, 0.0) + max(0.0, dur)
    sums: Dict[str, float] = {}
    total_sum = 0.0
    n = 0
    for ev in ring_events:
        ph = ev.get("phases") or {}
        total = ph.get("total")
        if total is None:
            continue
        n += 1
        total_sum += total * 1e3
        attributed = 0.0
        for p in _TASK_PHASES:
            v = (ph.get(p) or 0.0) * 1e3
            sums[p] = sums.get(p, 0.0) + v
            attributed += v
        tid = ev.get("task_id")
        tid_hex = tid.hex() if isinstance(tid, bytes) else str(tid or "")
        drv = 0.0
        for key in (tid_hex, tid_hex[:16]):
            if key in submit_ms:
                drv = submit_ms[key]
                break
        else:
            # span attrs carry the FULL task id; ring may hold raw bytes
            for k, v in submit_ms.items():
                if tid_hex and (k.startswith(tid_hex)
                                or tid_hex.startswith(k)):
                    drv = v
                    break
        if drv:
            sums["driver_submit"] = sums.get("driver_submit", 0.0) + drv
            attributed += drv
        sums["transit"] = sums.get("transit", 0.0) + max(
            0.0, total * 1e3 - attributed)
    if not n:
        return {"mode": "tasks", "tasks": 0, "segments": {},
                "dominant": None}
    mean_total = total_sum / n
    ordered = dict(sorted(sums.items(), key=lambda kv: -kv[1]))
    return {
        "mode": "tasks",
        "tasks": n,
        "end_to_end_ms_mean": round(mean_total, 3),
        "segments": {k: {"mean_ms": round(v / n, 3),
                         "pct": round(100.0 * (v / n) / mean_total, 1)
                         if mean_total else 0.0}
                     for k, v in ordered.items()},
        "dominant": next(iter(ordered), None),
    }


def format_breakdown(result: Dict[str, Any]) -> str:
    """Human-readable table for CLI/experiment printing."""
    lines = []
    if result.get("mode") == "tasks":
        lines.append(f"critical path over {result.get('tasks', 0)} tasks "
                     f"(mean end-to-end "
                     f"{result.get('end_to_end_ms_mean', 0)} ms/task):")
        key = "mean_ms"
    else:
        lines.append(f"trace {result.get('trace_id', '?')}: "
                     f"{result.get('end_to_end_ms', 0)} ms end-to-end, "
                     f"{result.get('spans', 0)} spans:")
        key = "ms"
    for name, seg in (result.get("segments") or {}).items():
        lines.append(f"  {seg.get('pct', 0):6.1f}%  "
                     f"{seg.get(key, 0):10.3f} ms  {name}")
    if result.get("dominant"):
        lines.append(f"  dominant: {result['dominant']}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Perfetto / Chrome-trace export
# ---------------------------------------------------------------------------


def _origin_pid_tid(s: Dict[str, Any], pids: Dict[str, int],
                    names: Dict[int, str]) -> Tuple[int, str]:
    node = s.get("node_id") or "local"
    pid = pids.get(node)
    if pid is None:
        pid = pids[node] = len(pids) + 1
        names[pid] = f"node:{node}"
    attrs = s.get("attributes") or {}
    wid = s.get("worker_id")
    if wid:
        tid = f"worker:{wid}"
    else:
        ppid = attrs.get("process.pid")
        comp = s.get("component") or "proc"
        tid = f"{comp}:{ppid}" if ppid else comp
    if "program" in attrs:
        # device-plane slices (device::compile, serve::step,
        # rllib::update carry a ``program`` attribute): their own track
        # under the owning process row, so compile/step slices read as
        # one device timeline instead of interleaving with control-
        # plane spans
        tid = f"device[{tid}]"
    return pid, tid


def build_perfetto(spans: List[Dict[str, Any]],
                   timeline_events: Optional[List[Dict[str, Any]]] = None
                   ) -> Dict[str, Any]:
    """One Chrome-trace/Perfetto document merging collected spans (task
    submit/execute, serve chain, lock-contention waits, train steps) with
    the driver flight recorder's task-phase slices, on per-node process
    rows with per-worker thread tracks. Loads directly in
    ``ui.perfetto.dev`` / ``chrome://tracing``."""
    events: List[Dict[str, Any]] = []
    pids: Dict[str, int] = {}
    pnames: Dict[int, str] = {}
    for s in spans or ():
        start = s.get("start_time_unix_nano")
        end = s.get("end_time_unix_nano")
        if start is None or end is None:
            continue
        pid, tid = _origin_pid_tid(s, pids, pnames)
        name = s.get("name") or "span"
        cat = name.split("::", 1)[0] if "::" in name else "span"
        args = {k: v for k, v in (s.get("attributes") or {}).items()}
        args["trace_id"] = s.get("trace_id")
        events.append({"name": name, "ph": "X", "ts": start / 1e3,
                       "dur": max(0.001, (end - start) / 1e3),
                       "pid": pid, "tid": tid, "cat": cat, "args": args})
    for ev in timeline_events or ():
        node = ev.get("node") or "local"
        pid = pids.get(node)
        if pid is None:
            pid = pids[node] = len(pids) + 1
            pnames[pid] = f"node:{node}"
        e = dict(ev)
        e["pid"] = pid
        e["tid"] = f"worker:{ev.get('tid')}"
        e.setdefault("cat", "task")
        events.append(e)
    meta: List[Dict[str, Any]] = []
    for node, pid in pids.items():
        meta.append({"ph": "M", "name": "process_name", "pid": pid,
                     "args": {"name": pnames[pid]}})
    tids = {(e["pid"], e["tid"]) for e in events if e.get("ph") == "X"}
    for pid, tid in sorted(tids, key=str):
        meta.append({"ph": "M", "name": "thread_name", "pid": pid,
                     "tid": tid, "args": {"name": str(tid)}})
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}
