"""Placement groups: gang-reserve resource bundles.

Role analog: reference ``python/ray/util/placement_group.py`` (PACK/SPREAD/
STRICT_PACK/STRICT_SPREAD strategies; on a single node every strategy
reduces to reserving the bundles). On a TPU cluster a bundle maps naturally
to one slice host; SLICE_PACK reserves one bundle per host of a pod slice.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu.core.ids import PlacementGroupID

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD", "SLICE_PACK")


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]], strategy: str):
        self.id = pg_id
        self.bundles = bundles
        self.strategy = strategy

    @property
    def bundle_specs(self) -> List[Dict[str, float]]:
        return self.bundles

    @property
    def bundle_count(self) -> int:
        return len(self.bundles)

    def ready(self):
        """Returns an ObjectRef resolving once the group is reserved.
        Reservation is synchronous single-node, so this is immediate."""
        from ray_tpu.core.runtime import _get_runtime

        return _get_runtime().put(True)

    def wait(self, timeout_seconds: Optional[float] = None) -> bool:
        """True once every bundle holds a reservation.

        Local mode: creation was a synchronous reserve, so the group is
        ready by construction. Cluster mode: bundles can be PENDING
        re-placement after a node death (the creator adapter reschedules
        them) — poll the directory until every bundle has an assigned
        node (VERDICT r3: an unconditional True would silently lie the
        moment reservation became async)."""
        import time

        from ray_tpu.core.runtime import _get_runtime

        rt = _get_runtime()
        if rt.cluster is None:
            return self.id.binary() in rt.pgs
        deadline = (None if timeout_seconds is None
                    else time.monotonic() + timeout_seconds)
        while True:
            rpc_timeout = 10.0
            if deadline is not None:
                rpc_timeout = max(0.1, min(10.0,
                                           deadline - time.monotonic()))
            try:
                rec = rt.cluster.gcs.call("pg_get", self.id.binary(),
                                          timeout=rpc_timeout)
            except Exception:
                rec = None
            if rec is not None:
                assignments = rec.get("assignments") or []
                if assignments and all(a is not None for a in assignments):
                    return True
            if deadline is not None and time.monotonic() >= deadline:
                return False
            time.sleep(0.1)

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundles, self.strategy))


def placement_group(
    bundles: List[Dict[str, float]],
    strategy: str = "PACK",
    name: str = "",
    lifetime: Optional[str] = None,
) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"invalid strategy {strategy!r}; one of {VALID_STRATEGIES}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be a non-empty list of non-empty dicts")
    from ray_tpu.core.runtime import _get_runtime

    rt = _get_runtime()
    pg_id = rt.create_placement_group([{k: float(v) for k, v in b.items()} for b in bundles], strategy)
    return PlacementGroup(PlacementGroupID(pg_id), bundles, strategy)


def remove_placement_group(pg: PlacementGroup) -> None:
    from ray_tpu.core.runtime import _get_runtime

    _get_runtime().remove_placement_group(pg.id.binary())


def get_current_placement_group() -> Optional[PlacementGroup]:
    return None
