"""Placement groups: gang-reserve resource bundles.

Role analog: reference ``python/ray/util/placement_group.py`` (PACK/SPREAD/
STRICT_PACK/STRICT_SPREAD strategies; on a single node every strategy
reduces to reserving the bundles). On a TPU cluster a bundle maps naturally
to one slice host; SLICE_PACK reserves one bundle per host of a pod slice.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu.core.ids import PlacementGroupID

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD", "SLICE_PACK")


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]], strategy: str):
        self.id = pg_id
        self.bundles = bundles
        self.strategy = strategy

    @property
    def bundle_specs(self) -> List[Dict[str, float]]:
        return self.bundles

    @property
    def bundle_count(self) -> int:
        return len(self.bundles)

    def ready(self):
        """Returns an ObjectRef resolving once the group is reserved.
        Reservation is synchronous single-node, so this is immediate."""
        from ray_tpu.core.runtime import _get_runtime

        return _get_runtime().put(True)

    def wait(self, timeout_seconds: Optional[float] = None) -> bool:
        return True

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundles, self.strategy))


def placement_group(
    bundles: List[Dict[str, float]],
    strategy: str = "PACK",
    name: str = "",
    lifetime: Optional[str] = None,
) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"invalid strategy {strategy!r}; one of {VALID_STRATEGIES}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be a non-empty list of non-empty dicts")
    from ray_tpu.core.runtime import _get_runtime

    rt = _get_runtime()
    pg_id = rt.create_placement_group([{k: float(v) for k, v in b.items()} for b in bundles], strategy)
    return PlacementGroup(PlacementGroupID(pg_id), bundles, strategy)


def remove_placement_group(pg: PlacementGroup) -> None:
    from ray_tpu.core.runtime import _get_runtime

    _get_runtime().remove_placement_group(pg.id.binary())


def get_current_placement_group() -> Optional[PlacementGroup]:
    return None
