"""Node-label wire format: ``k=v,k=v`` env-var round-trip (one place so
spawners and workers can't drift)."""

from __future__ import annotations

from typing import Dict

ENV_VAR = "RTPU_NODE_LABELS"


def parse_labels(raw: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for part in (raw or "").split(","):
        if "=" in part:
            k, _, v = part.partition("=")
            out[k.strip()] = v.strip()
    return out


def format_labels(labels: Dict[str, str]) -> str:
    return ",".join(f"{k}={v}" for k, v in labels.items())
