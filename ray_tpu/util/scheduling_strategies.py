"""Scheduling strategies (reference ``python/ray/util/scheduling_strategies.py``)."""

from __future__ import annotations

from typing import Optional


class PlacementGroupSchedulingStrategy:
    def __init__(
        self,
        placement_group,
        placement_group_bundle_index: int = -1,
        placement_group_capture_child_tasks: bool = False,
    ):
        self.placement_group = placement_group
        self.placement_group_bundle_index = placement_group_bundle_index
        self.placement_group_capture_child_tasks = placement_group_capture_child_tasks


class NodeAffinitySchedulingStrategy:
    def __init__(self, node_id: str, soft: bool = False):
        self.node_id = node_id
        self.soft = soft


# Label match operators (reference ``util/scheduling_strategies.py``
# In/NotIn/Exists/DoesNotExist). Labels are set at node start
# (``init(labels=...)``, ``--labels`` on the daemon, RTPU_NODE_LABELS env)
# and are the TPU-targeting story for heterogeneous clusters: e.g.
# {"tpu-generation": "v5e", "slice-type": "pod"}.

class In:
    op = "in"

    def __init__(self, *values: str):
        self.values = tuple(str(v) for v in values)


class NotIn:
    op = "not_in"

    def __init__(self, *values: str):
        self.values = tuple(str(v) for v in values)


class Exists:
    op = "exists"
    values = ()


class DoesNotExist:
    op = "does_not_exist"
    values = ()


class NodeLabelSchedulingStrategy:
    """Schedule onto nodes matching label predicates (reference
    ``node_label_scheduling_policy.h`` role). ``hard`` predicates are
    requirements (no matching node -> the task fails with a scheduling
    error); ``soft`` predicates are preferences (matching nodes win ties,
    but any hard-matching node may run the task)."""

    def __init__(self, hard: Optional[dict] = None,
                 soft: Optional[dict] = None):
        self.hard = dict(hard or {})
        self.soft = dict(soft or {})
