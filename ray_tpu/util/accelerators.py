"""Public TPU helpers (reference ``ray.util.accelerators.tpu``:
``python/ray/util/accelerators/tpu.py:7,18``) plus pod-slice scheduling
helpers built on the head-resource pattern (SURVEY §2.6)."""

from __future__ import annotations

from typing import Any, Callable, List, Optional

from ray_tpu.accelerators.tpu import TPUAcceleratorManager, \
    pod_head_resource  # noqa: F401 — re-exported public API


def get_current_pod_name() -> Optional[str]:
    """Name of the TPU pod slice this host belongs to (None off-TPU)."""
    return TPUAcceleratorManager().get_current_pod_name()


def get_current_pod_worker_count() -> Optional[int]:
    """Number of hosts in this pod slice (None off-TPU)."""
    return TPUAcceleratorManager().get_current_pod_worker_count()


def get_num_tpu_chips_on_node() -> int:
    return TPUAcceleratorManager().get_current_node_num_accelerators()


def fan_out_per_host(fn: Callable, pod_name: str, num_hosts: int,
                     *args, **kwargs) -> List[Any]:
    """Launch ``fn`` once per slice host (each consuming that host's
    ``{pod_name: 1}`` resource) and return the refs."""
    import ray_tpu

    remote_fn = fn if hasattr(fn, "remote") else ray_tpu.remote(fn)
    # merge with any resources already declared on the function — the pin
    # adds to (not replaces) e.g. a per-host TPU chip demand
    existing = dict(getattr(remote_fn, "_options", {}).get("resources") or {})
    existing[pod_name] = 1
    return [
        remote_fn.options(resources=existing).remote(*args, **kwargs)
        for _ in range(num_hosts)
    ]
