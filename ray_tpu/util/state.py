"""State API: list/summarize cluster entities.

Role analog: ``python/ray/util/state/api.py`` (``StateApiClient :110``,
``list_actors :788``, ``summarize_tasks :1382``) — backed here by the
driver's control plane (GCS analog) instead of a REST head.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


def _gcs():
    from ray_tpu.core.runtime import _get_runtime

    rt = _get_runtime()
    if rt is None:
        raise RuntimeError("ray_tpu is not initialized")
    return rt


def list_nodes() -> List[Dict[str, Any]]:
    import ray_tpu

    return ray_tpu.nodes()


def actor_queue_depths(actor_ids: List[bytes]) -> List[int]:
    """Pending-call depth per actor (same order as ``actor_ids``) — the
    public surface serve's load-aware routing reads; libraries must not
    reach into the runtime for this (layering seam)."""
    return _gcs().actor_queue_depths(actor_ids)


def list_actors(filters: Optional[List] = None) -> List[Dict[str, Any]]:
    rt = _gcs()
    out = []
    for info in rt.gcs.all_actors():
        rec = {
            "actor_id": info.actor_id.hex(),
            "state": info.state,
            "name": getattr(info, "name", "") or None,
            "restarts": getattr(info, "restarts", 0),
        }
        out.append(rec)
    return _apply_filters(out, filters)


def _all_task_events(rt) -> List[Dict[str, Any]]:
    """Cluster-wide task events when a GCS is attached (nodes flush their
    buffers there — reference TaskEventBuffer -> GcsTaskManager pipeline),
    else this driver's local buffer."""
    if rt.cluster is not None:
        try:
            evs = rt.cluster.gcs.call("task_events_get", 50000, timeout=10)
            if evs:
                return evs
        except Exception:
            pass
    return rt.timeline()


def list_tasks(filters: Optional[List] = None) -> List[Dict[str, Any]]:
    """Finished-task records — cluster-wide in cluster mode (every node
    ships its events to the GCS), driver-local otherwise."""
    rt = _gcs()
    out = []
    for ev in _all_task_events(rt):
        out.append({
            "name": ev.get("name"),
            "state": "FINISHED",
            "duration_ms": ev.get("dur", 0) / 1e3,
            "worker": ev.get("tid"),
            "node": ev.get("node"),
        })
    return _apply_filters(out, filters)


def list_objects(filters: Optional[List] = None) -> List[Dict[str, Any]]:
    rt = _gcs()
    out = []
    for oid, st in rt.gcs.all_objects():
        out.append({
            "object_id": oid.hex(),
            "status": st.status,
            "size": st.size,
            "in_plasma": st.inline is None,
        })
    return _apply_filters(out, filters)


def list_placement_groups() -> List[Dict[str, Any]]:
    rt = _gcs()
    with rt.lock:
        return [
            {"placement_group_id": pgid.hex(),
             "bundles": {i: dict(b) for i, b in pg["bundles"].items()},
             "strategy": pg["strategy"]}
            for pgid, pg in rt.pgs.items()
        ]


def list_workers() -> List[Dict[str, Any]]:
    rt = _gcs()
    with rt.lock:
        workers = list(rt.workers.values())
    out = []
    for ws in workers:
        out.append({
            "worker_id": ws.worker_id.hex(),
            "pid": ws.proc.pid if ws.proc else None,
            "kind": ws.kind,
            "status": ws.status,
        })
    return out


def list_task_events(limit: int = 1000) -> List[Dict[str, Any]]:
    """Recent task-lifecycle flight-recorder records (newest last): one
    dict per finished task with per-phase durations in seconds
    (queue/lease/arg_fetch/deserialize/execute/store_result/total)."""
    rt = _gcs()
    ring = list(getattr(rt, "task_ring", ()) or ())
    out = []
    for ev in ring[-int(limit):]:
        ev = dict(ev)
        # the hot path stores raw ids; render them here, per query
        ev["task_id"] = ev["task_id"].hex()[:16]
        ev["worker_id"] = ev["worker_id"].hex()[:8]
        out.append(ev)
    return out


def list_spans(limit: int = 10000,
               filters: Optional[List] = None) -> List[Dict[str, Any]]:
    """Collected trace spans — cluster-wide in cluster mode (every node
    ships its TraceStore deltas on the heartbeat; reference
    tracing-plane/GcsTaskManager role), head-local otherwise. Each span
    carries trace/span/parent ids, epoch-nano timestamps, attributes, and
    origin labels (node_id / worker_id / component). Empty unless tracing
    is armed (``enable_tracing()`` / ``RTPU_TRACING=1``)."""
    rt = _gcs()
    try:
        rt.collect_trace_spans()
    except Exception:
        pass
    if rt.cluster is not None:
        try:
            evs = rt.cluster.gcs.call("trace_events_get", int(limit),
                                      timeout=10)
            if evs:
                return _apply_filters(evs, filters)
        except Exception:
            pass
    return _apply_filters(rt.trace_store.snapshot(int(limit)), filters)


def summarize_critical_path(trace_id: Optional[str] = None,
                            limit: int = 5000) -> Dict[str, Any]:
    """Attribute end-to-end wall time to per-process segments.

    With ``trace_id``: sweep that trace's spans (serve request chain,
    task graph) into segments that sum exactly to the end-to-end time.
    Without: aggregate the flight-recorder ring per task — driver submit
    CPU (from submit spans, when tracing is armed), queue, lease, worker
    phases, and transit — the printed form of the multi-client
    control-plane ceiling (r8 root cause)."""
    from ray_tpu.util import trace_store as _ts

    rt = _gcs()
    spans = list_spans(limit=100_000)
    if trace_id is not None:
        sel = [s for s in spans if s.get("trace_id") == trace_id]
        return _ts.critical_path_for_trace(sel)
    ring = list(getattr(rt, "task_ring", ()) or ())[-int(limit):]
    return _ts.critical_path_for_tasks(ring, spans)


def export_perfetto(filename: Optional[str] = None) -> Dict[str, Any]:
    """Unified Perfetto/Chrome-trace document: collected spans (incl.
    lock-contention waits and train-step telemetry) merged with the
    flight recorder's task-phase slices, one process row per node and one
    thread track per worker. Write to ``filename`` and load it in
    ui.perfetto.dev / chrome://tracing. Supersedes the driver-only
    ``ray_tpu.timeline()`` export."""
    from ray_tpu.util import trace_store as _ts

    rt = _gcs()
    spans = list_spans(limit=200_000)
    events = _all_task_events(rt)
    doc = _ts.build_perfetto(spans, events)
    if filename:
        import json

        with open(filename, "w") as f:
            json.dump(doc, f)
    return doc


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over a pre-sorted list."""
    import math

    n = len(sorted_vals)
    if n == 0:
        return 0.0
    return sorted_vals[min(n - 1, max(0, int(math.ceil(q * n)) - 1))]


def summarize_tasks() -> Dict[str, Dict[str, Any]]:
    """Counts by (name, state) — reference ``summarize_tasks`` — plus a
    ``"phases"`` entry per task name with per-phase latency percentiles
    (p50/p99, milliseconds) over the driver's flight-recorder ring."""
    summary: Dict[str, Dict[str, Any]] = {}
    for t in list_tasks():
        name = t.get("name", "unknown")
        state = t.get("state", "unknown")
        summary.setdefault(name, {}).setdefault(state, 0)
        summary[name][state] += 1
    by_phase: Dict[str, Dict[str, List[float]]] = {}
    for ev in list_task_events(limit=100_000):
        phases = by_phase.setdefault(ev.get("name") or "task", {})
        for ph, v in (ev.get("phases") or {}).items():
            phases.setdefault(ph, []).append(v)
    for name, phases in by_phase.items():
        ent = summary.setdefault(name, {})
        ent["phases"] = {}
        for ph, vals in phases.items():
            vals.sort()
            ent["phases"][ph] = {
                "count": len(vals),
                "mean_ms": round(sum(vals) / len(vals) * 1e3, 3),
                "p50_ms": round(_percentile(vals, 0.5) * 1e3, 3),
                "p99_ms": round(_percentile(vals, 0.99) * 1e3, 3),
            }
    return summary


def summarize_contention() -> Dict[str, Any]:
    """Per-lock contention totals for THIS process (see
    :mod:`ray_tpu.util.contention`): acquisitions, contended count/%,
    cumulative and max wait. Worst lock first — the first row answers
    "which lock is the bottleneck?". Remote processes' accumulators are
    on the head ``/metrics`` as ``rtpu_lock_*`` series with origin
    labels."""
    from ray_tpu.util import contention

    return {"locks": contention.summarize(),
            "enabled": contention.enabled()}


def summarize_actors() -> Dict[str, int]:
    summary: Dict[str, int] = {}
    for a in list_actors():
        summary.setdefault(a["state"], 0)
        summary[a["state"]] += 1
    return summary


def summarize_objects() -> Dict[str, Any]:
    objs = list_objects()
    return {
        "total": len(objs),
        "in_plasma": sum(1 for o in objs if o["in_plasma"]),
        "inline": sum(1 for o in objs if not o["in_plasma"]),
    }


def _apply_filters(records: List[Dict[str, Any]],
                   filters: Optional[List]) -> List[Dict[str, Any]]:
    """filters: [(key, op, value)] with op in {'=', '!='} (reference
    state-API filter tuples)."""
    if not filters:
        return records
    out = []
    for r in records:
        keep = True
        for key, op, value in filters:
            got = r.get(key)
            if op == "=" and got != value:
                keep = False
            elif op == "!=" and got == value:
                keep = False
        if keep:
            out.append(r)
    return out
