"""State API: list/summarize cluster entities.

Role analog: ``python/ray/util/state/api.py`` (``StateApiClient :110``,
``list_actors :788``, ``summarize_tasks :1382``) — backed here by the
driver's control plane (GCS analog) instead of a REST head.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


def _gcs():
    from ray_tpu.core.runtime import _get_runtime

    rt = _get_runtime()
    if rt is None:
        raise RuntimeError("ray_tpu is not initialized")
    return rt


def list_nodes() -> List[Dict[str, Any]]:
    import ray_tpu

    return ray_tpu.nodes()


def actor_queue_depths(actor_ids: List[bytes]) -> List[int]:
    """Pending-call depth per actor (same order as ``actor_ids``) — the
    public surface serve's load-aware routing reads; libraries must not
    reach into the runtime for this (layering seam)."""
    return _gcs().actor_queue_depths(actor_ids)


def hint_object_pull_align(ref, stride: int,
                           payload_bytes: int = 0) -> None:
    """Block-batch framing hint for a cross-node fetch (ISSUE 13): a
    consumer that knows ``ref`` holds a batch of fixed-size records
    (KV blocks) registers the record stride — and the total record
    payload size, since records start AFTER the serialized header —
    BEFORE touching the value; the cluster adapter's chunked pull then
    aligns chunk boundaries to whole records. Public surface for the ML
    layers (layering seam) — a no-op off-cluster or when the object is
    already local.

    In a WORKER process the hint is stashed on the worker runtime and
    forwarded on the next ``get`` wire call — the pull itself runs in
    the hosting driver/daemon process, so a registry in this process
    would never be consulted."""
    oid_b = ref.binary() if hasattr(ref, "binary") else bytes(ref)
    try:
        from ray_tpu.core.runtime import _get_runtime

        rt = _get_runtime()
        if rt is not None and hasattr(rt, "hint_pull_align"):
            rt.hint_pull_align(oid_b, int(stride),
                               int(payload_bytes))  # worker: wire path
            return
    except Exception:
        pass
    try:
        from ray_tpu.cluster.adapter import hint_pull_align

        hint_pull_align(oid_b, int(stride), int(payload_bytes))
    except Exception:
        pass


def list_actors(filters: Optional[List] = None) -> List[Dict[str, Any]]:
    rt = _gcs()
    out = []
    for info in rt.gcs.all_actors():
        rec = {
            "actor_id": info.actor_id.hex(),
            "state": info.state,
            "name": getattr(info, "name", "") or None,
            "restarts": getattr(info, "restarts", 0),
        }
        out.append(rec)
    return _apply_filters(out, filters)


def _all_task_events(rt) -> List[Dict[str, Any]]:
    """Cluster-wide task events when a GCS is attached (nodes flush their
    buffers there — reference TaskEventBuffer -> GcsTaskManager pipeline),
    else this driver's local buffer."""
    if rt.cluster is not None:
        try:
            evs = rt.cluster.gcs.call("task_events_get", 50000, timeout=10)
            if evs:
                return evs
        except Exception:
            pass
    return rt.timeline()


def list_tasks(filters: Optional[List] = None) -> List[Dict[str, Any]]:
    """Finished-task records — cluster-wide in cluster mode (every node
    ships its events to the GCS), driver-local otherwise."""
    rt = _gcs()
    out = []
    for ev in _all_task_events(rt):
        out.append({
            "name": ev.get("name"),
            "state": "FINISHED",
            "duration_ms": ev.get("dur", 0) / 1e3,
            "worker": ev.get("tid"),
            "node": ev.get("node"),
        })
    return _apply_filters(out, filters)


def list_objects(filters: Optional[List] = None) -> List[Dict[str, Any]]:
    rt = _gcs()
    out = []
    for oid, st in rt.gcs.all_objects():
        out.append({
            "object_id": oid.hex(),
            "status": st.status,
            "size": st.size,
            "in_plasma": st.inline is None,
        })
    return _apply_filters(out, filters)


def list_placement_groups() -> List[Dict[str, Any]]:
    rt = _gcs()
    with rt.lock:
        return [
            {"placement_group_id": pgid.hex(),
             "bundles": {i: dict(b) for i, b in pg["bundles"].items()},
             "strategy": pg["strategy"]}
            for pgid, pg in rt.pgs.items()
        ]


def list_workers() -> List[Dict[str, Any]]:
    rt = _gcs()
    with rt.lock:
        workers = list(rt.workers.values())
    out = []
    for ws in workers:
        out.append({
            "worker_id": ws.worker_id.hex(),
            "pid": ws.proc.pid if ws.proc else None,
            "kind": ws.kind,
            "status": ws.status,
        })
    return out


def list_task_events(limit: int = 1000) -> List[Dict[str, Any]]:
    """Recent task-lifecycle flight-recorder records (newest last): one
    dict per finished task with per-phase durations in seconds
    (queue/lease/arg_fetch/deserialize/execute/store_result/total)."""
    rt = _gcs()
    ring = list(getattr(rt, "task_ring", ()) or ())
    out = []
    for ev in ring[-int(limit):]:
        ev = dict(ev)
        # the hot path stores raw ids; render them here, per query
        ev["task_id"] = ev["task_id"].hex()[:16]
        ev["worker_id"] = ev["worker_id"].hex()[:8]
        out.append(ev)
    return out


def list_spans(limit: int = 10000,
               filters: Optional[List] = None) -> List[Dict[str, Any]]:
    """Collected trace spans — cluster-wide in cluster mode (every node
    ships its TraceStore deltas on the heartbeat; reference
    tracing-plane/GcsTaskManager role), head-local otherwise. Each span
    carries trace/span/parent ids, epoch-nano timestamps, attributes, and
    origin labels (node_id / worker_id / component). Empty unless tracing
    is armed (``enable_tracing()`` / ``RTPU_TRACING=1``)."""
    rt = _gcs()
    try:
        rt.collect_trace_spans()
    except Exception:
        pass
    if rt.cluster is not None:
        try:
            evs = rt.cluster.gcs.call("trace_events_get", int(limit),
                                      timeout=10)
            if evs:
                return _apply_filters(evs, filters)
        except Exception:
            pass
    return _apply_filters(rt.trace_store.snapshot(int(limit)), filters)


def list_events(limit: int = 1000,
                filters: Optional[List] = None) -> List[Dict[str, Any]]:
    """Collected lifecycle events — cluster-wide in cluster mode (every
    node ships its EventStore deltas on the heartbeat; the GCS appends
    its own node register/death events), head-local otherwise. Each
    event carries a ``name`` from the catalog in
    :mod:`ray_tpu.util.events`, a timestamp, severity, structured
    fields, and origin labels; DEATH events carry a ``postmortem``
    (exit cause, stderr tail, error lines). On by default;
    ``RTPU_EVENTS=0`` empties the plane."""
    rt = _gcs()
    try:
        rt.collect_lifecycle_events()
    except Exception:
        pass
    if rt.cluster is not None:
        try:
            evs = rt.cluster.gcs.call("lifecycle_events_get", int(limit),
                                      timeout=10)
            if evs:
                return _apply_filters(evs, filters)
        except Exception:
            pass
    return _apply_filters(rt.event_store.snapshot(int(limit)), filters)


def subscribe_node_events(callback) -> bool:
    """Register ``callback(payload)`` for node lifecycle pubsub events
    (``{"event": "down"|"up", "node_id": ..., "cause": ...}``),
    delivered AFTER the cluster adapter's own failure handling has run
    for the node. Returns False off-cluster (single-node runtimes have
    no membership to watch). This is the public seam the train layer's
    elastic membership machinery (r20) subscribes through — callbacks
    run on the adapter's io pool, so keep them non-blocking."""
    rt = _gcs()
    if rt.cluster is None:
        return False
    rt.cluster.subscribe_node_events(callback)
    return True


def unsubscribe_node_events(callback) -> None:
    """Remove a :func:`subscribe_node_events` callback (idempotent)."""
    rt = _gcs()
    if rt.cluster is not None:
        rt.cluster.unsubscribe_node_events(callback)


def device_report() -> Dict[str, Any]:
    """Cluster-wide device plane: every process's compiled-program
    registry (compiles, retraces, signatures, cost/memory analysis),
    HBM watermarks, and live-buffer census, merged across nodes. Local
    entries come from this process's registry plus its workers' pushed
    snapshots (DeviceStore); remote nodes' entries ride heartbeats into
    the GCS as idempotent per-node payloads. On by default;
    ``RTPU_DEVICE_PLANE=0`` empties the plane."""
    from ray_tpu.util import device_plane

    rt = _gcs()
    comp = "driver"
    if rt.cluster is not None and not rt.cluster.is_scheduler:
        comp = "raylet"
    entries = device_plane.node_processes(rt, component=comp)
    me = rt.node_id.hex()[:8]
    for ent in entries:
        ent.setdefault("node_id", me)
    if rt.cluster is not None:
        try:
            remote = rt.cluster.gcs.call("device_report_get", rt.node_id,
                                         timeout=10)
            entries.extend(remote or ())
        except Exception:
            pass
    return device_plane.merge_report(entries)


def _resolve_log_target(rt, target: Dict[str, Any]) -> Dict[str, Any]:
    """Map a task/actor id onto the worker that ran it so the log fetch
    can rendezvous on worker_id: death events carry both ids (the usual
    reason someone fetches a log is that the worker died), and the
    head's flight-recorder ring covers tasks that finished alive."""
    want_task = (target.get("task_id") or "").lower()
    want_actor = (target.get("actor_id") or "").lower()
    if not want_task and not want_actor:
        return target
    for ev in reversed(list_events(limit=10000)):
        if want_task and (ev.get("task_id") or "").startswith(
                want_task[:16]) and ev.get("worker_id"):
            return {"worker_id": ev["worker_id"]}
        if want_actor and (ev.get("actor_id") or "").startswith(
                want_actor) and ev.get("worker_id"):
            return {"worker_id": ev["worker_id"]}
    if want_task:
        for ev in reversed(list(getattr(rt, "task_ring", ()) or ())):
            tid = ev.get("task_id")
            if tid is not None and tid.hex().startswith(want_task[:16]):
                return {"worker_id": ev["worker_id"].hex()[:8]}
    return target


def fetch_logs(target: Dict[str, Any], timeout: float = 5.0,
               tail_bytes: Optional[int] = None) -> List[Dict[str, Any]]:
    """Cluster-wide log fetch (the ``rtpu logs`` backend): resolve
    ``target`` — ``{"task_id"}``, ``{"actor_id"}``, ``{"worker_id"}``,
    ``{"node_id"}`` (all hex prefixes; node fetches also need
    ``"node": True``) or ``{"node": True}`` — to log files wherever
    they live, and return bounded tails with extracted error lines.
    Task/actor ids resolve through death events (cross-node) or the
    flight recorder; in cluster mode the fetch rides a GCS ``events``-
    channel rendezvous and only nodes that resolved the target reply.
    Falls back to ``/proc/<pid>/fd`` reads for live processes whose log
    file was deleted under them."""
    import time as _time

    rt = _gcs()
    target = _resolve_log_target(rt, dict(target or {}))
    if target.get("node_id") and "worker_id" not in target:
        target.setdefault("node", True)
    rows = rt.fetch_local_logs(target, tail_bytes=tail_bytes)
    if rt.cluster is None or rows:
        return rows
    try:
        req = rt.cluster.gcs.call("log_request", target, tail_bytes,
                                  timeout=10)
    except Exception:
        return rows
    deadline = _time.monotonic() + timeout
    replies: Dict[str, Any] = {}
    while _time.monotonic() < deadline:
        try:
            replies = rt.cluster.gcs.call("log_collect", req,
                                          timeout=10) or {}
        except Exception:
            replies = {}
        if replies:
            # one more collect round: give slower nodes of a broadcast
            # fetch a beat to land before returning
            _time.sleep(0.3)
            try:
                replies = rt.cluster.gcs.call("log_collect", req,
                                              timeout=10) or replies
            except Exception:
                pass
            break
        _time.sleep(0.2)
    out: List[Dict[str, Any]] = []
    for _node, node_rows in sorted(replies.items()):
        out.extend(node_rows)
    return out


def list_alerts() -> List[Dict[str, Any]]:
    """Currently-raised watchdog alerts at this head (see
    :mod:`ray_tpu.util.alerts`): rule name, severity, observed value vs
    threshold, and since-when. Empty when all rules are healthy or the
    plane is killed (``RTPU_ALERTS=0``)."""
    from ray_tpu.util import alerts

    return alerts.active_alerts()


def _collect_profile_batches(rt) -> List[Dict[str, Any]]:
    """Every collected profile batch visible from this head: the local
    ProfileStore (this process's sampler + its workers' pushes) plus —
    in cluster mode — the GCS buffer (every node's heartbeat-shipped
    deltas; the local store is the superset of what this node shipped,
    so GCS batches from OUR node id are dropped to avoid double counts)."""
    try:
        rt.collect_profile_batches()
    except Exception:
        pass
    local = rt.profile_store.snapshot()
    if rt.cluster is None:
        return local
    me = rt.node_id.hex()[:8]
    out = list(local)
    try:
        evs = rt.cluster.gcs.call("profile_events_get", 4096, timeout=10)
        for b in evs or ():
            if b.get("node_id") != me:
                out.append(b)
    except Exception:
        pass
    return out


def _sample_window(rt, seconds: Optional[float]) -> Dict[str, Any]:
    """THE shared arm→sample→disarm→collect sequence behind profile(),
    profile_collapsed() and export_speedscope().

    With ``seconds``: arm cluster-wide if not already armed (disarming
    again after — the disarm tail-flushes worker tables over the pipe),
    idle-sleep the window, then poll collection until the merged
    PROCESS SET stops growing (two stable polls after a minimum settle)
    — breaking on the first busy sample would return just the head's
    own instantly-available batch while worker casts and daemon
    heartbeat rides are still in flight. All waits are idle-typed so
    the query never profiles itself."""
    import time as _time

    from ray_tpu.util import profiling

    if seconds is None:
        return profiling.merge_batches(_collect_profile_batches(rt))
    since = _time.time()
    armed_here = not profiling.profiling_enabled()
    if armed_here:
        profiling.enable_profiling()
    profiling.idle_sleep(float(seconds))
    if armed_here:
        profiling.disable_profiling()
    deadline = _time.monotonic() + 8.0
    # minimum settle: one worker push interval + one heartbeat, so the
    # window's tail batches have a chance to land before stability can
    # possibly be declared
    min_wait = _time.monotonic() + 1.5
    prev_keys = None
    merged = profiling.merge_batches([])
    while _time.monotonic() < deadline:
        merged = profiling.merge_batches(
            _collect_profile_batches(rt), since=since)
        keys = frozenset(merged["processes"])
        if keys and keys == prev_keys and _time.monotonic() >= min_wait:
            break
        prev_keys = keys
        profiling.idle_sleep(0.4)
    return merged


def profile(seconds: Optional[float] = None,
            component: Optional[str] = None,
            top_n: int = 20) -> Dict[str, Any]:
    """Cluster-wide merged CPU profile (the profiling plane's query
    surface; ``GET /api/profile``).

    With ``seconds``: sample for that window — arming the profiler
    cluster-wide for the duration if it isn't already armed
    (``enable_profiling()`` semantics; disarmed again after) — then
    merge every process's batches whose window overlaps it. Without:
    merge everything collected since arming.

    Returns per-(node, pid, component) sample totals plus ``top_self``
    rankings (leaf-frame self-time — "which functions burn the CPU"),
    overall and per component. The ``driver`` component's ranking is the
    direct input to ROADMAP item 1 (the GIL-serialized control plane)."""
    from ray_tpu.util import profiling

    rt = _gcs()
    merged = _sample_window(rt, seconds)
    components = sorted({p["component"]
                         for p in merged["processes"].values()})
    out: Dict[str, Any] = {
        "seconds": seconds,
        "total_samples": merged["total"],
        "idle_samples": merged["idle_total"],
        "dropped_samples": merged["dropped"],
        "processes": {
            k: {"component": p["component"], "node_id": p["node_id"],
                "pid": p["pid"], "samples": p["total"],
                "idle_samples": p["idle_total"],
                "threads": sorted(p["threads"])}
            for k, p in sorted(merged["processes"].items())},
        "top_self": profiling.top_self(merged, component=component,
                                       n=top_n),
        "top_self_by_component": {
            c: profiling.top_self(merged, component=c, n=top_n)
            for c in components},
    }
    return out


def profile_collapsed(seconds: Optional[float] = None,
                      include_idle: bool = False) -> str:
    """Collapsed-stack text (``proc;thread;frames... N``) for
    flamegraph.pl or a speedscope paste — the raw export twin of
    :func:`profile`."""
    from ray_tpu.util import profiling

    merged = _sample_window(_gcs(), seconds)
    return profiling.collapsed_text(merged, include_idle=include_idle)


def export_speedscope(filename: Optional[str] = None,
                      seconds: Optional[float] = None) -> Dict[str, Any]:
    """Speedscope JSON document of the merged cluster profile (one
    sampled profile per thread, weights summing to its sample count).
    Write to ``filename`` and open it at https://speedscope.app."""
    from ray_tpu.util import profiling

    merged = _sample_window(_gcs(), seconds)
    doc = profiling.speedscope_doc(merged)
    if filename:
        import json

        with open(filename, "w") as f:
            json.dump(doc, f)
    return doc


def stack(timeout: float = 3.0) -> Dict[str, Any]:
    """LIVE python stacks of every process in the cluster (the
    ``ray_tpu stack`` / ``ray stack`` py-spy role): this head and its
    workers over the control pipes; in cluster mode every daemon (and
    ITS workers) via a GCS ``profiling``-channel stack request. Needs no
    arming. Returns ``{node: {proc: {thread: "root;...;leaf"}}}``."""
    import time as _time

    rt = _gcs()
    me = rt.node_id.hex()[:8]
    out: Dict[str, Any] = {}
    if rt.cluster is not None:
        try:
            req = rt.cluster.gcs.call("stack_request", timeout=10)
            deadline = _time.monotonic() + timeout
            want = max(1, len([n for n in rt.cluster.node_info()
                               if n.get("alive", n.get("Alive"))]))
            replies: Dict[str, Any] = {}
            while _time.monotonic() < deadline:
                replies = rt.cluster.gcs.call("stack_collect", req,
                                              timeout=10) or {}
                if len(replies) >= want:
                    break
                from ray_tpu.util import profiling as _prof

                _prof.idle_sleep(0.2)
            out.update(replies)
        except Exception:
            pass
    if me not in out:
        # single-node mode, or the head's own pubsub reply lost the race
        out[me] = rt.dump_stacks(timeout=min(2.0, timeout))
    return out


def summarize_critical_path(trace_id: Optional[str] = None,
                            limit: int = 5000) -> Dict[str, Any]:
    """Attribute end-to-end wall time to per-process segments.

    With ``trace_id``: sweep that trace's spans (serve request chain,
    task graph) into segments that sum exactly to the end-to-end time.
    Without: aggregate the flight-recorder ring per task — driver submit
    CPU (from submit spans, when tracing is armed), queue, lease, worker
    phases, and transit — the printed form of the multi-client
    control-plane ceiling (r8 root cause)."""
    from ray_tpu.util import trace_store as _ts

    rt = _gcs()
    spans = list_spans(limit=100_000)
    if trace_id is not None:
        sel = [s for s in spans if s.get("trace_id") == trace_id]
        return _ts.critical_path_for_trace(sel)
    ring = list(getattr(rt, "task_ring", ()) or ())[-int(limit):]
    return _ts.critical_path_for_tasks(ring, spans)


def export_perfetto(filename: Optional[str] = None) -> Dict[str, Any]:
    """Unified Perfetto/Chrome-trace document: collected spans (incl.
    lock-contention waits and train-step telemetry) merged with the
    flight recorder's task-phase slices, one process row per node and one
    thread track per worker. Write to ``filename`` and load it in
    ui.perfetto.dev / chrome://tracing. Supersedes the driver-only
    ``ray_tpu.timeline()`` export."""
    from ray_tpu.util import trace_store as _ts

    rt = _gcs()
    spans = list_spans(limit=200_000)
    events = _all_task_events(rt)
    doc = _ts.build_perfetto(spans, events)
    if filename:
        import json

        with open(filename, "w") as f:
            json.dump(doc, f)
    return doc


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over a pre-sorted list."""
    import math

    n = len(sorted_vals)
    if n == 0:
        return 0.0
    return sorted_vals[min(n - 1, max(0, int(math.ceil(q * n)) - 1))]


def summarize_tasks() -> Dict[str, Dict[str, Any]]:
    """Counts by (name, state) — reference ``summarize_tasks`` — plus a
    ``"phases"`` entry per task name with per-phase latency percentiles
    (p50/p99, milliseconds) over the driver's flight-recorder ring."""
    summary: Dict[str, Dict[str, Any]] = {}
    for t in list_tasks():
        name = t.get("name", "unknown")
        state = t.get("state", "unknown")
        summary.setdefault(name, {}).setdefault(state, 0)
        summary[name][state] += 1
    by_phase: Dict[str, Dict[str, List[float]]] = {}
    for ev in list_task_events(limit=100_000):
        phases = by_phase.setdefault(ev.get("name") or "task", {})
        for ph, v in (ev.get("phases") or {}).items():
            phases.setdefault(ph, []).append(v)
    for name, phases in by_phase.items():
        ent = summary.setdefault(name, {})
        ent["phases"] = {}
        for ph, vals in phases.items():
            vals.sort()
            ent["phases"][ph] = {
                "count": len(vals),
                "mean_ms": round(sum(vals) / len(vals) * 1e3, 3),
                "p50_ms": round(_percentile(vals, 0.5) * 1e3, 3),
                "p99_ms": round(_percentile(vals, 0.99) * 1e3, 3),
            }
    return summary


def summarize_contention() -> Dict[str, Any]:
    """Per-lock contention totals for THIS process (see
    :mod:`ray_tpu.util.contention`): acquisitions, contended count/%,
    cumulative and max wait. Worst lock first — the first row answers
    "which lock is the bottleneck?". Remote processes' accumulators are
    on the head ``/metrics`` as ``rtpu_lock_*`` series with origin
    labels."""
    from ray_tpu.util import contention

    return {"locks": contention.summarize(),
            "enabled": contention.enabled()}


def summarize_actors() -> Dict[str, int]:
    summary: Dict[str, int] = {}
    for a in list_actors():
        summary.setdefault(a["state"], 0)
        summary[a["state"]] += 1
    return summary


def summarize_objects() -> Dict[str, Any]:
    objs = list_objects()
    return {
        "total": len(objs),
        "in_plasma": sum(1 for o in objs if o["in_plasma"]),
        "inline": sum(1 for o in objs if not o["in_plasma"]),
    }


# ---------------------------------------------------------------------------
# object-memory forensics (`ray_tpu memory` — reference `ray memory` role)
# ---------------------------------------------------------------------------


def _pin_indexes(rt):
    """One pass over the driver's reference machinery: (pin counts,
    arg-pinned set, nested-pinned set) snapshotted under the ref lock so
    the per-object reason lookup below is O(1)."""
    with rt._ref_lock:
        pins = dict(rt._pin_total)
        arg_pinned = {b for deps in rt._arg_pins.values() for b in deps}
        nested_pinned = {b for nested in rt._result_ref_pins.values()
                         for b in nested}
    return pins, arg_pinned, nested_pinned


def _pin_reasons(rt, oid_b: bytes, pins, arg_pinned,
                 nested_pinned) -> List[str]:
    """Why an object is alive: ``create-ref`` (live ObjectRef pins —
    driver-local or worker borrows), ``arg-pin`` (argument of an
    in-flight task), ``nested-pin`` (referenced inside another stored
    object), ``lineage`` (reconstructable: its producing task spec is
    retained), ``spilled`` (bytes on disk, not shm)."""
    reasons = []
    if pins.get(oid_b, 0) > 0:
        reasons.append("create-ref")
    if oid_b in arg_pinned:
        reasons.append("arg-pin")
    if oid_b in nested_pinned:
        reasons.append("nested-pin")
    if oid_b in rt._lineage:
        reasons.append("lineage")
    try:
        from ray_tpu.core.ids import ObjectID as _OID

        if rt.store.contains_spilled(_OID(oid_b)):
            reasons.append("spilled")
    except Exception:
        pass
    return reasons


def memory_summary(limit: int = 10000,
                   min_size: int = 0) -> List[Dict[str, Any]]:
    """Per-object forensic rows (the ``ray memory`` analog): id, status,
    size, inline-vs-shm, owner process, pin count + reasons, age, and —
    when the profiler was armed at creation — the creating call-site.
    Largest first."""
    import time as _time

    rt = _gcs()
    now = _time.time()
    pins, arg_pinned, nested_pinned = _pin_indexes(rt)
    rows = []
    for oid, st in rt.gcs.all_objects():
        size = st.size or 0
        if size < min_size:
            continue
        b = oid.binary()
        meta = rt._obj_meta.get(b) or {}
        rows.append({
            "object_id": oid.hex(),
            "status": st.status,
            "size": size,
            "in_plasma": st.inline is None,
            "owner_node": rt.node_id.hex()[:8],
            "owner": meta.get("owner") or "?",
            "pins": pins.get(b, 0),
            "reasons": _pin_reasons(rt, b, pins, arg_pinned,
                                    nested_pinned),
            "age_s": (round(now - meta["ts"], 1)
                      if meta.get("ts") else None),
            "call_site": meta.get("site"),
        })
    rows.sort(key=lambda r: -r["size"])
    return rows[:limit]


#: last snapshot taken by snapshot_objects()/diff_objects() (the leak-
#: detector baseline)
_obj_snapshot: Optional[Dict[str, Dict[str, Any]]] = None


def snapshot_objects() -> Dict[str, Dict[str, Any]]:
    """Record (and return) the current object population as the baseline
    for :func:`diff_objects` — call before the workload under suspicion."""
    global _obj_snapshot
    _obj_snapshot = {r["object_id"]: r for r in memory_summary()}
    return _obj_snapshot


def diff_objects(prev: Optional[Dict[str, Dict[str, Any]]] = None
                 ) -> Dict[str, Any]:
    """Leak detector: diff the live object population against ``prev``
    (default: the last :func:`snapshot_objects` baseline). Objects that
    appeared and are still pinned are the leak suspects — each row
    carries its pin reasons and creation call-site (when the profiler
    was armed), which is what names the leaker."""
    global _obj_snapshot
    if prev is None:
        prev = _obj_snapshot or {}
    cur = {r["object_id"]: r for r in memory_summary()}
    _obj_snapshot = cur
    added = [r for oid, r in cur.items() if oid not in prev]
    removed = [r for oid, r in prev.items() if oid not in cur]
    leaked = [r for r in added if r["pins"] > 0 or r["reasons"]]
    return {
        "added": added,
        "removed": removed,
        "leak_suspects": sorted(leaked, key=lambda r: -r["size"]),
        "net_bytes": (sum(r["size"] for r in added)
                      - sum(r["size"] for r in removed)),
    }


def store_report() -> Dict[str, Any]:
    """This node's object-store occupancy/fragmentation report (native
    arena free-list walk + file segments + spill dir)."""
    return _gcs().store.report()


def object_store_tier(ref) -> str:
    """Storage tier of one object: ``"shm"`` (arena/segment resident),
    ``"spilled"`` (cold on-disk tier), ``"unknown"`` (no runtime, or the
    object is inline/absent). The PUBLIC residency probe the serving
    tier's model registry reports through ``/api/models`` — libraries
    must not reach into the store client for this (layering seam)."""
    try:
        from ray_tpu.core.runtime import _get_runtime

        rt = _get_runtime()
        if rt is None:
            return "unknown"
        oid = ref.id if hasattr(ref, "id") else ref
        if rt.store.contains_spilled(oid):
            return "spilled"
        if rt.store.contains(oid):
            return "shm"
    except Exception:
        pass
    return "unknown"


def _apply_filters(records: List[Dict[str, Any]],
                   filters: Optional[List]) -> List[Dict[str, Any]]:
    """filters: [(key, op, value)] with op in {'=', '!='} (reference
    state-API filter tuples)."""
    if not filters:
        return records
    out = []
    for r in records:
        keep = True
        for key, op, value in filters:
            got = r.get(key)
            if op == "=" and got != value:
                keep = False
            elif op == "!=" and got == value:
                keep = False
        if keep:
            out.append(r)
    return out
