"""The profiling plane: cluster-wide sampling profiler + head-side store.

Role analog: ``ray stack`` / ``ray timeline``'s py-spy integration
(reference ``python/ray/scripts/scripts.py:1830``) — the forensic tool
the Ray paper leans on to find control-plane bottlenecks. r8 proved the
task-throughput ceiling is ~1-2 ms of GIL-serialized driver CPU per
task; this module is what turns that bench inference into named Python
functions: a pure-stdlib sampling profiler whose merged, per-process
flamegraphs answer "which functions is the driver burning that
millisecond in?".

Recording side (every process): a daemon SAMPLER thread walks
``sys._current_frames()`` at ``RTPU_PROFILE_HZ`` (default ~67 Hz) and
aggregates each thread's stack into a bounded per-process table keyed by
(thread name, collapsed stack at function granularity). Threads whose
leaf frame is a known waiter (``threading.Event.wait``, pipe
``recv_bytes``, ``queue.get``...) are classified IDLE and land in a
separate table so wait-dominated threads (the driver has one receiver
thread per worker) don't drown the on-CPU signal. The sampler is
observer-only: its loop takes no instrumented (TimedLock/TimedRLock)
locks, hits no failpoints, and records no spans — enforced by the
graftlint ``profiler-sampler-discipline`` rule — so it can never
deadlock against or recurse into the paths it measures.

Arming mirrors ``tracing.enable_tracing()`` exactly: live workers learn
over their control pipe (``prof`` message, replayed to workers that
dial back later), daemons over the GCS KV + ``profiling`` pubsub
channel, later spawns via the environment, and the zygote fork-server
is retired on a flip so forked workers see the current env.
``RTPU_PROFILING=0`` is the kill switch; the disarmed cost of
``profiling_enabled()`` is one dict get — no lock, no clock.

Collection rides the existing transports (the trace-plane contract):
workers drain their table into batches pushed over the control pipe,
node daemons ship their :class:`ProfileStore` deltas on the GCS
heartbeat with the acked-cursor/dedup contract from ``trace_store``,
and the head merges per-(node, pid, component) at
``state.profile(seconds=...)`` / ``GET /api/profile`` — exported as
collapsed-stack text (flamegraph.pl / speedscope paste) and speedscope
JSON (one sampled profile per thread, weights summing to the sample
count).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from collections import deque
from itertools import islice
from typing import Any, Dict, List, Optional, Tuple

#: cluster-wide arming rides the GCS KV + pubsub (tracing pattern)
KV_NAMESPACE = "__profiling__"
KV_KEY = "spec"
CHANNEL = "profiling"

DEFAULT_HZ = 67.0

_lock = threading.Lock()
# _state["enabled"] doubles as the hot-path cache: None = unresolved,
# read WITHOUT the lock on every profiling_enabled() call (one dict get
# under the GIL; tests reset it to None to force re-resolution).
_state: Dict[str, Any] = {"enabled": None, "hz": None}

# lazily-bound builtin counters; never allowed to fail the plane
_m = {"samples": None, "dropped": None, "pushes": None}


def _metric(which: str):
    from ray_tpu.util import metric_defs, metrics

    names = {"samples": "rtpu_profile_samples_total",
             "dropped": "rtpu_profile_samples_dropped_total",
             "pushes": "rtpu_profile_push_batches_total"}
    inst = _m[which]
    if inst is None or metrics.registered(names[which]) is not inst:
        inst = _m[which] = metric_defs.get(names[which])
    return inst


# ---------------------------------------------------------------------------
# idle-frame classification
# ---------------------------------------------------------------------------

#: leaf frames in these stdlib files with these function names are
#: blocked waiters, not CPU burners (heuristic: Python cannot see
#: C-level blocking, so the deepest *Python* frame of a parked thread is
#: its stdlib wait wrapper). ``send``-side functions are deliberately
#: NOT here — a thread stuck in a pipe send is paying real backpressure.
_IDLE_FILES = ("threading.py", "queue.py", "selectors.py", "socket.py",
               "connection.py", "ssl.py", "subprocess.py", "socketserver.py")
_IDLE_FUNCS = ("wait", "wait_for", "select", "poll", "accept", "get",
               "join", "recv", "recv_bytes", "recv_bytes_into", "_recv",
               "_recv_bytes", "recv_into", "read", "readinto", "sleep",
               "_try_wait", "poll_once", "_wait_for_tstate_lock")


def _is_idle_leaf(filename: str, funcname: str) -> bool:
    return (funcname in _IDLE_FUNCS
            and filename.endswith(_IDLE_FILES))


# ---------------------------------------------------------------------------
# frame naming (function granularity, bounded cardinality)
# ---------------------------------------------------------------------------

#: code object id -> (weakref-to-code, rendered frame string).
#: Function-granularity frames (co_firstlineno, not f_lineno) keep the
#: table cardinality bounded by the number of live functions, not by
#: lines executed. The weakref VALIDATES each hit: a GC'd code object's
#: address can be reused by a new one (cloudpickled task fns churn in
#: long-lived workers), and returning the dead function's label would
#: corrupt exactly the attribution this plane exists to produce.
_frame_cache: Dict[int, tuple] = {}
_FRAME_CACHE_MAX = 8192


def _frame_name(code) -> str:
    import weakref

    key = id(code)
    hit = _frame_cache.get(key)
    if hit is not None and hit[0]() is code:
        return hit[1]
    fn = code.co_filename
    # keep the last two path components: enough to disambiguate
    # ("runtime.py" alone collides; "core/runtime.py" does not)
    parts = fn.rsplit(os.sep, 2)
    short = os.sep.join(parts[-2:]) if len(parts) > 1 else fn
    name = f"{code.co_name} ({short}:{code.co_firstlineno})"
    if len(_frame_cache) >= _FRAME_CACHE_MAX:
        _frame_cache.clear()  # rare: code churn (reloads); start over
    _frame_cache[key] = (weakref.ref(code), name)
    return name


# ---------------------------------------------------------------------------
# the sampler
# ---------------------------------------------------------------------------


class _Sampler:
    """Daemon thread aggregating stack samples into bounded tables.

    OBSERVER-ONLY discipline (graftlint ``profiler-sampler-discipline``):
    the loop body may not acquire TimedLock/TimedRLock-wrapped locks,
    hit failpoints, or record spans/metrics — it runs concurrently with
    every instrumented path it observes. The table lock below is a plain
    ``threading.Lock`` shared only with :meth:`drain`.
    """

    def __init__(self, hz: float, table_max: int, start: bool = True):
        self.hz = max(1.0, float(hz))
        self.table_max = max(64, int(table_max))
        self._table_lock = threading.Lock()  # plain lock, never timed
        # (thread_name, frames_tuple) -> count
        self._busy: Dict[Tuple[str, Tuple[str, ...]], int] = {}
        self._idle: Dict[Tuple[str, Tuple[str, ...]], int] = {}
        self._dropped = 0
        self._total = 0
        self._idle_total = 0
        self._t0 = time.time()
        # per-thread walk cache: a PARKED thread's top frame (object id +
        # instruction offset) is unchanged between ticks, so its stack
        # key can be reused without re-walking — this is what keeps the
        # sampler's cost per tick proportional to RUNNING threads, not
        # to the driver's one-receiver-thread-per-worker population
        self._walk_cache: Dict[int, tuple] = {}
        # thread-name map refresh is amortized (threading.enumerate takes
        # the interpreter's thread-registry lock; ~1/s is plenty)
        self._names: Dict[int, str] = {}
        self._names_at = 0.0
        self._stop = threading.Event()
        self._thread = None
        if start:
            self._thread = threading.Thread(
                target=self._sample_loop, daemon=True,
                name="rtpu_profiler")
            self._thread.start()

    def _sample_once(self) -> None:
        frames = sys._current_frames()
        now = time.monotonic()
        if (now - self._names_at > 1.0
                or any(i not in self._names for i in frames)):
            # refresh amortized, AND whenever a thread this map has
            # never seen appears — a freshly started thread must be
            # attributed by name from its first sample
            self._names = {t.ident: t.name for t in threading.enumerate()}
            for i in frames:
                # non-registry threads (C-spawned) get a stable fallback
                # so they never re-trigger the refresh
                self._names.setdefault(i, f"tid-{i}")
            self._names_at = now
        names = self._names
        me = threading.get_ident()
        cache = self._walk_cache
        for ident, frame in frames.items():
            if ident == me:
                continue  # never sample the sampler
            sig = (id(frame), frame.f_lasti)
            hit = cache.get(ident)
            if hit is not None and hit[0] == sig:
                key, idle = hit[1], hit[2]
            else:
                stack: List[str] = []
                leaf_code = frame.f_code
                f = frame
                depth = 0
                while f is not None and depth < 128:
                    stack.append(_frame_name(f.f_code))
                    f = f.f_back
                    depth += 1
                stack.reverse()  # root -> leaf (collapsed-stack order)
                tname = names.get(ident) or f"tid-{ident}"
                idle = _is_idle_leaf(leaf_code.co_filename,
                                     leaf_code.co_name)
                key = (tname, tuple(stack))
                cache[ident] = (sig, key, idle)
            with self._table_lock:
                table = self._idle if idle else self._busy
                n = table.get(key)
                if n is None and (len(self._busy) + len(self._idle)
                                  >= self.table_max):
                    self._dropped += 1
                    continue
                table[key] = (n or 0) + 1
                if idle:
                    self._idle_total += 1
                else:
                    self._total += 1
        if len(cache) > 4 * max(8, len(frames)):
            # dead threads leave stale idents behind; prune occasionally
            cache_keys = set(frames)
            for k in list(cache):
                if k not in cache_keys:
                    del cache[k]

    def _sample_loop(self) -> None:
        period = 1.0 / self.hz
        next_t = time.monotonic()
        while not self._stop.is_set():
            self._sample_once()
            next_t += period
            delay = next_t - time.monotonic()
            if delay <= 0:
                # fell behind (GIL-starved under load): resynchronize
                # instead of bursting to catch up
                next_t = time.monotonic() + period
                delay = period
            if self._stop.wait(delay):
                return

    def record_for_tests(self, thread: str, frames: List[str],
                         idle: bool = False) -> None:
        """Inject one synthetic sample (deterministic bound/shape tests)."""
        key = (thread, tuple(frames))
        with self._table_lock:
            table = self._idle if idle else self._busy
            n = table.get(key)
            if n is None and (len(self._busy) + len(self._idle)
                              >= self.table_max):
                self._dropped += 1
                return
            table[key] = (n or 0) + 1
            if idle:
                self._idle_total += 1
            else:
                self._total += 1

    def drain(self) -> Optional[Dict[str, Any]]:
        """Swap the tables out and return one batch dict (None when no
        samples landed). Samples leave exactly once; the builtin
        counters are settled here in one batch, never per sample."""
        with self._table_lock:
            if not self._busy and not self._idle and not self._dropped:
                return None
            busy, self._busy = self._busy, {}
            idle, self._idle = self._idle, {}
            dropped, self._dropped = self._dropped, 0
            total, self._total = self._total, 0
            idle_total, self._idle_total = self._idle_total, 0
            t0, self._t0 = self._t0, time.time()
        batch = {
            "pid": os.getpid(),
            "t0": t0,
            "t1": time.time(),
            "hz": self.hz,
            "samples": [[t, list(s), n] for (t, s), n in busy.items()],
            "idle": [[t, list(s), n] for (t, s), n in idle.items()],
            "total": total,
            "idle_total": idle_total,
            "dropped": dropped,
        }
        try:
            if total or idle_total:
                _metric("samples")._inc_key((), total + idle_total)
            if dropped:
                _metric("dropped")._inc_key((), dropped)
        except Exception:
            pass
        return batch

    def stats(self) -> Dict[str, int]:
        with self._table_lock:
            return {"busy_keys": len(self._busy),
                    "idle_keys": len(self._idle),
                    "total": self._total, "idle_total": self._idle_total,
                    "dropped": self._dropped}

    def stop(self) -> None:
        self._stop.set()


_sampler: Optional[_Sampler] = None
#: final windows of stopped samplers (disarm flip): drained batches wait
#: here until the next collection hop ships them — without this, the
#: tail of a `state.profile(seconds=...)` window would vanish on disarm
_pending_batches: List[Dict[str, Any]] = []


def _fork_reset() -> None:
    # the sampler thread does not survive fork; the child (a zygote
    # worker) restarts it lazily from its own main loop when armed
    global _sampler
    _sampler = None
    _pending_batches.clear()
    _state["enabled"] = None
    _frame_cache.clear()


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_fork_reset)


def _hz() -> float:
    hz = _state["hz"]
    if hz is None:
        try:
            from ray_tpu import config

            hz = float(config.get("profile_hz"))
        except Exception:
            hz = DEFAULT_HZ
        _state["hz"] = hz
    return hz


def _table_max() -> int:
    try:
        from ray_tpu import config

        return int(config.get("profile_table_max"))
    except Exception:
        return 4096


def ensure_sampler() -> Optional[_Sampler]:
    """Start (or return) this process's sampler when profiling is armed.
    Called from arming paths and the worker main loop — never from the
    disarmed fast path."""
    global _sampler
    if not profiling_enabled():
        return None
    with _lock:
        if _sampler is None and _state["enabled"]:
            _sampler = _Sampler(_hz(), _table_max())
        return _sampler


def _stop_sampler() -> None:
    global _sampler
    with _lock:
        s, _sampler = _sampler, None
    if s is not None:
        s.stop()
        tail = s.drain()
        if tail:
            _pending_batches.append(tail)


# ---------------------------------------------------------------------------
# arming (the enable_tracing() contract)
# ---------------------------------------------------------------------------


def _resolve() -> bool:
    with _lock:
        if _state["enabled"] is None:
            _state["enabled"] = os.environ.get("RTPU_PROFILING", "0") == "1"
    if _state["enabled"]:
        ensure_sampler()
    return _state["enabled"]


def profiling_enabled() -> bool:
    e = _state["enabled"]
    if e is None:
        return _resolve()
    return e


def push_spec() -> Dict[str, Any]:
    """The arming payload shipped to workers/daemons (pipe + pubsub/KV)."""
    return {"enabled": bool(profiling_enabled()), "hz": _hz()}


def apply_remote(payload: Dict[str, Any]) -> None:
    """Apply a driver-pushed arming payload in THIS process (worker pipe
    message / daemon pubsub / KV late-join sync)."""
    enabled = bool(payload.get("enabled"))
    os.environ["RTPU_PROFILING"] = "1" if enabled else "0"
    hz = payload.get("hz")
    with _lock:
        _state["enabled"] = enabled
        if hz:
            _state["hz"] = float(hz)
            os.environ["RTPU_PROFILE_HZ"] = str(hz)
    if enabled:
        ensure_sampler()
    else:
        _stop_sampler()


def broadcast_local(rt, payload: Optional[Dict[str, Any]]) -> None:
    """Push an arming payload to every live worker of ``rt`` and remember
    it so workers spawned later receive it on dial-back (mirrors
    tracing.broadcast_local)."""
    if not getattr(rt, "is_driver", False):
        return
    rt._profile_push = payload
    for ws in list(getattr(rt, "workers", {}).values()):
        if ws.status == "dead" or ws.conn is None:
            continue
        try:
            ws.send(("prof", payload))
        except Exception:
            pass


def _retire_zygote() -> None:
    # the fork-server's env snapshot predates the flip (tracing pattern);
    # retire it so the next spawn sees the current RTPU_PROFILING
    from ray_tpu.util import tracing

    tracing._retire_zygote()


def _broadcast(payload: Dict[str, Any]) -> None:
    """Local workers + cluster-wide distribution of an arming flip."""
    _retire_zygote()
    try:
        from ray_tpu.core import runtime as _rt_mod

        rt = _rt_mod._runtime
    except Exception:
        rt = None
    if rt is None or not getattr(rt, "is_driver", False):
        return
    broadcast_local(rt, payload)
    cluster = getattr(rt, "cluster", None)
    if cluster is not None:
        try:
            cluster.kv_op("put", KV_KEY, json.dumps(payload).encode(),
                          KV_NAMESPACE, True)
            cluster.gcs.call("publish", CHANNEL, payload, timeout=10)
        except Exception:
            pass


def enable_profiling(hz: Optional[float] = None) -> None:
    """Arm the sampling profiler in THIS process, its live workers
    (control pipe push), workers spawned after this call (env), and — in
    cluster mode — every daemon and ITS workers (GCS KV + ``profiling``
    pubsub; late joiners pull the KV at registration)."""
    os.environ["RTPU_PROFILING"] = "1"
    with _lock:
        _state["enabled"] = True
        if hz:
            _state["hz"] = float(hz)
            os.environ["RTPU_PROFILE_HZ"] = str(hz)
    ensure_sampler()
    _broadcast(push_spec())


def disable_profiling() -> None:
    """The runtime counterpart of ``RTPU_PROFILING=0``: stop sampling in
    this process and everywhere :func:`enable_profiling` reaches. Workers
    flush their table tails on receipt (the trace-plane disarm flush)."""
    os.environ["RTPU_PROFILING"] = "0"
    with _lock:
        _state["enabled"] = False
    _stop_sampler()
    _broadcast(push_spec())


def sync_from_kv(kv_get) -> None:
    """Pull + apply the cluster-wide arming payload (late joiners /
    re-registration). ``kv_get(key, namespace) -> Optional[bytes]``."""
    try:
        blob = kv_get(KV_KEY, KV_NAMESPACE)
    except Exception:
        return
    if blob:
        try:
            apply_remote(json.loads(blob.decode()))
        except Exception:
            pass


def drain_batches() -> List[Dict[str, Any]]:
    """Pop this process's aggregated window(s) as a batch list — the
    collection hop (worker pipe push / daemon heartbeat / head query).
    Samples leave exactly once; includes the stashed final window of a
    just-stopped sampler (disarm tail)."""
    out: List[Dict[str, Any]] = []
    while _pending_batches:
        try:
            out.append(_pending_batches.pop(0))
        except IndexError:
            break
    s = _sampler
    if s is not None:
        batch = s.drain()
        if batch:
            out.append(batch)
    return out


def idle_sleep(seconds: float) -> None:
    """Sleep that the sampler classifies IDLE: the profiler cannot see
    C-level ``time.sleep`` (the leaf Python frame is the caller, which
    reads as busy), but an ``Event.wait`` parks in ``threading.py
    wait`` — use this for waits inside profiling/query paths so the
    profiler never attributes its own window to itself."""
    threading.Event().wait(max(0.0, seconds))


def note_push() -> None:
    """Count one shipped batch (worker pipe / heartbeat ride)."""
    try:
        _metric("pushes")._inc_key(())
    except Exception:
        pass


def sampler_stats() -> Dict[str, int]:
    s = _sampler
    return s.stats() if s is not None else {}


def _reset_for_tests() -> None:
    _stop_sampler()
    _pending_batches.clear()  # a stopped sampler's tail must not leak
    with _lock:                # into the next test's drain
        _state["enabled"] = None
        _state["hz"] = None
    _frame_cache.clear()


# ---------------------------------------------------------------------------
# one-shot live stacks (`ray_tpu stack`'s py-spy role)
# ---------------------------------------------------------------------------


def current_stacks() -> Dict[str, str]:
    """One live sample of every thread in THIS process:
    ``{thread_name: "root;...;leaf"}`` at function granularity. Needs no
    arming — it is a read of ``sys._current_frames()``."""
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    me = threading.get_ident()
    out: Dict[str, str] = {}
    for ident, frame in frames.items():
        if ident == me:
            continue
        stack: List[str] = []
        f = frame
        depth = 0
        while f is not None and depth < 128:
            stack.append(_frame_name(f.f_code))
            f = f.f_back
            depth += 1
        out[names.get(ident) or f"tid-{ident}"] = ";".join(
            reversed(stack))
    return out


def caller_site(skip_prefixes: Tuple[str, ...] = ("ray_tpu",)) -> str:
    """Nearest non-runtime caller frame as ``file:line func`` — the
    creation call-site recorded for object-memory forensics when the
    profiler is armed."""
    f = sys._getframe(1)
    depth = 0
    while f is not None and depth < 32:
        fn = f.f_code.co_filename
        norm = fn.replace(os.sep, "/")
        if not any(f"/{p}/" in norm or norm.startswith(p + "/")
                   for p in skip_prefixes):
            parts = fn.rsplit(os.sep, 2)
            short = os.sep.join(parts[-2:]) if len(parts) > 1 else fn
            return f"{short}:{f.f_lineno} {f.f_code.co_name}"
        f = f.f_back
        depth += 1
    return ""


# ---------------------------------------------------------------------------
# head-side store (the trace_store cursor/dedup contract)
# ---------------------------------------------------------------------------


class ProfileStore:
    """Bounded store of collected profile batches with origin labels.

    Appends carry an absolute sequence number so the cluster adapter can
    ship deltas over the heartbeat with an acked cursor (same contract
    as :class:`ray_tpu.util.trace_store.TraceStore`); eviction past the
    cap silently advances the readable window."""

    def __init__(self, cap: Optional[int] = None):
        if cap is None:
            try:
                from ray_tpu import config

                cap = int(config.get("profile_store_max"))
            except Exception:
                cap = 2048
        self._lock = threading.Lock()
        self._dq: "deque[Dict[str, Any]]" = deque(maxlen=max(16, cap))
        self._total = 0

    def ingest(self, batches: List[Dict[str, Any]],
               labels: Optional[Dict[str, str]] = None) -> None:
        if not batches:
            return
        rx = time.time()
        with self._lock:
            for b in batches:
                if labels:
                    b = dict(b)
                    for k, v in labels.items():
                        b.setdefault(k, v)
                # receiver-side arrival stamp: the window filter in
                # merge_batches uses THIS clock as a fallback, so a
                # remote node's skewed wall clock cannot silently drop
                # its batches from a state.profile(seconds=...) window
                b.setdefault("_rx", rx)
                self._dq.append(b)
                self._total += 1

    def snapshot(self, limit: Optional[int] = None) -> List[Dict[str, Any]]:
        with self._lock:
            out = list(self._dq)
        return out[-limit:] if limit else out

    def since(self, cursor: int, max_n: int = 200
              ) -> Tuple[List[Dict[str, Any]], int]:
        """(batch, start): ``start`` is the absolute index of batch[0]
        (>= cursor when eviction skipped entries). Advance the cursor to
        ``start + len(batch)`` only after the receiver acked."""
        with self._lock:
            start_abs = self._total - len(self._dq)
            i = max(0, cursor - start_abs)
            batch = list(islice(self._dq, i, i + max_n))
            return batch, start_abs + i

    def __len__(self) -> int:
        with self._lock:
            return len(self._dq)

    def clear(self) -> None:
        with self._lock:
            self._dq.clear()


# ---------------------------------------------------------------------------
# merge + export (collapsed text, speedscope JSON)
# ---------------------------------------------------------------------------


def _proc_key(b: Dict[str, Any]) -> str:
    comp = b.get("component") or "proc"
    node = b.get("node_id") or "local"
    return f"{comp}@{node}/{b.get('pid')}"


def merge_batches(batches: List[Dict[str, Any]],
                  since: Optional[float] = None) -> Dict[str, Any]:
    """Merge collected batches per (node, pid, component) process, each
    holding per-thread stack counts. ``since`` keeps only batches whose
    window ended after that wall-clock time OR that ARRIVED at a store
    after it (the ``state.profile(seconds=...)`` window filter; the
    arrival stamp makes the filter robust to remote clock skew — a
    batch received after the window opened necessarily overlaps it, up
    to one push interval of slop)."""
    procs: Dict[str, Dict[str, Any]] = {}
    for b in batches:
        if since is not None and (b.get("t1") or 0) < since \
                and (b.get("_rx") or 0) < since:
            continue
        key = _proc_key(b)
        p = procs.get(key)
        if p is None:
            p = procs[key] = {
                "component": b.get("component") or "proc",
                "node_id": b.get("node_id") or "local",
                "worker_id": b.get("worker_id"),
                "pid": b.get("pid"),
                "threads": {},
                "idle_threads": {},
                "total": 0, "idle_total": 0, "dropped": 0,
                "t0": b.get("t0"), "t1": b.get("t1"),
            }
        p["t0"] = min(p["t0"], b.get("t0") or p["t0"])
        p["t1"] = max(p["t1"], b.get("t1") or p["t1"])
        p["total"] += int(b.get("total") or 0)
        p["idle_total"] += int(b.get("idle_total") or 0)
        p["dropped"] += int(b.get("dropped") or 0)
        for field, dest in (("samples", "threads"),
                            ("idle", "idle_threads")):
            for thread, stack, n in b.get(field) or ():
                tt = p[dest].setdefault(thread, {})
                sk = tuple(stack)
                tt[sk] = tt.get(sk, 0) + int(n)
    return {"processes": procs,
            "total": sum(p["total"] for p in procs.values()),
            "idle_total": sum(p["idle_total"] for p in procs.values()),
            "dropped": sum(p["dropped"] for p in procs.values())}


def top_self(merged: Dict[str, Any], component: Optional[str] = None,
             n: int = 20) -> List[Dict[str, Any]]:
    """On-CPU functions ranked by SELF samples (leaf-frame attribution)
    across the merged profile, optionally restricted to one component
    (``"driver"`` = the control plane). The direct input to "which
    functions is the driver burning that millisecond in?"."""
    counts: Dict[str, int] = {}
    total = 0
    for p in merged["processes"].values():
        if component is not None and p["component"] != component:
            continue
        for stacks in p["threads"].values():
            for stack, c in stacks.items():
                if not stack:
                    continue
                leaf = stack[-1]
                counts[leaf] = counts.get(leaf, 0) + c
                total += c
    ranked = sorted(counts.items(), key=lambda kv: -kv[1])[:n]
    return [{"function": fn, "self_samples": c,
             "self_pct": round(100.0 * c / total, 1) if total else 0.0}
            for fn, c in ranked]


def collapsed_text(merged: Dict[str, Any],
                   include_idle: bool = False) -> str:
    """Collapsed-stack lines (``proc;thread;frame;...;leaf N``) — paste
    into speedscope or feed flamegraph.pl."""
    lines: List[str] = []
    for key, p in sorted(merged["processes"].items()):
        sources = [p["threads"]]
        if include_idle:
            sources.append(p["idle_threads"])
        for src in sources:
            for thread, stacks in sorted(src.items()):
                for stack, c in sorted(stacks.items(), key=str):
                    lines.append(
                        ";".join([key, thread, *stack]) + f" {c}")
    return "\n".join(lines)


def speedscope_doc(merged: Dict[str, Any],
                   name: str = "ray_tpu profile") -> Dict[str, Any]:
    """Speedscope file-format document: ONE sampled profile per sampled
    (process, thread), a shared frame table, and per-profile weights
    that sum exactly to that thread's sample count (each sample weighs
    1). Open at https://speedscope.app."""
    frames: List[Dict[str, Any]] = []
    index: Dict[str, int] = {}

    def fidx(fname: str) -> int:
        i = index.get(fname)
        if i is None:
            i = index[fname] = len(frames)
            frames.append({"name": fname})
        return i

    profiles = []
    for key, p in sorted(merged["processes"].items()):
        for thread, stacks in sorted(p["threads"].items()):
            samples, weights = [], []
            for stack, c in sorted(stacks.items(), key=str):
                # one entry per UNIQUE stack weighted by its count: the
                # weights of a profile sum exactly to that thread's
                # sample count while staying compact for hot stacks
                samples.append([fidx(f) for f in stack])
                weights.append(c)
            end = sum(weights)
            profiles.append({
                "type": "sampled",
                "name": f"{key} {thread}",
                "unit": "none",
                "startValue": 0,
                "endValue": end,
                "samples": samples,
                "weights": weights,
            })
    return {
        "$schema": "https://www.speedscope.app/file-format-schema.json",
        "shared": {"frames": frames},
        "profiles": profiles,
        "name": name,
        "exporter": "ray_tpu",
        "activeProfileIndex": 0,
    }
