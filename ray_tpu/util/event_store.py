"""Head-side lifecycle-event store (the receiver half of the event plane).

``util/events.py`` is the recording half: every process records
lifecycle events into its bounded ring; collection drains the rings over
the existing channels (worker control pipe, GCS heartbeat) into ONE of
these per head process, each event stamped with origin labels
(``node_id`` / ``worker_id`` / ``component``) — the exact shape the
TraceStore gives spans and the metrics federation gives samples.
Reference role: the event head's aggregated table behind the dashboard's
event view.

Appends carry an absolute sequence number so the cluster adapter can
ship deltas over the heartbeat with an acked cursor (the same
cursor+dedup contract the task/trace/profile pipelines use); eviction
past the cap silently advances the readable window.
"""

from __future__ import annotations

import threading
from collections import deque
from itertools import islice
from typing import Any, Dict, List, Optional, Tuple


class EventStore:
    """Bounded store of collected lifecycle events with origin labels."""

    def __init__(self, cap: Optional[int] = None):
        if cap is None:
            try:
                from ray_tpu import config

                cap = int(config.get("event_store_max"))
            except Exception:
                cap = 16384
        self._lock = threading.Lock()
        self._dq: "deque[Dict[str, Any]]" = deque(maxlen=max(64, cap))
        self._total = 0  # events ever appended (absolute sequence)

    def ingest(self, events: List[Dict[str, Any]],
               labels: Optional[Dict[str, str]] = None) -> None:
        if not events:
            return
        with self._lock:
            for ev in events:
                if labels:
                    ev = dict(ev)
                    for k, v in labels.items():
                        ev.setdefault(k, v)
                self._dq.append(ev)
                self._total += 1

    def snapshot(self, limit: Optional[int] = None) -> List[Dict[str, Any]]:
        with self._lock:
            out = list(self._dq)
        return out[-limit:] if limit else out

    def since(self, cursor: int, max_n: int = 1000
              ) -> Tuple[List[Dict[str, Any]], int]:
        """(batch, start) where ``start`` is the absolute index of
        batch[0] (>= cursor when eviction skipped events). Advance the
        cursor to ``start + len(batch)`` only after the receiver acked."""
        with self._lock:
            start_abs = self._total - len(self._dq)
            i = max(0, cursor - start_abs)
            batch = list(islice(self._dq, i, i + max_n))
            return batch, start_abs + i

    def __len__(self) -> int:
        with self._lock:
            return len(self._dq)

    def clear(self) -> None:
        with self._lock:
            self._dq.clear()
