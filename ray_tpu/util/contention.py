"""Lightweight lock-contention profiler for the runtime's hot locks.

Green-field relative to the reference (Ray profiles contention with
external tools — py-spy, perf); on a 2-vCPU box the driver's single
dispatch lock IS the scalability story (BENCH multi-client inversion),
so the runtime carries its own instrumentation:

- :func:`timed_lock` / :func:`timed_rlock` wrap ``threading`` locks.
  The uncontended path costs ONE extra non-blocking acquire attempt and
  two unlocked integer adds — no clock read, no metric lock. Only a
  CONTENDED acquisition (the fast try failed) pays two clock reads and
  a histogram observe (``rtpu_lock_wait_seconds{lock=...}``).
- queue-wait sampling for thread-pool-style handoffs lives with the
  pools themselves (cluster/rpc.py observes
  ``rtpu_rpc_server_queue_wait_seconds``); this module only covers
  locks.
- :func:`summarize` feeds ``state.summarize_contention()`` and the
  dashboard's ``/api/contention``; a metrics collector exports the
  accumulators as ``rtpu_lock_{acquisitions,contended,wait_seconds_sum}``
  gauges so federation ships them like everything else.

Stats are PER NAME, not per instance: the driver's many worker send
locks share one "driver.worker_send" row. Accumulator updates are
unlocked plain-int adds — the GIL makes torn reads impossible and a
lost increment under a race costs accuracy a profiler doesn't need;
taking a lock to measure locks would add the very contention being
measured. Disable with ``RTPU_CONTENTION_PROFILER=0`` (wrappers then
return raw ``threading`` locks with zero overhead).
"""

from __future__ import annotations

import threading
import time
from time import perf_counter
from typing import Dict, Optional

#: contended waits shorter than this skip the histogram (they would only
#: bounce the histogram's own lock); the unlocked accumulators still see
#: them.
HISTOGRAM_MIN_WAIT_S = 5e-5

#: contended waits at least this long also become ``lock::<name>`` spans
#: when tracing is armed (contention slices on the Perfetto timeline)
TRACE_MIN_WAIT_S = 1e-3


class _LockStats:
    __slots__ = ("name", "acquisitions", "contended", "wait_total",
                 "wait_max")

    def __init__(self, name: str):
        self.name = name
        self.acquisitions = 0
        self.contended = 0
        self.wait_total = 0.0
        self.wait_max = 0.0


_stats_lock = threading.Lock()
_stats: Dict[str, _LockStats] = {}
_hist = None
_collector_registered = False


def _get_stats(name: str) -> _LockStats:
    with _stats_lock:
        st = _stats.get(name)
        if st is None:
            st = _stats[name] = _LockStats(name)
        _ensure_collector()
    return st


def _wait_hist():
    global _hist
    from ray_tpu.util import metric_defs, metrics

    if _hist is None or metrics.registered("rtpu_lock_wait_seconds") \
            is not _hist:
        _hist = metric_defs.get("rtpu_lock_wait_seconds")
    return _hist


def _ensure_collector() -> None:
    """Export the accumulators as gauges at every registry snapshot."""
    global _collector_registered
    if _collector_registered:
        return
    _collector_registered = True
    from ray_tpu.util import metric_defs, metrics

    def collect():
        acq = metric_defs.get("rtpu_lock_acquisitions")
        con = metric_defs.get("rtpu_lock_contended")
        tot = metric_defs.get("rtpu_lock_wait_seconds_sum")
        with _stats_lock:
            rows = list(_stats.values())
        for st in rows:
            tags = {"lock": st.name}
            acq.set(st.acquisitions, tags=tags)
            con.set(st.contended, tags=tags)
            tot.set(st.wait_total, tags=tags)

    metrics.register_collector(collect)


class _TimedLockBase:
    """Shared acquire/release timing over an inner threading lock.

    Duck-types the stdlib lock surface including the private Condition
    protocol (``_release_save``/``_acquire_restore``/``_is_owned``), so
    ``threading.Condition(timed_rlock(...))`` works — Condition's
    wait-path re-acquire bypasses the timing on purpose (parked waiters
    are not contention)."""

    __slots__ = ("_inner", "_stats", "_hist_key")

    def __init__(self, inner, name: str):
        self._inner = inner
        self._stats = _get_stats(name)
        self._hist_key = (("lock", name),)

    def acquire(self, blocking: bool = True, timeout: float = -1):
        st = self._stats
        st.acquisitions += 1
        inner = self._inner
        if inner.acquire(False):
            return True
        if not blocking:
            return False
        t0 = perf_counter()
        ok = inner.acquire(True, timeout)
        wait = perf_counter() - t0
        st.contended += 1
        st.wait_total += wait
        if wait > st.wait_max:
            st.wait_max = wait
        if wait >= HISTOGRAM_MIN_WAIT_S:
            try:
                _wait_hist()._observe_key(self._hist_key, wait)
            except Exception:
                pass
        if wait >= TRACE_MIN_WAIT_S:
            # contention slice on the unified timeline: only waits long
            # enough to be visible at trace zoom, only when tracing is
            # armed (uncontended/short paths never reach here)
            try:
                from ray_tpu.util import tracing

                if tracing.tracing_enabled():
                    end = time.time_ns()
                    tracing.record_span(f"lock::{st.name}",
                                        end - int(wait * 1e9), end)
            except Exception:
                pass
        return ok

    def release(self):
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self._inner.release()
        return False

    # -- Condition protocol (delegated, untimed) -----------------------

    def _is_owned(self):
        return self._inner._is_owned()

    def _release_save(self):
        return self._inner._release_save()

    def _acquire_restore(self, state):
        return self._inner._acquire_restore(state)


class TimedLock(_TimedLockBase):
    def __init__(self, name: str):
        super().__init__(threading.Lock(), name)

    def locked(self):
        # only here, not on the base: threading.RLock has no .locked()
        # until 3.12, so TimedRLock must not advertise it either.
        return self._inner.locked()

    def _is_owned(self):  # Condition fallback for plain locks
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def _release_save(self):
        self._inner.release()

    def _acquire_restore(self, state):
        self._inner.acquire()


class TimedRLock(_TimedLockBase):
    def __init__(self, name: str):
        super().__init__(threading.RLock(), name)


def enabled() -> bool:
    from ray_tpu import config

    return bool(config.get("contention_profiler"))


def timed_lock(name: str):
    """A ``threading.Lock`` with wait-time accounting under ``name``
    (raw lock when the profiler is disabled)."""
    return TimedLock(name) if enabled() else threading.Lock()


def timed_rlock(name: str):
    return TimedRLock(name) if enabled() else threading.RLock()


def summarize() -> Dict[str, Dict[str, float]]:
    """Per-lock contention totals for THIS process since start:
    {name: {acquisitions, contended, contended_pct, wait_total_s,
    wait_max_s}} sorted by total wait, worst first."""
    with _stats_lock:
        rows = list(_stats.values())
    out = {}
    for st in sorted(rows, key=lambda s: -s.wait_total):
        acq = st.acquisitions
        out[st.name] = {
            "acquisitions": acq,
            "contended": st.contended,
            "contended_pct": round(100.0 * st.contended / acq, 2)
            if acq else 0.0,
            "wait_total_s": round(st.wait_total, 6),
            "wait_max_s": round(st.wait_max, 6),
        }
    return out


def reset() -> None:
    """Zero the accumulators (bench A/B sections)."""
    with _stats_lock:
        rows = list(_stats.values())
    for st in rows:
        st.acquisitions = 0
        st.contended = 0
        st.wait_total = 0.0
        st.wait_max = 0.0


def top_waits(n: int = 3) -> Dict[str, float]:
    """The n locks with the largest cumulative wait: {name: seconds}."""
    s = summarize()
    return {k: v["wait_total_s"] for k, v in list(s.items())[:n]}
