"""User metrics API: Counter/Gauge/Histogram + Prometheus text exposition.

Role analog: ``python/ray/util/metrics.py`` over the reference's
OpenCensus pipeline (``src/ray/stats``) — here a process-local registry
with a Prometheus text-format dump served by the dashboard-lite HTTP
endpoint (``_private/metrics_agent.py`` analog).
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Sequence, Tuple

_registry_lock = threading.Lock()
_registry: Dict[str, "Metric"] = {}


class Metric:
    metric_type = "untyped"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Sequence[str]] = None):
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys or ())
        self._default_tags: Dict[str, str] = {}
        self._lock = threading.Lock()
        with _registry_lock:
            _registry[name] = self

    def set_default_tags(self, tags: Dict[str, str]) -> "Metric":
        self._default_tags = dict(tags)
        return self

    def _key(self, tags: Optional[Dict[str, str]]) -> Tuple:
        merged = {**self._default_tags, **(tags or {})}
        return tuple(sorted(merged.items()))

    def _samples(self) -> List[Tuple[Tuple, float]]:
        raise NotImplementedError


class Counter(Metric):
    metric_type = "counter"

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._values: Dict[Tuple, float] = {}

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        if value < 0:
            raise ValueError("counters only increase")
        k = self._key(tags)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + value

    def _samples(self):
        with self._lock:
            return list(self._values.items())


class Gauge(Metric):
    metric_type = "gauge"

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._values: Dict[Tuple, float] = {}

    def set(self, value: float, tags: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._values[self._key(tags)] = float(value)

    def inc(self, value: float = 1.0, tags=None) -> None:
        k = self._key(tags)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + value

    def dec(self, value: float = 1.0, tags=None) -> None:
        self.inc(-value, tags)

    def _samples(self):
        with self._lock:
            return list(self._values.items())


class Histogram(Metric):
    metric_type = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[Sequence[float]] = None,
                 tag_keys: Optional[Sequence[str]] = None):
        super().__init__(name, description, tag_keys)
        self.boundaries = sorted(boundaries or
                                 [0.001, 0.01, 0.1, 1, 10, 100, 1000])
        self._counts: Dict[Tuple, List[int]] = {}
        self._sums: Dict[Tuple, float] = {}
        self._totals: Dict[Tuple, int] = {}

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None) -> None:
        k = self._key(tags)
        with self._lock:
            if k not in self._counts:
                self._counts[k] = [0] * (len(self.boundaries) + 1)
                self._sums[k] = 0.0
                self._totals[k] = 0
            idx = bisect.bisect_left(self.boundaries, value)
            self._counts[k][idx] += 1
            self._sums[k] += value
            self._totals[k] += 1

    def _samples(self):
        with self._lock:
            return [(k, (list(c), self._sums[k], self._totals[k]))
                    for k, c in self._counts.items()]


def _escape_label(v: str) -> str:
    # Prometheus text format: \ -> \\, " -> \", newline -> \n
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace(
        "\n", "\\n")


def _fmt_tags(key: Tuple) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in key)
    return "{" + inner + "}"


def prometheus_text() -> str:
    """All registered metrics in Prometheus exposition format."""
    lines: List[str] = []
    with _registry_lock:
        metrics = list(_registry.values())
    for m in metrics:
        lines.append(f"# HELP {m.name} {m.description}")
        lines.append(f"# TYPE {m.name} {m.metric_type}")
        if isinstance(m, Histogram):
            for key, (counts, total_sum, total) in m._samples():
                cum = 0
                for b, c in zip(m.boundaries, counts):
                    cum += c
                    tags = dict(key)
                    tags["le"] = repr(b)
                    lines.append(
                        f"{m.name}_bucket{_fmt_tags(tuple(sorted(tags.items())))} {cum}")
                tags = dict(key)
                tags["le"] = "+Inf"
                lines.append(
                    f"{m.name}_bucket{_fmt_tags(tuple(sorted(tags.items())))} {total}")
                lines.append(f"{m.name}_sum{_fmt_tags(key)} {total_sum}")
                lines.append(f"{m.name}_count{_fmt_tags(key)} {total}")
        else:
            for key, val in m._samples():
                lines.append(f"{m.name}{_fmt_tags(key)} {val}")
    return "\n".join(lines) + "\n"


def clear_registry() -> None:
    with _registry_lock:
        _registry.clear()
