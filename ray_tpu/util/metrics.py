"""User metrics API: Counter/Gauge/Histogram + Prometheus text exposition
+ cluster-wide federation.

Role analog: ``python/ray/util/metrics.py`` over the reference's
OpenCensus pipeline (``src/ray/stats``) — a process-local registry with a
Prometheus text-format dump served by the dashboard-lite HTTP endpoint
(``_private/metrics_agent.py`` analog). Federation mirrors the reference's
agent pipeline shape: every process serializes its registry to plain
records and pushes *deltas* up one hop (worker -> driver over the control
pipe; node -> GCS on the heartbeat), so the head ``/metrics`` endpoint
exposes every process's samples as ONE Prometheus-scrapable target with
``node_id``/``worker_id``/``component`` origin labels.

Registration semantics (reference parity): re-creating a metric with an
existing name MERGES into the existing registration — both instances share
one backing store, so previously recorded samples are never orphaned.
Re-registering under a different metric type (or histogram boundaries)
raises.
"""

from __future__ import annotations

import bisect
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

_registry_lock = threading.Lock()
_registry: Dict[str, "Metric"] = {}

# Collector hooks: callables run right before every registry snapshot
# (local exposition, worker delta push, node heartbeat payload), so
# sampled gauges — queue depths, table sizes, arena usage — are refreshed
# at read time instead of taxing every mutation on the hot path
# (reference: opencensus gauge-callback role). Hooks must be fast and
# never raise (exceptions are swallowed; a broken hook loses its samples,
# not the scrape).
_collectors_lock = threading.Lock()
_collectors: List = []


def register_collector(fn) -> None:
    with _collectors_lock:
        if fn not in _collectors:
            _collectors.append(fn)


def unregister_collector(fn) -> None:
    with _collectors_lock:
        try:
            _collectors.remove(fn)
        except ValueError:
            pass


def _run_collectors() -> None:
    with _collectors_lock:
        hooks = list(_collectors)
    for fn in hooks:
        try:
            fn()
        except Exception:
            pass


class Metric:
    metric_type = "untyped"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Sequence[str]] = None):
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys or ())
        self._default_tags: Dict[str, str] = {}
        self._lock = threading.Lock()

    def _register(self) -> None:
        """Publish this metric, merging into an existing registration of
        the same name (see module docstring). Called by subclasses AFTER
        their sample storage exists, so merging can alias it."""
        with _registry_lock:
            existing = _registry.get(self.name)
            if existing is None or existing is self:
                _registry[self.name] = self
                return
            if existing.metric_type != self.metric_type:
                raise ValueError(
                    f"metric {self.name!r} already registered as "
                    f"{existing.metric_type}, cannot re-register as "
                    f"{self.metric_type}")
            self._merge_into(existing)

    def _merge_into(self, existing: "Metric") -> None:
        # share the lock; subclasses alias their sample storage too
        self._lock = existing._lock

    def set_default_tags(self, tags: Dict[str, str]) -> "Metric":
        self._default_tags = dict(tags)
        return self

    def _key(self, tags: Optional[Dict[str, str]]) -> Tuple:
        merged = {**self._default_tags, **(tags or {})}
        return tuple(sorted(merged.items()))

    def _samples(self) -> List[Tuple[Tuple, float]]:
        raise NotImplementedError


class Counter(Metric):
    metric_type = "counter"

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._values: Dict[Tuple, float] = {}
        self._register()

    def _merge_into(self, existing: "Metric") -> None:
        super()._merge_into(existing)
        self._values = existing._values

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        if value < 0:
            raise ValueError("counters only increase")
        self._inc_key(self._key(tags), value)

    def _inc_key(self, k: Tuple, value: float = 1.0) -> None:
        """Pre-sorted-key fast path (hot-loop callers cache tag tuples)."""
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + value

    def _samples(self):
        with self._lock:
            return list(self._values.items())


class Gauge(Metric):
    metric_type = "gauge"

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._values: Dict[Tuple, float] = {}
        self._register()

    def _merge_into(self, existing: "Metric") -> None:
        super()._merge_into(existing)
        self._values = existing._values

    def set(self, value: float, tags: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._values[self._key(tags)] = float(value)

    def inc(self, value: float = 1.0, tags=None) -> None:
        k = self._key(tags)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + value

    def dec(self, value: float = 1.0, tags=None) -> None:
        self.inc(-value, tags)

    def _samples(self):
        with self._lock:
            return list(self._values.items())


class Histogram(Metric):
    metric_type = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[Sequence[float]] = None,
                 tag_keys: Optional[Sequence[str]] = None):
        super().__init__(name, description, tag_keys)
        self.boundaries = sorted(boundaries or
                                 [0.001, 0.01, 0.1, 1, 10, 100, 1000])
        self._counts: Dict[Tuple, List[int]] = {}
        self._sums: Dict[Tuple, float] = {}
        self._totals: Dict[Tuple, int] = {}
        self._register()

    def _merge_into(self, existing: "Metric") -> None:
        if list(self.boundaries) != list(existing.boundaries):
            raise ValueError(
                f"histogram {self.name!r} already registered with "
                f"boundaries {existing.boundaries}, cannot re-register "
                f"with {self.boundaries}")
        super()._merge_into(existing)
        self._counts = existing._counts
        self._sums = existing._sums
        self._totals = existing._totals

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None) -> None:
        self._observe_key(self._key(tags), value)

    def _observe_key(self, k: Tuple, value: float) -> None:
        """Pre-sorted-key fast path for hot-loop callers that cache their
        tag tuples (the task flight recorder observes several phases per
        task; re-merging/sorting the same one-tag dict each time is pure
        overhead there)."""
        with self._lock:
            self._observe_locked(k, value)

    def observe_many(self, items) -> None:
        """Batch observe of (pre-sorted-key, value) pairs under ONE lock
        acquisition — the flight recorder records ~7 phases per finished
        task from several reader threads at once; per-observe locking
        would bounce this lock thousands of times a second."""
        with self._lock:
            for k, value in items:
                self._observe_locked(k, value)

    def _observe_locked(self, k: Tuple, value: float) -> None:
        if k not in self._counts:
            self._counts[k] = [0] * (len(self.boundaries) + 1)
            self._sums[k] = 0.0
            self._totals[k] = 0
        idx = bisect.bisect_left(self.boundaries, value)
        self._counts[k][idx] += 1
        self._sums[k] += value
        self._totals[k] += 1

    def _samples(self):
        with self._lock:
            return [(k, (list(c), self._sums[k], self._totals[k]))
                    for k, c in self._counts.items()]


def _escape_label(v: str) -> str:
    # Prometheus text format: \ -> \\, " -> \", newline -> \n
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace(
        "\n", "\\n")


def _fmt_tags(key: Tuple) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in key)
    return "{" + inner + "}"


# ----------------------------------------------------------------------
# plain-record form (what crosses process boundaries)
# ----------------------------------------------------------------------
#
# A record is a picklable dict:
#   {"name", "type", "desc", "samples", ["boundaries"]}
# with histogram sample values as (bucket_counts, sum, total) triples —
# exactly the in-registry shape, so export is a snapshot, not a transform.


def metric_record(m: Metric) -> Dict[str, Any]:
    rec: Dict[str, Any] = {"name": m.name, "type": m.metric_type,
                           "desc": m.description, "samples": m._samples()}
    if isinstance(m, Histogram):
        rec["boundaries"] = list(m.boundaries)
    return rec


def registry_records() -> List[Dict[str, Any]]:
    """Snapshot every registered metric as a plain record (running the
    sampled-gauge collector hooks first, so reads see fresh values)."""
    _run_collectors()
    with _registry_lock:
        metrics = list(_registry.values())
    return [metric_record(m) for m in metrics]


class DeltaExporter:
    """Ship only metrics whose samples changed since the last collect —
    the sender side of the federation push (reference metrics-agent delta
    exporter role). Cumulative values ride whole (receivers replace per
    metric name), so a lost push self-heals on the next change."""

    def __init__(self):
        self._fp: Dict[str, int] = {}

    def collect(self) -> List[Dict[str, Any]]:
        out = []
        for rec in registry_records():
            fp = hash(repr((rec["samples"], rec.get("boundaries"))))
            if self._fp.get(rec["name"]) != fp:
                self._fp[rec["name"]] = fp
                out.append(rec)
        return out


class FederationStore:
    """Receiver side: per-origin metric records with origin labels
    (worker_id / node_id / component), merged per metric name. Bounded by
    origin count; a re-pushed record replaces the previous one, so
    cumulative counters never double-count."""

    MAX_ORIGINS = 512

    def __init__(self):
        self._lock = threading.Lock()
        # origin -> {"labels": {...}, "records": {name: record}}
        self._origins: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()

    def ingest(self, origin: str, labels: Dict[str, str],
               records: List[Dict[str, Any]]) -> None:
        with self._lock:
            ent = self._origins.pop(origin, None)
            if ent is None:
                ent = {"labels": dict(labels), "records": {}}
            else:
                ent["labels"] = dict(labels)
            for rec in records:
                ent["records"][rec["name"]] = rec
            self._origins[origin] = ent
            while len(self._origins) > self.MAX_ORIGINS:
                self._origins.popitem(last=False)

    def export(self) -> List[Tuple[Dict[str, str], List[Dict[str, Any]]]]:
        """[(labels, records)] for every known origin (render/forward)."""
        with self._lock:
            return [(dict(e["labels"]), list(e["records"].values()))
                    for e in self._origins.values()]

    def clear(self) -> None:
        with self._lock:
            self._origins.clear()


#: process-wide store of remote-origin samples (driver: its workers;
#: daemon: its workers; head dashboard additionally pulls peers' via GCS)
federation = FederationStore()


def _render_scalar(lines: List[str], name: str, labels, samples) -> None:
    for key, val in samples:
        if labels:
            key = tuple(sorted({**dict(key), **labels}.items()))
        lines.append(f"{name}{_fmt_tags(key)} {val}")


def _render_histogram(lines: List[str], name: str, labels, boundaries,
                      samples) -> None:
    for key, (counts, total_sum, total) in samples:
        base = {**dict(key), **(labels or {})}
        cum = 0
        for b, c in zip(boundaries, counts):
            cum += c
            tags = dict(base)
            tags["le"] = repr(b)
            lines.append(
                f"{name}_bucket{_fmt_tags(tuple(sorted(tags.items())))} {cum}")
        tags = dict(base)
        tags["le"] = "+Inf"
        lines.append(
            f"{name}_bucket{_fmt_tags(tuple(sorted(tags.items())))} {total}")
        bkey = tuple(sorted(base.items()))
        lines.append(f"{name}_sum{_fmt_tags(bkey)} {total_sum}")
        lines.append(f"{name}_count{_fmt_tags(bkey)} {total}")


def prometheus_text(extra: Optional[List[Tuple[Dict[str, str],
                                               List[Dict[str, Any]]]]] = None
                    ) -> str:
    """Prometheus exposition of the local registry, plus optional remote
    origins (``extra``: [(origin_labels, records)]). Samples sharing a
    metric name are grouped under ONE HELP/TYPE header (the text format
    forbids repeating it); origin labels are merged into each remote
    sample's label set. Local samples stay unlabeled — single-process
    consumers see the exact pre-federation format."""
    groups: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()

    def add(labels, rec):
        g = groups.get(rec["name"])
        if g is None:
            groups[rec["name"]] = g = {"type": rec["type"],
                                       "desc": rec["desc"], "entries": []}
        elif g["type"] != rec["type"]:
            return  # cross-origin type conflict: keep the first seen
        g["entries"].append((labels, rec))

    for rec in registry_records():
        add(None, rec)
    for labels, recs in extra or ():
        for rec in recs:
            add(labels, rec)

    lines: List[str] = []
    for name, g in groups.items():
        lines.append(f"# HELP {name} {g['desc']}")
        lines.append(f"# TYPE {name} {g['type']}")
        for labels, rec in g["entries"]:
            if g["type"] == "histogram":
                _render_histogram(lines, name, labels,
                                  rec.get("boundaries") or [],
                                  rec["samples"])
            else:
                _render_scalar(lines, name, labels, rec["samples"])
    return "\n".join(lines) + "\n"


def registered(name: str) -> Optional[Metric]:
    """The currently registered instance for ``name`` (None if absent).
    Lets caches (metric_defs) notice a clear_registry and re-register."""
    return _registry.get(name)


def clear_registry() -> None:
    with _registry_lock:
        _registry.clear()
