"""Alerting watchdog: declarative rules over the metric registries.

Role analog: the reference dashboard's alerting surface (and every
prod cluster's Prometheus rules file), folded into the head process —
the metrics already exist (four planes feed them), so alerting is a
small evaluator, not a new pipeline. A background thread at the head
samples the merged metric view every ``alerts_interval_s`` seconds and
evaluates a declarative rule list; a rule that breaches for
``for_ticks`` consecutive ticks RAISES (one ``alert_raised`` lifecycle
event + the ``rtpu_alerts_active`` gauge), and clears only after
``clear_ticks`` consecutive healthy ticks (hysteresis — a metric
hovering at the threshold must not flap a page).

Rule kinds (each a plain dict — the whole rule table is data):

``gauge_above``     any sample of ``metric`` exceeds ``threshold``
``ratio_above``     sum(metric) / sum(denominator) exceeds ``threshold``
``hist_p_above``    the ``q`` quantile of ``metric``'s observations
                    WITHIN the last tick window (bucket deltas, not
                    cumulative history) exceeds ``threshold``; skipped
                    until ``min_count`` observations land in the window
``stall``           ``metric`` (a depth gauge) sits at/above
                    ``min_depth`` while ``flow`` (a counter) made no
                    progress across the window
``delta_above``     the summed counter ``metric`` grew by more than
                    ``threshold`` within one tick window (a RATE rule
                    over cumulative counters — the compile-storm shape)

The default table covers the failure modes this box actually produces:
heartbeat-gap stretch, worker-spawn stalls (zygote queueing), serve KV
pool exhaustion, scheduler queue stalls, serve SLO burn (TTFT/TPOT
histograms), and arena occupancy. ``RTPU_ALERTS=0`` kills the plane;
surfaced via ``state.list_alerts()`` / ``/api/alerts`` / ``rtpu
status``.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

#: consecutive breach/healthy ticks before raise/clear (hysteresis)
FOR_TICKS = 2
CLEAR_TICKS = 2

DEFAULT_RULES: List[Dict[str, Any]] = [
    {"name": "heartbeat_gap", "kind": "hist_p_above",
     "metric": "rtpu_gcs_heartbeat_gap_seconds", "q": 0.99,
     "threshold": 3.0, "min_count": 3, "severity": "warning",
     "description": "p99 inter-heartbeat gap stretched past 3s "
                    "(nominal 0.5s): GCS or sender contention"},
    {"name": "worker_spawn_stall", "kind": "hist_p_above",
     "metric": "rtpu_worker_spawn_seconds", "q": 0.5,
     "threshold": 5.0, "min_count": 1, "severity": "warning",
     "description": "median worker spawn >5s this window: fork/boot "
                    "queueing (the r8 zygote-burst signature)"},
    {"name": "kv_pool_exhaustion", "kind": "gauge_above",
     "metric": "rtpu_serve_pool_kv_used_fraction", "threshold": 0.95,
     "severity": "warning",
     "description": "a serve replica's KV block pool is >95% used: "
                    "admission sheds/preemption imminent"},
    {"name": "queue_stall", "kind": "stall",
     "metric": "rtpu_scheduler_ready_queue_depth",
     "flow": "rtpu_scheduler_tasks_dispatched_total", "min_depth": 1,
     "severity": "warning",
     "description": "ready tasks queued but nothing dispatched across "
                    "a whole window: resource deadlock or dead pool"},
    {"name": "serve_slo_ttft", "kind": "hist_p_above",
     "metric": "rtpu_serve_ttft_seconds", "q": 0.95,
     "threshold": 2.0, "min_count": 5, "severity": "warning",
     "description": "serve p95 time-to-first-token >2s this window"},
    {"name": "serve_slo_tpot", "kind": "hist_p_above",
     "metric": "rtpu_serve_tpot_seconds", "q": 0.95,
     "threshold": 0.5, "min_count": 20, "severity": "warning",
     "description": "serve p95 time-per-output-token >500ms this "
                    "window"},
    {"name": "arena_occupancy", "kind": "ratio_above",
     "metric": "rtpu_object_store_bytes_used",
     "denominator": "rtpu_object_store_capacity_bytes",
     "threshold": 0.9, "severity": "warning",
     "description": "shm arena >90% full: spills (and their disk-rate "
                    "ceiling) imminent"},
    {"name": "jit_compile_storm", "kind": "delta_above",
     "metric": "rtpu_jit_retraces_total", "threshold": 2.0,
     "severity": "warning",
     "description": "3+ jit retraces within one watchdog window: a "
                    "registered program is recompiling in a loop "
                    "(shape/dtype churn) — read the jit_recompile "
                    "events' signature diffs for the offending arg"},
    {"name": "hbm_occupancy", "kind": "ratio_above",
     "metric": "rtpu_tpu_hbm_used_bytes",
     "denominator": "rtpu_tpu_hbm_limit_bytes",
     "threshold": 0.92, "severity": "warning",
     "description": "device HBM >92% full: the next retrace or batch "
                    "bump OOMs — read /api/devices' live-buffer census "
                    "for what is resident"},
]

_lock = threading.Lock()
_state: Dict[str, Any] = {"enabled": None}


def _resolve() -> bool:
    with _lock:
        if _state["enabled"] is None:
            _state["enabled"] = os.environ.get("RTPU_ALERTS", "1") != "0"
        return _state["enabled"]


def alerts_enabled() -> bool:
    e = _state["enabled"]
    if e is None:
        return _resolve()
    return e


def _reset_for_tests() -> None:
    global _watchdog
    with _lock:
        _state["enabled"] = None
    _watchdog = None


# ---------------------------------------------------------------------------
# metric view: merged name -> [(tags_key, value)] across origins
# ---------------------------------------------------------------------------


def _merge_records(payloads: List[Tuple[dict, list]]) -> Dict[str, list]:
    """[(origin_labels, records)] -> {metric_name: [(key, value)]} with
    histogram values as (bucket_counts, sum, total, boundaries)."""
    view: Dict[str, list] = {}
    for _labels, records in payloads:
        for rec in records or ():
            samples = rec.get("samples") or []
            if not samples:
                continue
            rows = view.setdefault(rec["name"], [])
            if rec.get("type") == "histogram":
                bounds = rec.get("boundaries") or []
                for k, (counts, s, total) in samples:
                    rows.append((k, (list(counts), s, total, bounds)))
            else:
                rows.extend(samples)
    return view


def default_sample_fn() -> Dict[str, list]:
    """The head's merged metric view: this process's registry, its
    workers' federated samples, and — in cluster mode — every other
    node's latest heartbeat payload from the GCS."""
    from ray_tpu.util import metrics as _metrics

    payloads: List[Tuple[dict, list]] = [({}, _metrics.registry_records())]
    try:
        payloads.extend(_metrics.federation.export())
    except Exception:
        pass
    try:
        from ray_tpu.core import runtime as _rt_mod

        rt = _rt_mod._runtime
        cluster = getattr(rt, "cluster", None) if rt is not None else None
        if cluster is not None:
            remote = cluster.gcs.call("metrics_get",
                                      cluster.node_id, timeout=5)
            payloads.extend(remote or [])
    except Exception:
        pass
    return _merge_records(payloads)


# ---------------------------------------------------------------------------
# rule evaluation
# ---------------------------------------------------------------------------


def _hist_totals(rows: list):
    """Aggregate histogram samples: (summed bucket counts, total, bounds)."""
    counts: Optional[List[int]] = None
    total = 0
    bounds: list = []
    for _k, v in rows:
        c, _s, t, b = v
        if counts is None:
            counts = [0] * len(c)
            bounds = b
        if len(c) == len(counts):
            counts = [a + x for a, x in zip(counts, c)]
            total += t
    return counts or [], total, bounds


def _quantile(counts: List[int], total: int, bounds: list,
              q: float) -> float:
    """Upper-bound quantile from bucket counts (the Prometheus
    histogram_quantile convention: the bucket boundary the q-th
    observation falls under; +Inf bucket reports the top boundary)."""
    if total <= 0:
        return 0.0
    rank = q * total
    acc = 0
    for i, c in enumerate(counts):
        acc += c
        if acc >= rank:
            return float(bounds[i]) if i < len(bounds) else float(
                bounds[-1] if bounds else 0.0)
    return float(bounds[-1] if bounds else 0.0)


class Watchdog:
    """Evaluate a declarative rule table over the metric view on a
    fixed tick, with raise/clear hysteresis. ``evaluate_once`` is the
    whole engine — the thread just calls it on an interval — so tests
    drive ticks synthetically with a fake ``sample_fn``."""

    def __init__(self, rules: Optional[List[Dict[str, Any]]] = None,
                 sample_fn: Optional[Callable[[], Dict[str, list]]] = None,
                 interval_s: Optional[float] = None):
        if interval_s is None:
            try:
                from ray_tpu import config

                interval_s = float(config.get("alerts_interval_s"))
            except Exception:
                interval_s = 5.0
        self.rules = list(DEFAULT_RULES if rules is None else rules)
        self.sample_fn = sample_fn or default_sample_fn
        self.interval_s = interval_s
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # per-rule evaluation state: breach/ok streaks + active flag
        self._streak: Dict[str, int] = {}
        self._active: Dict[str, Dict[str, Any]] = {}
        # previous cumulative samples for windowed kinds
        self._prev: Dict[str, Any] = {}

    # -- per-kind checks (each returns (breached, observed value)) -----

    def _check(self, rule: Dict[str, Any],
               view: Dict[str, list]) -> Tuple[Optional[bool], float]:
        kind = rule["kind"]
        rows = view.get(rule["metric"]) or []
        if kind == "gauge_above":
            if not rows:
                return None, 0.0
            val = max(float(v) for _k, v in rows)
            return val > rule["threshold"], val
        if kind == "ratio_above":
            den_rows = view.get(rule["denominator"]) or []
            num = sum(float(v) for _k, v in rows)
            den = sum(float(v) for _k, v in den_rows)
            if den <= 0:
                return None, 0.0
            val = num / den
            return val > rule["threshold"], val
        if kind == "hist_p_above":
            counts, total, bounds = _hist_totals(rows)
            prev = self._prev.get(rule["name"]) or ([0] * len(counts), 0)
            pc, pt = prev
            if len(pc) != len(counts):
                pc, pt = [0] * len(counts), 0
            self._prev[rule["name"]] = (counts, total)
            win = [max(0, a - b) for a, b in zip(counts, pc)]
            wtotal = max(0, total - pt)
            if wtotal < rule.get("min_count", 1):
                return None, 0.0
            val = _quantile(win, wtotal, bounds, rule["q"])
            return val > rule["threshold"], val
        if kind == "delta_above":
            total = sum(float(v) for _k, v in rows)
            prev = self._prev.get(rule["name"])
            self._prev[rule["name"]] = total
            if prev is None:
                return None, 0.0  # first tick: no window yet
            delta = max(0.0, total - prev)
            return delta > rule["threshold"], delta
        if kind == "stall":
            flow_rows = view.get(rule["flow"]) or []
            depth = max((float(v) for _k, v in rows), default=0.0)
            flow = sum(float(v) for _k, v in flow_rows)
            pflow = self._prev.get(rule["name"])
            self._prev[rule["name"]] = flow
            if pflow is None:
                return None, depth
            breached = (depth >= rule.get("min_depth", 1)
                        and flow - pflow <= 0)
            return breached, depth
        return None, 0.0

    def evaluate_once(self,
                      view: Optional[Dict[str, list]] = None) -> List[dict]:
        """One watchdog tick: evaluate every rule, apply hysteresis,
        emit raise/clear events, refresh the active gauge. Returns the
        active alert list."""
        if view is None:
            view = self.sample_fn()
        from ray_tpu.util import events

        with self._lock:
            for rule in self.rules:
                name = rule["name"]
                try:
                    breached, val = self._check(rule, view)
                except Exception:
                    breached, val = None, 0.0
                if breached is None:
                    continue  # no data: streaks hold, nothing flaps
                streak = self._streak.get(name, 0)
                streak = (max(1, streak + 1) if breached
                          else min(-1, streak - 1))
                self._streak[name] = streak
                active = name in self._active
                if breached and not active and streak >= FOR_TICKS:
                    self._active[name] = {
                        "alert": name, "severity": rule["severity"],
                        "value": val, "threshold": rule["threshold"],
                        "description": rule["description"],
                        "since": time.time()}
                    events.emit("alert_raised", alert=name,
                                severity=rule["severity"], value=val,
                                threshold=rule["threshold"],
                                description=rule["description"])
                elif active:
                    if breached:
                        self._active[name]["value"] = val  # keep fresh
                    elif -streak >= CLEAR_TICKS:
                        self._active.pop(name, None)
                        events.emit("alert_cleared", alert=name,
                                    severity=rule["severity"], value=val,
                                    threshold=rule["threshold"])
            out = [dict(a) for a in self._active.values()]
        self._set_gauge(out)
        return out

    @staticmethod
    def _set_gauge(active: List[dict]) -> None:
        try:
            from ray_tpu.util import metric_defs as _md

            g = _md.get("rtpu_alerts_active")
            by_sev: Dict[str, int] = {"warning": 0, "error": 0}
            for a in active:
                by_sev[a.get("severity", "warning")] = by_sev.get(
                    a.get("severity", "warning"), 0) + 1
            for sev, n in by_sev.items():
                g.set(n, tags={"severity": sev})
        except Exception:
            pass

    def active(self) -> List[dict]:
        with self._lock:
            return [dict(a) for a in self._active.values()]

    # -- thread --------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="alerts-watchdog")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                if not _is_head():
                    # node daemons share DriverRuntime (and this hook)
                    # but must not evaluate: their alert events would
                    # duplicate the head's per condition. The adapter
                    # attaches after __init__, so this is a per-tick
                    # check, not a start-time one.
                    continue
                self.evaluate_once()
            except Exception:
                pass


def _is_head() -> bool:
    """True for the process that should evaluate rules: the local-mode
    driver (no cluster) or the cluster head's driver — never a node
    daemon (its metrics reach the head on heartbeats)."""
    try:
        from ray_tpu.core import runtime as _rt_mod

        rt = _rt_mod._runtime
        if rt is None:
            return False
        cluster = getattr(rt, "cluster", None)
        return cluster is None or bool(cluster.is_scheduler)
    except Exception:
        return False


_watchdog: Optional[Watchdog] = None


def start_watchdog() -> Optional[Watchdog]:
    """Start (once) the head-side watchdog thread; None when the plane
    is killed (``RTPU_ALERTS=0``)."""
    global _watchdog
    if not alerts_enabled():
        return None
    with _lock:
        if _watchdog is None:
            _watchdog = Watchdog()
            _watchdog.start()
        return _watchdog


def stop_watchdog() -> None:
    global _watchdog
    with _lock:
        wd = _watchdog
        _watchdog = None
    if wd is not None:
        wd.stop()


def active_alerts() -> List[dict]:
    """The raised-and-not-cleared alert list (empty when no watchdog)."""
    wd = _watchdog
    return wd.active() if wd is not None else []
