"""Device plane: the compiled-program registry — observability plane #6.

Five planes (metrics, flight recorder, tracing, profiling, lifecycle
events) cover the HOST runtime; this plane covers the layer that makes
the framework TPU-native: jitted XLA programs. The reference pays for
this layer with per-component C++ stats (arXiv:1712.05889 §4); a TPU
stack needs the XLA-level equivalent — compile-time/HBM/FLOP accounting
per compiled program.

Three surfaces on one registry:

**Registry** — :func:`registered_jit` wraps ``jax.jit`` at the hot
entry points (``TrainLoopHelper``, the serve engine's paged
decode/copy/gather/scatter programs, the RL learner update, model
multiplexing's draft/verify programs). Every compiled program registers
its name, abstract input signature, compile wall time, donation map,
and the backend's static ``cost_analysis`` (flops, bytes accessed) —
plus ``memory_analysis`` when ``RTPU_DEVICE_PLANE_MEMORY=1`` opts into
the second XLA compile it costs. Each probe is guarded for the
axon/old-jax sandbox (no ``_cache_size``, no cost model: degrade, never
fail a step). Disarmed cost is one dict get per call.

**Retrace detector** — compile detection is a ``_cache_size()`` probe
after each call (old jax falls back to a per-call signature set). A
recompile past a program's first emits ONE ``jit_recompile`` lifecycle
event carrying the shape/dtype/static-arg DIFF against the prior
signature — the thing you need to fix it — and feeds
``rtpu_jit_compiles_total{program}`` / ``rtpu_jit_retraces_total`` and
the ``jit_compile_storm`` alert rule (util/alerts.py).

**HBM census + attribution** — :func:`snapshot` bundles the program
table with ``tpu_info.hbm_usage`` watermarks and a live-buffer census
(``jax.live_arrays`` grouped by shape/dtype). Snapshots federate like
metrics: workers cast them over the control pipe ("device" cast),
node daemons ride the GCS heartbeat as idempotent per-node payloads,
and ``state.device_report()`` merges the cluster view for
``/api/devices`` / ``rtpu devices``. ``train/telemetry.py``, the serve
engine and the RL learner read :func:`program_flops_per_step` to
compute achieved FLOP/s and MFU from the cost model instead of
hand-maintained formulas (cost-analysis flops count every executed
flop, remat recompute included — callers that want MODEL flops, e.g.
bench's headline MFU, keep the analytic formula and report both).

Timing discipline: the plane never calls ``block_until_ready`` — the
wrapper measures call wall time (dispatch + first-execution on compile
calls, the existing ``record_compile`` convention); step-time
attribution stays with the callers' dependent ``device_get`` timing.

``RTPU_DEVICE_PLANE=0`` is the kill switch (plane is ON by default —
compiles are rare; per-call overhead is a dict get + two clock reads +
an int compare, A/B'd by bench.py ``device_plane_overhead``).
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

#: bounds — the registry is a bounded table like every plane's ring
MAX_PROGRAMS = 256
MAX_SIGS = 8          # signature history kept per program
MAX_CENSUS_GROUPS = 32  # top-N live-buffer groups by bytes

_state: Dict[str, Any] = {"enabled": None}
_lock = threading.Lock()


def _resolve() -> bool:
    with _lock:
        if _state["enabled"] is None:
            _state["enabled"] = (
                os.environ.get("RTPU_DEVICE_PLANE", "1") != "0")
        return _state["enabled"]


def device_plane_enabled() -> bool:
    """Hot-path arming check: one dict get (the events/tracing idiom)."""
    e = _state["enabled"]
    if e is None:
        return _resolve()
    return e


def enable_device_plane() -> None:
    _state["enabled"] = True


def disable_device_plane() -> None:
    _state["enabled"] = False


def _reset_for_tests() -> None:
    global _registry
    with _lock:
        _state["enabled"] = None
    _registry = CompiledProgramRegistry()


# lazily-bound builtin metrics; never allowed to fail a call
_m: Dict[str, Any] = {}


def _metric(name: str):
    from ray_tpu.util import metric_defs, metrics

    inst = _m.get(name)
    if inst is None or metrics.registered(name) is not inst:
        inst = _m[name] = metric_defs.get(name)
    return inst


# ---------------------------------------------------------------------------
# abstract signatures + diffs
# ---------------------------------------------------------------------------


def _describe_leaf(x: Any) -> str:
    """One leaf of an abstract signature: ``f32[4,8]``-style for arrays
    (anything with shape+dtype: jax arrays — donated/deleted ones keep
    their metadata — numpy arrays, ShapeDtypeStructs), a bounded repr
    for python statics (THE static-arg half of a retrace diff)."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return "%s[%s]" % (dtype, ",".join(str(d) for d in shape))
    r = repr(x)
    return "py:%s:%s" % (type(x).__name__,
                         r if len(r) <= 40 else r[:37] + "...")


def abstract_signature(args: tuple, kwargs: dict) -> Dict[str, str]:
    """{tree path: leaf description} for a call's arguments — the unit
    the retrace detector stores and diffs. Paths come from
    ``tree_flatten_with_path`` so the diff names the actual argument
    (``[0]['params']['w']``), not a flat index."""
    import jax

    leaves, _ = jax.tree_util.tree_flatten_with_path((args, kwargs))
    sig: Dict[str, str] = {}
    for path, leaf in leaves:
        key = jax.tree_util.keystr(path)
        # (args, kwargs) wrapper: strip the outer [0]/[1] for readability
        key = key.replace("[0]", "args", 1) if key.startswith("[0]") \
            else key.replace("[1]", "kwargs", 1)
        sig[key] = _describe_leaf(leaf)
    return sig


def signature_diff(old: Dict[str, str],
                   new: Dict[str, str]) -> Dict[str, Any]:
    """The payload of a ``jit_recompile`` event: what changed between
    the prior signature and the one that just forced a recompile."""
    changed = {p: {"was": old[p], "now": new[p]}
               for p in new if p in old and old[p] != new[p]}
    added = {p: new[p] for p in new if p not in old}
    removed = {p: old[p] for p in old if p not in new}
    out: Dict[str, Any] = {}
    if changed:
        out["changed"] = changed
    if added:
        out["added"] = added
    if removed:
        out["removed"] = removed
    return out


def _to_spec(x: Any) -> Any:
    """Array leaf -> ShapeDtypeStruct (so ``.lower()`` for cost analysis
    never touches buffers — donated inputs are already invalid by the
    time the compile is detected); everything else passes through."""
    import jax

    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None and not isinstance(
            x, jax.ShapeDtypeStruct):
        try:
            return jax.ShapeDtypeStruct(tuple(shape), dtype)
        except Exception:
            return x
    return x


def _normalize_cost(cost: Any) -> Optional[Dict[str, float]]:
    """``cost_analysis()`` returns a dict (Lowered) or a list of dicts
    (Compiled, one per partition) depending on the jax version — fold to
    one {metric: value} dict of the keys the plane reports."""
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
    if not isinstance(cost, dict):
        return None
    out: Dict[str, float] = {}
    for key in ("flops", "bytes accessed", "transcendentals"):
        v = cost.get(key)
        if isinstance(v, (int, float)):
            out[key.replace(" ", "_")] = float(v)
    return out or None


def _normalize_memory(mem: Any) -> Optional[Dict[str, int]]:
    out: Dict[str, int] = {}
    for attr in ("generated_code_size_in_bytes", "argument_size_in_bytes",
                 "output_size_in_bytes", "temp_size_in_bytes",
                 "alias_size_in_bytes"):
        v = getattr(mem, attr, None)
        if isinstance(v, int):
            out[attr.replace("_in_bytes", "")] = v
    return out or None


def _memory_analysis_wanted() -> bool:
    """memory_analysis costs a SECOND XLA compile of the program (the
    AOT ``lower().compile()`` path) — opt-in only."""
    return os.environ.get("RTPU_DEVICE_PLANE_MEMORY", "0") == "1"


# ---------------------------------------------------------------------------
# the registry
# ---------------------------------------------------------------------------


class CompiledProgramRegistry:
    """Per-process table of compiled programs (bounded, LRU on insert).

    One row per program NAME — a re-created wrapper (a second serve
    engine in the same process) folds into the same row: its fresh
    compile counts, but an already-seen signature is not a retrace."""

    def __init__(self):
        self._lock = threading.Lock()
        self._programs: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self._version = 0

    @property
    def version(self) -> int:
        return self._version

    def _row(self, name: str, component: str) -> Dict[str, Any]:
        rec = self._programs.get(name)
        if rec is None:
            while len(self._programs) >= MAX_PROGRAMS:
                self._programs.popitem(last=False)
            rec = {"program": name, "component": component, "steps": 1,
                   "donate": [], "sigs": [], "compiles": 0, "retraces": 0,
                   "compile_s_total": 0.0, "compile_s_last": 0.0,
                   "calls": 0, "cost": None, "memory": None,
                   "last_compile_ts": 0.0}
            self._programs[name] = rec
        return rec

    def record_compile(self, name: str, component: str, *,
                       sig: Optional[Dict[str, str]], seconds: float,
                       donate: Tuple[int, ...] = (), steps: int = 1,
                       cost: Optional[Dict[str, float]] = None,
                       memory: Optional[Dict[str, int]] = None,
                       ) -> Optional[Dict[str, Any]]:
        """Fold one compile event into the table. Returns the signature
        diff when this signature is NOVEL past the row's first (i.e. a
        retrace someone should look at), else None."""
        diff = None
        with self._lock:
            rec = self._row(name, component)
            rec["compiles"] += 1
            rec["calls"] += 1
            rec["compile_s_total"] += seconds
            rec["compile_s_last"] = seconds
            rec["last_compile_ts"] = time.time()
            # always refresh: cost and steps must stay a consistent pair
            # (a re-jitted scan with a different length updates both)
            rec["steps"] = max(1, int(steps))
            if donate:
                rec["donate"] = sorted(set(rec["donate"]) | set(donate))
            if cost:
                rec["cost"] = cost
            if memory:
                rec["memory"] = memory
            if sig is not None and sig not in rec["sigs"]:
                if rec["sigs"]:
                    rec["retraces"] += 1
                    diff = signature_diff(rec["sigs"][-1], sig)
                rec["sigs"].append(sig)
                del rec["sigs"][:-MAX_SIGS]
            self._version += 1
        return diff

    def note_call(self, name: str, component: str = "") -> None:
        # hot path (every armed registered-jit call): once the row
        # exists, the increment rides the GIL — a slightly racy counter
        # beats a lock acquisition per jit dispatch
        rec = self._programs.get(name)
        if rec is None:
            with self._lock:
                rec = self._row(name, component)
        rec["calls"] += 1

    def program(self, name: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            rec = self._programs.get(name)
            return None if rec is None else _copy_row(rec)

    def rows(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [_copy_row(r) for r in self._programs.values()]

    def flops_per_step(self, name: str) -> Optional[float]:
        """Cost-analysis flops for ONE step of ``name`` (a scanned
        multi-step program's per-call flops divided by its scan length).
        None when the backend gave no cost model — callers fall back to
        their analytic formula."""
        with self._lock:
            rec = self._programs.get(name)
            if rec is None or not rec["cost"]:
                return None
            flops = rec["cost"].get("flops")
            if not flops or flops <= 0:
                return None
            return float(flops) / max(1, int(rec["steps"]))


def _copy_row(rec: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(rec)
    out["sigs"] = [dict(s) for s in rec["sigs"]]
    out["donate"] = list(rec["donate"])
    if rec.get("cost"):
        out["cost"] = dict(rec["cost"])
    if rec.get("memory"):
        out["memory"] = dict(rec["memory"])
    return out


_registry = CompiledProgramRegistry()


def registry() -> CompiledProgramRegistry:
    return _registry


def program_flops_per_step(name: str) -> Optional[float]:
    return _registry.flops_per_step(name)


# ---------------------------------------------------------------------------
# the jit wrapper
# ---------------------------------------------------------------------------


class RegisteredFunction:
    """``jax.jit`` + registration. Calls forward to the jitted function;
    when the plane is armed, a ``_cache_size()`` probe after each call
    detects fresh compiles (old jax without the probe: per-call
    signature set). Only compile calls pay the slow path (signature
    walk, ``lower().cost_analysis()``, event/metric emission)."""

    def __init__(self, fn: Callable, *, name: str, component: str = "",
                 steps: int = 1, **jit_kwargs: Any):
        import jax

        self._name = name
        self._component = component
        self._steps = int(steps)
        self._jit_kwargs = jit_kwargs
        self._jitted = jax.jit(fn, **jit_kwargs)
        donate = jit_kwargs.get("donate_argnums") or ()
        self._donate = (donate,) if isinstance(donate, int) else \
            tuple(donate)
        # NEVER store the bound ``_cache_size`` method: a bound method
        # of the C++ PjitFunction kept on this wrapper makes the
        # engine <-> jit reference cycle uncollectable (measured: the
        # serve engine — and every arena weight view it aliases — then
        # survives ``del`` + gc.collect() forever). Keep only a flag
        # and re-``getattr`` per probe; the temporary method dies with
        # the call frame.
        self._has_probe = callable(getattr(self._jitted, "_cache_size",
                                           None))
        self._cache_size = 0
        self._known_keys: set = set()  # fallback-path signature keys
        # under an OUTER trace (a registered step_fn called inside a
        # registered scanned program) the inner call is a trace, not a
        # device program — skip its bookkeeping
        clean = getattr(getattr(jax, "core", None),
                        "trace_state_clean", None)
        self._trace_clean = clean if callable(clean) else None

    @property
    def name(self) -> str:
        return self._name

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        if not device_plane_enabled():
            return self._jitted(*args, **kwargs)
        if self._trace_clean is not None:
            try:
                if not self._trace_clean():
                    return self._jitted(*args, **kwargs)
            except Exception:
                self._trace_clean = None
        t0 = time.perf_counter()
        out = self._jitted(*args, **kwargs)
        dt = time.perf_counter() - t0
        compiled = False
        if self._has_probe:
            try:
                n = self._jitted._cache_size()
                compiled = n != self._cache_size
                self._cache_size = n
            except Exception:
                self._has_probe = False  # old/odd jax: fall through
        if not self._has_probe:
            try:
                key = _hash_sig(args, kwargs)
                compiled = key not in self._known_keys
                self._known_keys.add(key)
            except Exception:
                compiled = False
        try:
            if compiled:
                self._on_compile(args, kwargs, dt)
            else:
                _registry.note_call(self._name, self._component)
        except Exception:
            pass  # the plane must never fail a step
        return out

    # AOT passthroughs so registered functions stay drop-in for jax.jit
    def lower(self, *args: Any, **kwargs: Any):
        return self._jitted.lower(*args, **kwargs)

    def eval_shape(self, *args: Any, **kwargs: Any):
        return self._jitted.eval_shape(*args, **kwargs)

    # -- slow path: one compile event ----------------------------------

    def _on_compile(self, args: tuple, kwargs: dict,
                    seconds: float) -> None:
        sig = None
        try:
            sig = abstract_signature(args, kwargs)
        except Exception:
            pass
        cost = memory = None
        try:
            import jax

            specs_a, specs_k = jax.tree_util.tree_map(
                _to_spec, (args, kwargs))
            low = self._jitted.lower(*specs_a, **specs_k)
            cost = _normalize_cost(low.cost_analysis())
            if _memory_analysis_wanted():
                memory = _normalize_memory(low.compile().memory_analysis())
        except Exception:
            pass  # axon/old-jax sandbox: no cost model is fine
        _record_compile_event(
            self._name, self._component, sig=sig, seconds=seconds,
            donate=self._donate, steps=self._steps, cost=cost,
            memory=memory)


def _hash_sig(args: tuple, kwargs: dict) -> Tuple:
    """Hashable per-call key for the no-_cache_size fallback path."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    return (treedef,) + tuple(
        (tuple(x.shape), str(x.dtype))
        if hasattr(x, "shape") and hasattr(x, "dtype")
        else (type(x).__name__, repr(x)[:40]) for x in leaves)


def _record_compile_event(name: str, component: str, *, sig, seconds,
                          donate=(), steps=1, cost=None,
                          memory=None) -> None:
    """THE compile-event sink (shared by the jit wrapper and the eager
    ``tracked_call`` hook): registry fold, retrace event, metrics,
    trace span."""
    diff = _registry.record_compile(
        name, component, sig=sig, seconds=seconds, donate=donate,
        steps=steps, cost=cost, memory=memory)
    try:
        _metric("rtpu_jit_compiles_total").inc(1, tags={"program": name})
        _metric("rtpu_jit_compile_seconds").observe(
            seconds, tags={"program": name})
        if diff:
            _metric("rtpu_jit_retraces_total").inc(
                1, tags={"program": name})
    except Exception:
        pass
    if diff:
        try:
            from ray_tpu.util import events

            events.emit("jit_recompile", program=name,
                        component=component,
                        seconds=round(seconds, 4), diff=diff)
        except Exception:
            pass
    try:
        from ray_tpu.util import tracing

        if tracing.tracing_enabled():
            end = time.time_ns()
            tracing.record_span(
                "device::compile", end - int(seconds * 1e9), end,
                {"program": name, "component": component,
                 "retrace": bool(diff),
                 **({"flops": cost["flops"]}
                    if cost and "flops" in cost else {})})
    except Exception:
        pass


def registered_jit(fn: Optional[Callable] = None, *, name: str,
                   component: str = "", steps: int = 1,
                   **jit_kwargs: Any):
    """``jax.jit`` with device-plane registration (decorator-friendly).

    ``name`` is the program's registry identity (``"serve::decode"``);
    ``steps`` declares a scanned multi-step program's scan length so
    ``program_flops_per_step`` can report per-step flops."""
    if fn is None:
        return lambda f: RegisteredFunction(
            f, name=name, component=component, steps=steps, **jit_kwargs)
    return RegisteredFunction(fn, name=name, component=component,
                              steps=steps, **jit_kwargs)


def tracked_call(name: str, component: str, fn: Callable[[], Any],
                 args: tuple, statics: Optional[dict] = None) -> Any:
    """Registry hook for EAGER dispatchers (``ops.flash_attention`` is
    deliberately unjitted so ``impl="auto"`` resolves per trace): a
    novel (arrays, statics) signature means the internals compiled —
    record it as a compile of ``name``; known signatures count a call."""
    if not device_plane_enabled():
        return fn()
    t0 = time.perf_counter()
    out = fn()
    dt = time.perf_counter() - t0
    try:
        sig = abstract_signature(args, {**(statics or {})})
        rec = _registry.program(name)
        if rec is None or sig not in rec["sigs"]:
            _record_compile_event(name, component, sig=sig, seconds=dt)
        else:
            _registry.note_call(name, component)
    except Exception:
        pass
    return out


# ---------------------------------------------------------------------------
# HBM census + snapshots (the federated unit)
# ---------------------------------------------------------------------------


def live_buffer_census() -> Optional[Dict[str, Any]]:
    """Live device arrays grouped by (dtype, shape) — top groups by
    bytes. None when jax was never imported in this process (zygote
    workers must not pay a jax import for a census)."""
    if "jax" not in sys.modules:
        return None
    try:
        import jax

        arrs = jax.live_arrays()
    except Exception:
        return None
    groups: Dict[Tuple[str, Tuple[int, ...]], List[int]] = {}
    total_bytes = 0
    n = 0
    for a in arrs:
        try:
            key = (str(a.dtype), tuple(a.shape))
            nbytes = int(a.nbytes)
        except Exception:
            continue
        ent = groups.setdefault(key, [0, 0])
        ent[0] += 1
        ent[1] += nbytes
        total_bytes += nbytes
        n += 1
    top = sorted(groups.items(), key=lambda kv: -kv[1][1])
    return {
        "buffers": n, "bytes": total_bytes,
        "groups": [{"dtype": k[0],
                    "shape": list(k[1]),
                    "count": c, "bytes": b}
                   for k, (c, b) in top[:MAX_CENSUS_GROUPS]]}


def _hbm() -> Optional[Dict[str, int]]:
    if "jax" not in sys.modules:
        return None
    try:
        from ray_tpu.util.tpu_info import hbm_usage

        return hbm_usage()
    except Exception:
        return None


def snapshot(min_version: Optional[int] = None,
             census: bool = True) -> Optional[Dict[str, Any]]:
    """This process's device-plane unit: program table + HBM watermarks
    + live-buffer census. ``min_version`` gates the push paths — None
    when nothing changed since (an empty registry never ships)."""
    reg = _registry
    with reg._lock:
        version = reg._version
        if min_version is not None and version <= min_version:
            return None
        programs = [_copy_row(r) for r in reg._programs.values()]
    snap: Dict[str, Any] = {"pid": os.getpid(), "version": version,
                            "programs": programs}
    hbm = _hbm()
    if hbm:
        snap["hbm"] = hbm
    if census:
        c = live_buffer_census()
        if c:
            snap["live_buffers"] = c
    try:
        _metric("rtpu_device_programs").set(len(programs))
        if census and snap.get("live_buffers"):
            _metric("rtpu_device_live_buffers").set(
                snap["live_buffers"]["buffers"])
            _metric("rtpu_device_live_buffer_bytes").set(
                snap["live_buffers"]["bytes"])
    except Exception:
        pass
    return snap


class DeviceStore:
    """Receiver side (driver/daemon): latest snapshot per origin with
    origin labels — snapshot-replace semantics like the metrics
    FederationStore (registry rows are mutable state, not a stream)."""

    MAX_ORIGINS = 256

    def __init__(self):
        self._lock = threading.Lock()
        self._origins: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()

    def ingest(self, origin: str, labels: Dict[str, str],
               snap: Dict[str, Any]) -> None:
        with self._lock:
            self._origins.pop(origin, None)
            self._origins[origin] = {"labels": dict(labels),
                                     "snap": snap}
            while len(self._origins) > self.MAX_ORIGINS:
                self._origins.popitem(last=False)

    def export(self) -> List[Dict[str, Any]]:
        """[{**snap, **labels}] for every known origin."""
        with self._lock:
            return [{**e["snap"], **e["labels"]}
                    for e in self._origins.values()]

    def clear(self) -> None:
        with self._lock:
            self._origins.clear()


def node_processes(rt: Any = None,
                   component: Optional[str] = None) -> List[Dict[str, Any]]:
    """This NODE's process entries: the local process's snapshot plus
    every worker snapshot its DeviceStore ingested — the per-node unit
    the adapter ships on heartbeats."""
    out: List[Dict[str, Any]] = []
    snap = snapshot()
    if snap and (snap["programs"] or snap.get("hbm")
                 or snap.get("live_buffers")):
        ent = dict(snap)
        if component:
            ent["component"] = component
        out.append(ent)
    store = getattr(rt, "device_store", None) if rt is not None else None
    if store is not None:
        out.extend(store.export())
    return out


def merge_report(entries: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold process entries (each a labeled snapshot) into the
    ``state.device_report()`` shape: flat program rows with origin
    labels, per-process HBM/census, and cluster totals."""
    programs: List[Dict[str, Any]] = []
    processes: List[Dict[str, Any]] = []
    totals = {"processes": 0, "programs": 0, "compiles": 0,
              "retraces": 0, "live_buffer_bytes": 0}
    hbm_used = hbm_limit = 0
    for ent in entries:
        labels = {k: ent[k] for k in ("node_id", "worker_id", "component",
                                      "pid") if k in ent}
        proc: Dict[str, Any] = dict(labels)
        proc["programs"] = len(ent.get("programs") or ())
        if ent.get("hbm"):
            proc["hbm"] = ent["hbm"]
            hbm_used += int(ent["hbm"].get("bytes_in_use", 0))
            hbm_limit += int(ent["hbm"].get("bytes_limit", 0))
        if ent.get("live_buffers"):
            proc["live_buffers"] = ent["live_buffers"]
            totals["live_buffer_bytes"] += int(
                ent["live_buffers"].get("bytes", 0))
        processes.append(proc)
        totals["processes"] += 1
        for row in ent.get("programs") or ():
            r = dict(row)
            r.update(labels)
            programs.append(r)
            totals["programs"] += 1
            totals["compiles"] += int(row.get("compiles", 0))
            totals["retraces"] += int(row.get("retraces", 0))
    if hbm_limit:
        totals["hbm"] = {"bytes_in_use": hbm_used,
                         "bytes_limit": hbm_limit}
    programs.sort(key=lambda r: (-r.get("compile_s_total", 0.0),
                                 r.get("program", "")))
    return {"processes": processes, "programs": programs,
            "totals": totals}
