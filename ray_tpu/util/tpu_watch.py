"""Opportunistic TPU bench watcher — catch the chip whenever the tunnel is up.

The axon tunnel on this box is intermittent (down for hours at a time;
rounds 1-3 never recorded a real-TPU number because the bench only ran at
end-of-round).  This daemon inverts the bet: it probes the tunnel with a
cheap child-process device query every ``--interval`` seconds for the whole
round, and the first time the probe succeeds it

  1. runs an on-chip Pallas flash-attention numerics check (fwd AND bwd,
     kernel vs blockwise-XLA reference, plus a long-sequence bwd that would
     OOM without the memory-efficient custom VJP), and
  2. runs the full ``bench.py`` measurement on the chip,

caching both to ``BENCH_TPU_LAST_GOOD.json``.  ``bench.py`` consults that
cache when its own end-of-round probe finds the tunnel down, so one window
of tunnel uptime anywhere in the round produces the real MFU number.

Every probe attempt is appended to ``TPU_WATCH_LOG.jsonl`` — if the tunnel
never comes up, the log is the proof that we watched all round.

The parent process NEVER imports jax (a bare device query on the axon
backend can hang for minutes); all chip contact happens in child processes
with hard timeouts.  Role analog: none in the reference — this is
infrastructure for the intermittent-tunnel dev box.

Run: ``ray_tpu bench --watch`` or ``python -m ray_tpu.util.tpu_watch``.
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
DEFAULT_LOG = os.path.join(_REPO, "TPU_WATCH_LOG.jsonl")
DEFAULT_CACHE = os.path.join(_REPO, "BENCH_TPU_LAST_GOOD.json")
DEFAULT_PIDFILE = os.path.join(_REPO, ".tpu_watch.pid")


# ---------------------------------------------------------------------------
# single-instance hygiene (ISSUE 7 satellite): CLAUDE.md says start the
# watcher every session, so starting must be IDEMPOTENT — a live watcher
# is adopted (pidfile rewritten), duplicates are killed, and --status
# answers "is one running?" without side effects. r10 found three
# 7-12h-old leaked watchers, each with its own jax-importing probe
# children contending for the 2 cores.
# ---------------------------------------------------------------------------


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True


def _cmdline(pid: int) -> str:
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            return f.read().replace(b"\0", b" ").decode("utf-8", "replace")
    except OSError:
        return ""


def _is_watcher(pid: int) -> bool:
    """A long-running watcher parent — NOT its --numerics/--status
    children, not the bench/sweep children (different cmdlines), not a
    bounded one-shot refresh (--iterations), and not a WRAPPER process
    (`timeout ... python -m ...`, `bash -c '... tpu_watch ...'`) that
    merely carries the module string in its cmdline (the pkill -f
    self-match class CLAUDE.md warns about) — only direct python
    invocations are adoptable/killable."""
    cl = _cmdline(pid)
    if ("ray_tpu.util.tpu_watch" not in cl or "--numerics" in cl
            or "--status" in cl or "--iterations" in cl):
        return False
    first = cl.split()[0] if cl.split() else ""
    return "python" in os.path.basename(first)


def find_watchers(exclude: int = -1):
    """Pids of running watcher parents, oldest (lowest start) first."""
    out = []
    for ent in os.listdir("/proc"):
        if not ent.isdigit():
            continue
        pid = int(ent)
        if pid == exclude or pid == os.getpid():
            continue
        if _is_watcher(pid):
            out.append(pid)
    return sorted(out)


def read_pidfile(path: str):
    try:
        with open(path) as f:
            return int(f.read().strip())
    except (OSError, ValueError):
        return None


def write_pidfile(path: str, pid: int) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(str(pid))
    os.replace(tmp, path)


def watcher_status(pidfile: str = DEFAULT_PIDFILE,
                   log_path: str = DEFAULT_LOG,
                   cache_path: str = DEFAULT_CACHE,
                   scan=find_watchers) -> dict:
    """One dict answering "is a watcher running, and what has it seen?"
    (``--status``). ``scan`` is injectable for tests."""
    pid = read_pidfile(pidfile)
    pid_ok = pid is not None and _pid_alive(pid) and _is_watcher(pid)
    others = [p for p in scan() if p != pid]
    last = None
    try:
        with open(log_path, "rb") as f:
            tail = f.readlines()[-1]
        last = json.loads(tail)
    except (OSError, IndexError, json.JSONDecodeError):
        pass
    cache_age = None
    try:
        with open(cache_path) as f:
            cache_age = round(time.time() - json.load(f)["ts"])
    except Exception:
        pass
    return {
        "running": pid_ok or bool(others),
        "pid": pid if pid_ok else (others[0] if others else None),
        "pidfile_stale": pid is not None and not pid_ok,
        "unadopted_watchers": others,
        "last_log": last,
        "cache_age_s": cache_age,
    }


def ensure_single_instance(pidfile: str, force: bool,
                           scan=find_watchers) -> bool:
    """Idempotent-start gate. Returns True when THIS process should
    proceed to watch (pidfile now holds our pid). With a live watcher
    already running: adopt it into the pidfile, kill any duplicates, and
    return False. ``--force`` kills everything found and starts fresh.

    The whole decision runs under an O_EXCL gate lock (failpoints'
    once=PATH election pattern): two near-simultaneous starts must not
    each scan, see the other mid-gate, mutually "adopt", and BOTH exit —
    leaving no watcher at all. A lock older than 60s is a crashed gate
    and is broken."""
    import signal

    lock = pidfile + ".lock"
    lock_fd = None
    deadline = time.monotonic() + 75.0
    while lock_fd is None:
        try:
            lock_fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except OSError:
            try:
                if time.time() - os.path.getmtime(lock) > 60.0:
                    os.unlink(lock)  # crashed gate: break the lock
                    continue
            except OSError:
                continue  # holder just finished; retry the open
            if time.monotonic() > deadline:
                # a healthy holder decides for both of us; defer to it
                print("tpu_watch start gate busy; deferring to the "
                      "concurrent starter")
                return False
            time.sleep(0.5)
    try:
        return _gate_decision_locked(pidfile, force, scan, signal)
    finally:
        os.close(lock_fd)
        try:
            os.unlink(lock)
        except OSError:
            pass


def _gate_decision_locked(pidfile: str, force: bool, scan, signal) -> bool:
    pid = read_pidfile(pidfile)
    keep = pid if (pid is not None and _pid_alive(pid)
                   and _is_watcher(pid)) else None
    others = [p for p in scan() if p != keep]
    if force:
        for p in ([keep] if keep else []) + others:
            try:
                os.kill(p, signal.SIGTERM)
            except OSError:
                pass
        keep, others = None, []
    if keep is None and others:
        keep = others.pop(0)  # adopt the stalest leaked watcher
    # duplicates beyond the adopted one are leaks: kill them
    for p in others:
        try:
            os.kill(p, signal.SIGTERM)
        except OSError:
            pass
    if keep is not None:
        write_pidfile(pidfile, keep)
        print(f"tpu_watch already running (pid {keep}); adopted into "
              f"{pidfile}"
              + (f", killed {len(others)} duplicate(s)" if others else ""))
        return False
    write_pidfile(pidfile, os.getpid())
    return True


def _now_iso() -> str:
    return datetime.datetime.now(datetime.timezone.utc).isoformat(
        timespec="seconds")


def _append_log(log_path: str, record: dict) -> None:
    record = {"ts": round(time.time(), 1), "iso": _now_iso(), **record}
    with open(log_path, "a") as f:
        f.write(json.dumps(record) + "\n")


def probe(timeout: float = 25.0) -> dict:
    """Cheap child-process device query (cold runtime start ~7s healthy)."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.config.update('jax_platforms', 'axon'); "
             "d = jax.devices(); print('NDEV', len(d), getattr(d[0], 'device_kind', '?'))"],
            capture_output=True, text=True, timeout=timeout,
            env=dict(os.environ))
    except subprocess.TimeoutExpired:
        return {"ok": False, "detail": f"device query hung {timeout:.0f}s"}
    except Exception as e:  # pragma: no cover - spawn failure
        return {"ok": False, "detail": f"probe spawn failed: {e}"}
    ok = proc.returncode == 0 and "NDEV" in proc.stdout
    tail = (proc.stdout if ok else (proc.stderr or proc.stdout))[-300:]
    return {"ok": ok, "detail": tail.strip()}


def run_numerics_child(timeout: float = 420.0) -> dict:
    """On-chip Pallas kernel correctness: fwd+bwd vs XLA reference."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "axon"
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "ray_tpu.util.tpu_watch", "--numerics"],
            capture_output=True, text=True, timeout=timeout, env=env,
            cwd=_REPO)
    except subprocess.TimeoutExpired:
        return {"ok": False, "error": f"numerics child timed out {timeout:.0f}s"}
    except Exception as e:  # pragma: no cover
        return {"ok": False, "error": f"spawn failed: {e}"}
    for line in reversed(proc.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return {"ok": False,
            "error": f"rc={proc.returncode}: {(proc.stderr or '')[-800:]}"}


def run_bench_child(timeout: float = 900.0) -> dict:
    """Full bench.py on the chip; parse its single JSON line."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "axon"
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(_REPO, "bench.py")],
            capture_output=True, text=True, timeout=timeout, env=env,
            cwd=_REPO)
    except subprocess.TimeoutExpired:
        return {"ok": False, "error": f"bench child timed out {timeout:.0f}s"}
    except Exception as e:  # pragma: no cover
        return {"ok": False, "error": f"spawn failed: {e}"}
    for line in reversed(proc.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return {"ok": True, "result": json.loads(line)}
            except json.JSONDecodeError:
                continue
    return {"ok": False,
            "error": f"rc={proc.returncode}: {(proc.stderr or '')[-800:]}"}


SWEEP_OUT = os.path.join(_REPO, "experiments", "MFU_SWEEP_R5_RESULTS.jsonl")
_SWEEP_CHILD_TIMEOUT = 900.0  # matches the r4 sweep's per-config budget


def _sweep_mod():
    """The mfu_sweep module (jax-free at import time), or None. Loaded
    fresh each call so edits to the config list are picked up without a
    watcher restart."""
    try:
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "_mfu_sweep", os.path.join(_REPO, "experiments", "mfu_sweep.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod
    except Exception:
        return None


def _sweep_ok_count(path: str = SWEEP_OUT) -> int:
    mod = _sweep_mod()
    if mod is None:
        return 0
    return sum(1 for r in mod._scan_records(path) if r.get("ok"))


def _sweep_unmeasured() -> int:
    """How many sweep configs still need a chip attempt. Uses the sweep's
    own _done_names, which retires configs after repeated failures — a
    deterministic OOM must not make the watcher re-burn tunnel time every
    probe iteration."""
    mod = _sweep_mod()
    if mod is None:
        return 0  # can't tell — don't risk a sweep busy-loop
    try:
        names = {row[0] for row in mod.CONFIGS}
        return len(names - mod._done_names(SWEEP_OUT))
    except Exception:
        return 0


def run_sweep_child() -> dict:
    """Resumable MFU sweep (experiments/mfu_sweep.py) on the chip.

    Appends per-config records to SWEEP_OUT incrementally, so a window
    that closes mid-sweep still keeps every measured config; --skip-ok
    makes the next window continue where this one stopped. The sweep runs
    in its own process group and the WHOLE group is killed on timeout —
    an orphaned grandchild would keep holding the chip while the watcher
    moves on to the bench (two claimants hang the tunnel, CLAUDE.md).
    """
    import signal

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "axon"
    before = _sweep_ok_count()
    # size the budget to the work actually remaining
    timeout = 120.0 + _sweep_unmeasured() * (_SWEEP_CHILD_TIMEOUT + 40.0)
    try:
        proc = subprocess.Popen(
            [sys.executable, os.path.join(_REPO, "experiments", "mfu_sweep.py"),
             "--out", SWEEP_OUT, "--skip-ok",
             "--timeout", str(int(_SWEEP_CHILD_TIMEOUT))],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env, cwd=_REPO, start_new_session=True)
        try:
            stdout, _ = proc.communicate(timeout=timeout)
            tail = stdout.strip().splitlines()[-1] if stdout.strip() else ""
            out = {"ok": proc.returncode == 0, "tail": tail[-500:]}
        except subprocess.TimeoutExpired:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except OSError:
                pass
            proc.wait(timeout=30)
            out = {"ok": False, "error": f"sweep timed out {timeout:.0f}s "
                                         "(group killed; partial results kept)"}
    except Exception as e:  # pragma: no cover
        out = {"ok": False, "error": f"spawn failed: {e}"}
    out["new_ok_configs"] = _sweep_ok_count() - before
    return out


def _bench_is_real_tpu(result: dict) -> bool:
    detail = result.get("detail", {})
    return (result.get("metric") == "llama_train_mfu"
            and result.get("value", 0) > 0
            and "error" not in result
            and detail.get("backend") in ("axon", "tpu")
            # a result carrying tpu_cache is bench.py ECHOING this very
            # cache (its tunnel-down fallback) — re-caching it would
            # launder a stale number with a fresh timestamp
            and "tpu_cache" not in detail)


def load_cache(cache_path: str = DEFAULT_CACHE) -> dict | None:
    """Last good on-chip measurement, or None. Used by bench.py fallback."""
    try:
        with open(cache_path) as f:
            cached = json.load(f)
        if _bench_is_real_tpu(cached.get("bench", {})):
            return cached
    except Exception:
        pass
    return None


def watch(interval: float, log_path: str, cache_path: str,
          refresh_s: float, max_iterations: int | None = None) -> None:
    _append_log(log_path, {"event": "watch_start", "pid": os.getpid(),
                           "interval_s": interval})
    i = 0
    while max_iterations is None or i < max_iterations:
        i += 1
        p = probe()
        rec = {"event": "probe", "ok": p["ok"], "detail": p["detail"]}
        cached = load_cache(cache_path)
        cache_age = (time.time() - cached["ts"]) if cached else None

        def _cache_if_good(bench, numerics):
            # LATEST good measurement (not max-ever): a config change can
            # legitimately lower the number, and a stale-ts cache would
            # re-trigger benching every iteration. Historical bests live
            # in the sweep results file.
            if bench.get("ok") and _bench_is_real_tpu(bench["result"]):
                payload = {"ts": round(time.time(), 1), "iso": _now_iso(),
                           "bench": bench["result"], "numerics": numerics}
                # lift the device-plane section (compiled-program
                # registry: compile times, cost-analysis flops, HBM
                # watermarks from the real chip) to a top-level key so
                # the cached compile/cost table survives even if the
                # bench detail is ever trimmed
                dp = (bench["result"].get("detail")
                      or {}).get("device_plane")
                if dp:
                    payload["device_plane"] = dp
                tmp = cache_path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(payload, f, indent=1)
                os.replace(tmp, cache_path)
                _append_log(log_path, {"event": "bench_cached",
                                       "mfu": bench["result"].get("value")})
            else:
                _append_log(log_path, {
                    "event": "bench_failed" if not bench.get("ok")
                    else "bench_not_cached",
                    "error": bench.get("error",
                                       json.dumps(bench.get("result"))[:500])})

        if p["ok"]:
            _append_log(log_path, rec)
            # MFU sweep FIRST (CLAUDE.md: when the tunnel is up, drop
            # everything and run the experiments — the window may not
            # return). Resumable via --skip-ok; partial results are kept
            # if the window dies mid-sweep.
            new_cfgs = 0
            if _sweep_unmeasured() > 0:
                _append_log(log_path, {"event": "sweep_start"})
                sweep = run_sweep_child()
                _append_log(log_path, {"event": "sweep_done", **sweep})
                new_cfgs = sweep.get("new_ok_configs", 0)
            # Then numerics + bench (bench adopts the best sweep config);
            # skip both when the cache is fresh and the sweep added nothing.
            if cache_age is None or cache_age > refresh_s or new_cfgs > 0:
                _append_log(log_path, {"event": "bench_start"})
                numerics = run_numerics_child()
                _append_log(log_path, {"event": "numerics_done", **numerics})
                _cache_if_good(run_bench_child(), numerics)
        else:
            if cache_age is not None:
                rec["cache_age_s"] = round(cache_age)
            _append_log(log_path, rec)
        time.sleep(interval)


# ---------------------------------------------------------------------------
# --numerics child: jax lives here.
# ---------------------------------------------------------------------------

def numerics_child() -> None:
    """Pallas flash kernel vs blockwise-XLA reference, on the real chip.

    Compares forward outputs and dq/dk/dv grads (GQA shapes, causal) in
    bf16, then proves the memory-efficient custom VJP sustains a long
    sequence whose naive probability residuals would not fit HBM.
    """
    sys.path.insert(0, _REPO)
    from ray_tpu.util.tpu_info import honor_jax_platform_env

    honor_jax_platform_env()  # the axon sitecustomize ignores the env var
    import jax
    import jax.numpy as jnp

    from ray_tpu.ops.attention import flash_attention

    out: dict = {"ok": False, "backend": None}
    out["backend"] = jax.default_backend()
    out["device_kind"] = getattr(jax.devices()[0], "device_kind", "?")

    key = jax.random.PRNGKey(0)
    kq, kk, kv, kw = jax.random.split(key, 4)
    small = os.environ.get("RTPU_NUMERICS_SMALL") == "1"  # CPU smoke test
    B, S, HQ, HKV, D = (1, 256, 4, 2, 64) if small else (2, 1024, 8, 2, 128)
    q = jax.random.normal(kq, (B, S, HQ, D), jnp.bfloat16)
    k = jax.random.normal(kk, (B, S, HKV, D), jnp.bfloat16)
    v = jax.random.normal(kv, (B, S, HKV, D), jnp.bfloat16)
    w = jax.random.normal(kw, (B, S, HQ, D), jnp.bfloat16)

    def loss(q, k, v, impl):
        o = flash_attention(q, k, v, causal=True, impl=impl)
        return (o.astype(jnp.float32) * w.astype(jnp.float32)).sum()

    def eval_impl(impl):
        val, grads = jax.jit(
            jax.value_and_grad(loss, argnums=(0, 1, 2)),
            static_argnames=("impl",))(q, k, v, impl=impl)
        return jax.device_get(val), jax.device_get(grads)

    def max_err(a, b):
        import numpy as np
        a = np.asarray(a, dtype="float32")
        b = np.asarray(b, dtype="float32")
        denom = max(1.0, float(abs(b).max()))
        return round(float(abs(a - b).max()) / denom, 6)

    # independent reference: plain softmax attention (no custom VJP, no
    # tiling) — both tiled impls must agree with it. The CPU smoke run
    # skips pallas (non-interpret Mosaic needs a real TPU).
    val_n, grads_n = eval_impl("naive")
    tol = 0.03  # bf16 accumulation-order differences
    impl_ok = {}
    for impl in (("xla",) if small else ("pallas", "xla")):
        t0 = time.perf_counter()
        val_i, grads_i = eval_impl(impl)
        out[f"{impl}_compile_run_s"] = round(time.perf_counter() - t0, 1)
        errs = {
            f"{impl}_fwd_rel_err": max_err(val_i, val_n),
            f"{impl}_dq_rel_err": max_err(grads_i[0], grads_n[0]),
            f"{impl}_dk_rel_err": max_err(grads_i[1], grads_n[1]),
            f"{impl}_dv_rel_err": max_err(grads_i[2], grads_n[2]),
        }
        out.update(errs)
        impl_ok[impl] = all(e < tol for e in errs.values())

    # Kernel-feature checks vs the naive reference, pallas fwd+bwd on real
    # Mosaic (interpret mode can pass where silicon fails). One comparator
    # so tolerance/timing fixes apply to every feature at once.
    def compare_pallas_vs_naive(prefix: str, loss_of_impl) -> None:
        try:
            errs = {}
            ref = None
            for impl in ("naive", "pallas"):
                val, grads = jax.jit(
                    jax.value_and_grad(loss_of_impl, argnums=(0, 1, 2)),
                    static_argnames=("impl",))(q, k, v, impl=impl)
                jax.device_get(val)
                if ref is None:
                    ref = (val, grads)
                else:
                    errs[f"{prefix}_fwd_rel_err"] = max_err(val, ref[0])
                    for name, a, b in zip(("dq", "dk", "dv"), grads,
                                          ref[1]):
                        errs[f"{prefix}_{name}_rel_err"] = max_err(a, b)
            out.update(errs)
            out[f"{prefix}_ok"] = all(e < tol for e in errs.values())
        except Exception as e:
            out[f"{prefix}_ok"] = False
            out[f"{prefix}_error"] = str(e)[-300:]

    if not small and impl_ok.get("pallas"):
        # sliding-window banded-liveness predicates (round-4 addition)
        def wloss(q, k, v, impl):
            o = flash_attention(q, k, v, causal=True, impl=impl,
                                window=S // 4)
            return (o.astype(jnp.float32) * w.astype(jnp.float32)).sum()

        compare_pallas_vs_naive("window", wloss)

        # attention-logit softcap: tanh in the kernel fwd + the
        # 1-(s/cap)^2 chain factor in both bwd kernels (round-5 addition)
        def closs(q, k, v, impl):
            o = flash_attention(q, k, v, causal=True, impl=impl,
                                window=S // 4, softcap=20.0)
            return (o.astype(jnp.float32) * w.astype(jnp.float32)).sum()

        compare_pallas_vs_naive("softcap", closs)

    # Long-seq bwd: at S=16384, B=4, H=8 the naive per-layer probability
    # residual alone is B*H*S^2*4B = 32 GiB — over the 16 GiB HBM. The
    # memory-efficient VJP must sustain it.
    S2 = 512 if small else 16384
    ql = jax.random.normal(kq, (1 if small else 4, S2, 8, D), jnp.bfloat16)
    kl = jax.random.normal(kk, (1 if small else 4, S2, 2, D), jnp.bfloat16)
    try:
        t0 = time.perf_counter()
        g = jax.jit(jax.grad(
            lambda q, k, v: flash_attention(
                q, k, v, causal=True).astype(jnp.float32).sum()))(ql, kl, kl)
        float(jax.device_get(g.astype(jnp.float32).sum()))
        out["longseq_16k_bwd_s"] = round(time.perf_counter() - t0, 1)
        out["longseq_16k_bwd_ok"] = True
    except Exception as e:
        out["longseq_16k_bwd_ok"] = False
        out["longseq_16k_bwd_error"] = str(e)[-400:]

    out["ok"] = (all(impl_ok.values())
                 and out.get("longseq_16k_bwd_ok", False))
    print(json.dumps(out))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    from ray_tpu import config

    ap.add_argument("--interval", type=float,
                    default=float(config.get("watch_interval")))
    ap.add_argument("--log", default=os.environ.get("RTPU_WATCH_LOG", DEFAULT_LOG))
    ap.add_argument("--cache", default=DEFAULT_CACHE)
    ap.add_argument("--refresh", type=float,
                    default=float(config.get("watch_refresh")),
                    help="re-run the on-chip bench if the cache is older than this")
    ap.add_argument("--iterations", type=int, default=None)
    ap.add_argument("--numerics", action="store_true",
                    help="(child mode) run the on-chip numerics check")
    ap.add_argument("--pidfile", default=DEFAULT_PIDFILE)
    ap.add_argument("--status", action="store_true",
                    help="report whether a watcher is running (exit 0) "
                         "or not (exit 1), plus last probe + cache age")
    ap.add_argument("--force", action="store_true",
                    help="kill any running watcher(s) and start fresh")
    args = ap.parse_args(argv)
    if args.numerics:
        numerics_child()
        return 0
    if args.status:
        st = watcher_status(args.pidfile, args.log, args.cache)
        print(json.dumps(st, indent=1))
        return 0 if st["running"] else 1
    if args.iterations is not None:
        # bounded one-shot (e.g. the CLAUDE.md cache refresh:
        # --iterations 1 --refresh 0): runs regardless of a background
        # watcher — the gate must never silently no-op an explicit
        # refresh (and never kill it as a "duplicate": _is_watcher
        # excludes --iterations cmdlines)
        watch(args.interval, args.log, args.cache, args.refresh,
              args.iterations)
        return 0
    if not ensure_single_instance(args.pidfile, args.force):
        return 0
    try:
        watch(args.interval, args.log, args.cache, args.refresh,
              args.iterations)
    finally:
        # only remove OUR pidfile (an adopter may have rewritten it)
        if read_pidfile(args.pidfile) == os.getpid():
            try:
                os.unlink(args.pidfile)
            except OSError:
                pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
