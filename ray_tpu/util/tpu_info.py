"""TPU backend detection + per-chip peak-FLOPs table.

The TPU may be attached under platform name "tpu" (direct PJRT) or "axon"
(tunneled PJRT plugin) — anything that dispatches to Pallas kernels or
computes MFU must use these helpers instead of comparing
``jax.default_backend()`` to the literal "tpu".
"""

from __future__ import annotations

TPU_PLATFORMS = ("tpu", "axon")

# Public spec-sheet peak bf16 matmul FLOP/s per chip.
PEAK_FLOPS_BY_KIND = {
    "v2": 45e12,
    "v3": 123e12,
    "v4": 275e12,
    "v5e": 197e12,
    "v5litepod": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
    "trillium": 918e12,
}


def is_tpu_backend() -> bool:
    import jax

    return jax.default_backend() in TPU_PLATFORMS


def device_kind() -> str:
    import jax

    return getattr(jax.devices()[0], "device_kind", "unknown")


def peak_flops_per_chip(default: float = 197e12) -> float:
    """Best-effort peak bf16 FLOP/s for the attached chip."""
    kind = device_kind().lower().replace(" ", "").replace("-", "")
    for key, val in sorted(PEAK_FLOPS_BY_KIND.items(),
                           key=lambda kv: -len(kv[0])):
        if key in kind:
            return val
    return default


def hbm_usage():
    """HBM usage summed over local devices: ``{"bytes_in_use",
    "bytes_limit"}``, or None off-TPU / when the backend exposes no
    ``memory_stats`` (the tunneled axon plugin sometimes doesn't)."""
    try:
        import jax

        if jax.default_backend() not in TPU_PLATFORMS:
            return None
        used = limit = 0
        for d in jax.local_devices():
            ms = getattr(d, "memory_stats", None)
            ms = ms() if callable(ms) else None
            if not ms:
                return None
            used += int(ms.get("bytes_in_use", 0))
            limit += int(ms.get("bytes_limit", 0)
                         or ms.get("bytes_reservable_limit", 0))
        return {"bytes_in_use": used, "bytes_limit": limit}
    except Exception:
        return None


def honor_jax_platform_env(*, only_if_imported: bool = False) -> None:
    """Make jax respect the JAX_PLATFORMS env var in this process.

    A site-installed TPU plugin (axon sitecustomize) may pin
    ``jax_platforms`` by config at interpreter start, silently overriding
    the env var — a CPU-pinned process would then hang trying to claim the
    TPU tunnel on its first device query. Call this before any device query
    whenever the env var is authoritative (workers, driver entry points,
    bench). With ``only_if_imported`` the no-op case skips the jax import
    (worker fast path: if sitecustomize didn't import jax, nothing pinned
    the config either).
    """
    import os
    import sys

    platforms = os.environ.get("JAX_PLATFORMS", "")
    if not platforms:
        return
    if only_if_imported and "jax" not in sys.modules:
        return
    try:
        import jax

        jax.config.update("jax_platforms", platforms)
    except Exception:
        pass


def force_cpu() -> None:
    """Pin jax to CPU before any device query (tests/dev boxes where the
    TPU tunnel may be registered but unavailable)."""
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    honor_jax_platform_env()
