"""Autoscaler: demand-driven TPU node/slice provisioning.

Role analog: ``python/ray/autoscaler/_private/autoscaler.py:172``
(StandardAutoscaler) + the cloud NodeProvider plugin interface
(``node_provider.py``) + the fake in-memory provider the reference tests
with (``autoscaler/_private/fake_multi_node/``). TPU specifics follow the
reference's GCP provider (``gcp/node_provider.py:75-94``): a TPU *slice* is
the provisioning unit — one create call yields every host in the slice,
each carrying the slice-name resource and worker 0 the ``-head`` marker
(the scheduling pattern from ``_private/accelerators/tpu.py:335-398``).
"""

from ray_tpu.autoscaler.autoscaler import (
    AutoscalerConfig,
    NodeTypeConfig,
    StandardAutoscaler,
    request_resources,
)
from ray_tpu.autoscaler.node_provider import NodeProvider
from ray_tpu.autoscaler.fake_provider import FakeTpuNodeProvider

__all__ = [
    "AutoscalerConfig",
    "NodeTypeConfig",
    "StandardAutoscaler",
    "NodeProvider",
    "FakeTpuNodeProvider",
    "request_resources",
]
