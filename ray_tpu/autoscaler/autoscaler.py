"""StandardAutoscaler: bin-pack demand onto node types, launch/terminate.

Role analog: ``python/ray/autoscaler/_private/autoscaler.py:172`` driven by
``resource_demand_scheduler.py`` bin-packing, with the TPU twist that a
demand bundle naming a slice-shaped resource (``TPU-v5e-16-head`` or an
aggregate chip count beyond one host) provisions a whole SLICE. Demand
comes from ``request_resources`` (the reference SDK call) and/or a pluggable
``load_source`` callable returning pending bundles (wired to the GCS's
queued-task view in cluster mode).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ray_tpu.autoscaler.fake_provider import SLICE_SHAPES
from ray_tpu.autoscaler.node_provider import NodeInfo, NodeProvider

Bundle = Dict[str, float]


@dataclass
class NodeTypeConfig:
    """One entry of ``available_node_types`` (reference YAML schema)."""

    name: str
    min_workers: int = 0
    max_workers: int = 10
    is_slice: bool = False  # True -> provisioned via create_slice


@dataclass
class AutoscalerConfig:
    node_types: List[NodeTypeConfig] = field(default_factory=list)
    idle_timeout_s: float = 60.0
    max_launch_per_step: int = 8


_requested: List[Bundle] = []
_requested_lock = threading.Lock()


def request_resources(bundles: List[Bundle]) -> None:
    """Declare a standing resource demand (reference
    ``ray.autoscaler.sdk.request_resources``): the autoscaler keeps the
    cluster able to satisfy these bundles. Pass ``[]`` to clear."""
    with _requested_lock:
        _requested[:] = [dict(b) for b in bundles]


def _get_requested() -> List[Bundle]:
    with _requested_lock:
        return [dict(b) for b in _requested]


class StandardAutoscaler:
    def __init__(self, provider: NodeProvider, config: AutoscalerConfig,
                 load_source: Optional[Callable[[], List[Bundle]]] = None):
        self.provider = provider
        self.config = config
        self.load_source = load_source
        # node_id -> monotonic ts when it was last seen busy
        self._last_busy: Dict[str, float] = {}
        self._by_name = {t.name: t for t in config.node_types}

    # -- one scaling step (reference StandardAutoscaler.update) ----------

    def update(self,
               used_resources: Optional[Dict[str, Dict[str, float]]] = None
               ) -> None:
        """One reconcile step: satisfy min_workers, bin-pack unmet demand,
        launch, then scale idle nodes down. ``used_resources``:
        node_id -> resources currently in use on it (for idle detection)."""
        nodes = self.provider.non_terminated_nodes()
        demand = _get_requested()
        if self.load_source is not None:
            demand = demand + list(self.load_source() or [])
        self._scale_up(nodes, demand)
        self._scale_down(self.provider.non_terminated_nodes(),
                         used_resources or {}, demand)

    # -- scale up --------------------------------------------------------

    def _scale_up(self, nodes: List[NodeInfo], demand: List[Bundle]) -> None:
        counts: Dict[str, int] = {}
        for n in nodes:
            if n.slice_id is not None:
                counts[n.node_type] = counts.get(n.node_type, 0)
            else:
                counts[n.node_type] = counts.get(n.node_type, 0) + 1
        # slices count once per slice, not per host
        slice_ids = {}
        for n in nodes:
            if n.slice_id is not None:
                slice_ids.setdefault(n.node_type, set()).add(n.slice_id)
        for t, ids in slice_ids.items():
            counts[t] = len(ids)

        launches: Dict[str, int] = {}
        # 1. min_workers floors
        for t in self.config.node_types:
            have = counts.get(t.name, 0)
            if have < t.min_workers:
                launches[t.name] = t.min_workers - have

        # 2. bin-pack unmet demand onto virtual capacity
        free: List[Dict[str, float]] = [dict(n.resources) for n in nodes]
        for t, k in launches.items():
            free.extend(self._virtual_nodes(t, k))
        for bundle in demand:
            if self._fit(bundle, free):
                continue
            t = self._pick_type(bundle, counts, launches)
            if t is None:
                continue
            launches[t.name] = launches.get(t.name, 0) + 1
            free.extend(self._virtual_nodes(t.name, 1))
            # re-fit this bundle against the new capacity
            self._fit(bundle, free)

        # 3. launch
        for name, k in launches.items():
            t = self._by_name[name]
            have = counts.get(name, 0)
            k = min(k, t.max_workers - have, self.config.max_launch_per_step)
            for _ in range(max(0, k)):
                if t.is_slice:
                    created = self.provider.create_slice(name)
                else:
                    created = self.provider.create_nodes(name, 1)
                now = time.monotonic()
                for n in created:
                    self._last_busy[n.node_id] = now

    def _virtual_nodes(self, type_name: str, k: int) -> List[Dict[str, float]]:
        t = self._by_name[type_name]
        out = []
        for _ in range(k):
            if t.is_slice:
                hosts, chips = SLICE_SHAPES[type_name]
                head = {"CPU": 8.0, "TPU": float(chips),
                        f"TPU-{type_name}-head": 1.0,
                        f"tpu-{type_name}-pending": float(hosts)}
                out.append(head)
                out.extend({"CPU": 8.0, "TPU": float(chips)}
                           for _ in range(hosts - 1))
            else:
                out.append(dict(self._fake_type_resources(type_name)))
        return out

    def _fake_type_resources(self, type_name: str) -> Dict[str, float]:
        getter = getattr(self.provider, "_node_types", {})
        return getter.get(type_name, {"CPU": 1.0})

    @staticmethod
    def _fit(bundle: Bundle, free: List[Dict[str, float]]) -> bool:
        """First-fit-decreasing single-node placement; mutates ``free``."""
        for node in free:
            if all(node.get(k, 0.0) >= v for k, v in bundle.items()):
                for k, v in bundle.items():
                    node[k] = node.get(k, 0.0) - v
                return True
        return False

    def _pick_type(self, bundle: Bundle, counts: Dict[str, int],
                   launches: Dict[str, int]) -> Optional[NodeTypeConfig]:
        for t in self.config.node_types:
            planned = counts.get(t.name, 0) + launches.get(t.name, 0)
            if planned >= t.max_workers:
                continue
            if t.is_slice:
                hosts, chips = SLICE_SHAPES[t.name]
                cap = {"CPU": 8.0, "TPU": float(chips),
                       f"TPU-{t.name}-head": 1.0}
                # aggregate chip demand can ride a whole slice
                cap_total = {"CPU": 8.0 * hosts, "TPU": float(chips * hosts),
                             f"TPU-{t.name}-head": 1.0}
                if all(cap.get(k, 0.0) >= v for k, v in bundle.items()) or \
                        all(cap_total.get(k, 0.0) >= v
                            for k, v in bundle.items()):
                    return t
            else:
                cap = self._fake_type_resources(t.name)
                if all(cap.get(k, 0.0) >= v for k, v in bundle.items()):
                    return t
        return None

    # -- scale down ------------------------------------------------------

    def _scale_down(self, nodes: List[NodeInfo],
                    used: Dict[str, Dict[str, float]],
                    demand: List[Bundle]) -> None:
        now = time.monotonic()
        by_slice: Dict[str, List[NodeInfo]] = {}
        singles: List[NodeInfo] = []
        for n in nodes:
            if n.slice_id is not None:
                by_slice.setdefault(n.slice_id, []).append(n)
            else:
                singles.append(n)
            if used.get(n.node_id):
                self._last_busy[n.node_id] = now
            self._last_busy.setdefault(n.node_id, now)

        # nodes still needed by standing demand are not idle-terminated
        keep: set = set()
        free = [(n.node_id, dict(n.resources)) for n in nodes]
        # slice aggregates for bundles no single host satisfies (e.g.
        # {"TPU": 16} riding a 4-host slice)
        slice_free: Dict[str, Dict[str, float]] = {}
        for sid, members in by_slice.items():
            agg: Dict[str, float] = {}
            for n in members:
                for k, v in n.resources.items():
                    agg[k] = agg.get(k, 0.0) + v
            slice_free[sid] = agg
        for bundle in demand:
            placed = False
            for nid, res in free:
                if all(res.get(k, 0.0) >= v for k, v in bundle.items()):
                    for k, v in bundle.items():
                        res[k] = res.get(k, 0.0) - v
                    keep.add(nid)
                    placed = True
                    break
            if placed:
                continue
            for sid, agg in slice_free.items():
                if all(agg.get(k, 0.0) >= v for k, v in bundle.items()):
                    for k, v in bundle.items():
                        agg[k] = agg.get(k, 0.0) - v
                    keep.update(n.node_id for n in by_slice[sid])
                    break

        counts: Dict[str, int] = {}
        for n in singles:
            counts[n.node_type] = counts.get(n.node_type, 0) + 1
        for sid, members in by_slice.items():
            counts[members[0].node_type] = counts.get(
                members[0].node_type, 0) + 1

        def idle(n: NodeInfo) -> bool:
            return (n.node_id not in keep
                    and now - self._last_busy.get(n.node_id, now)
                    > self.config.idle_timeout_s)

        for n in singles:
            t = self._by_name.get(n.node_type)
            floor = t.min_workers if t else 0
            if idle(n) and counts.get(n.node_type, 0) > floor:
                self.provider.terminate_node(n.node_id)
                counts[n.node_type] -= 1
                self._last_busy.pop(n.node_id, None)

        for sid, members in by_slice.items():
            t = self._by_name.get(members[0].node_type)
            floor = t.min_workers if t else 0
            if all(idle(n) for n in members) and \
                    counts.get(members[0].node_type, 0) > floor:
                self.provider.terminate_slice(sid)
                counts[members[0].node_type] -= 1
                for n in members:
                    self._last_busy.pop(n.node_id, None)
