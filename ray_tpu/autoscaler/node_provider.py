"""NodeProvider interface: the cloud seam the autoscaler drives.

Role analog: ``python/ray/autoscaler/node_provider.py`` — reduced to the
calls the scaling loop needs. A provider manages NODES (hosts); TPU slices
are multi-host: ``create_slice`` provisions every host of a slice in one
call (the reference's GCP TPU path fills pod resources per host,
``gcp/node_provider.py:283-292``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class NodeInfo:
    node_id: str
    node_type: str          # e.g. "cpu-worker" | "v5e-16"
    slice_id: Optional[str]  # shared by every host of one slice
    resources: Dict[str, float]
    is_slice_head: bool = False
    tags: Dict[str, str] = field(default_factory=dict)


class NodeProvider:
    """Subclass per cloud; all methods are called from the scaling loop."""

    def create_nodes(self, node_type: str, count: int) -> List[NodeInfo]:
        """Provision ``count`` single-host nodes of ``node_type``."""
        raise NotImplementedError

    def create_slice(self, slice_type: str) -> List[NodeInfo]:
        """Provision one TPU slice; returns every host in it."""
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def terminate_slice(self, slice_id: str) -> None:
        """A slice lives and dies as a unit (ICI has no partial membership)."""
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[NodeInfo]:
        raise NotImplementedError
