"""Fake in-memory TPU node provider for tests and local dev.

Role analog: ``python/ray/autoscaler/_private/fake_multi_node/node_provider.py``
with the GCP TPU slice behavior layered in: ``create_slice("v5e-16")``
yields hosts-per-slice nodes, each advertising the per-slice name resource
and the head host the ``TPU-<type>-head`` marker — exactly the resource
shapes ``ray_tpu.accelerators.tpu`` derives on real metal, so slice-aware
scheduling logic is testable with zero hardware.
"""

from __future__ import annotations

import itertools
import threading
from typing import Dict, List

from ray_tpu.autoscaler.node_provider import NodeInfo, NodeProvider

# slice type -> (num_hosts, chips_per_host)  (v2-v4: 8-core hosts; v5+: 4)
SLICE_SHAPES = {
    "v4-8": (1, 4),
    "v4-16": (2, 4),
    "v5e-4": (1, 4),
    "v5e-8": (2, 4),
    "v5e-16": (4, 4),
    "v5e-64": (16, 4),
    "v5e-256": (64, 4),
    "v5p-8": (1, 4),
    "v6e-16": (4, 4),
}


class FakeTpuNodeProvider(NodeProvider):
    def __init__(self, node_types: Dict[str, Dict[str, float]] = None):
        self._node_types = dict(node_types or {})
        self._nodes: Dict[str, NodeInfo] = {}
        self._lock = threading.Lock()
        self._ids = itertools.count()
        self.create_calls: List[str] = []
        self.terminate_calls: List[str] = []

    def _next_id(self, prefix: str) -> str:
        return f"{prefix}-{next(self._ids)}"

    def create_nodes(self, node_type: str, count: int) -> List[NodeInfo]:
        res = self._node_types.get(node_type)
        if res is None:
            raise ValueError(f"unknown node type {node_type!r}")
        out = []
        with self._lock:
            for _ in range(count):
                nid = self._next_id(node_type)
                info = NodeInfo(nid, node_type, None, dict(res))
                self._nodes[nid] = info
                out.append(info)
            self.create_calls.append(node_type)
        return out

    def create_slice(self, slice_type: str) -> List[NodeInfo]:
        if slice_type not in SLICE_SHAPES:
            raise ValueError(f"unknown slice type {slice_type!r}")
        hosts, chips = SLICE_SHAPES[slice_type]
        out = []
        with self._lock:
            slice_id = self._next_id(f"slice-{slice_type}")
            pod_name = f"tpu-{slice_id}"
            for h in range(hosts):
                nid = self._next_id(slice_type)
                resources = {
                    "CPU": 8.0,
                    "TPU": float(chips),
                    pod_name: 1.0,  # per-slice name resource on every host
                }
                head = h == 0
                if head:
                    # fan-out anchor (reference tpu.py:335-398)
                    resources[f"TPU-{slice_type}-head"] = 1.0
                out.append(NodeInfo(nid, slice_type, slice_id, resources,
                                    is_slice_head=head,
                                    tags={"pod_name": pod_name}))
                self._nodes[nid] = out[-1]
            self.create_calls.append(slice_type)
        return out

    def terminate_node(self, node_id: str) -> None:
        with self._lock:
            self._nodes.pop(node_id, None)
            self.terminate_calls.append(node_id)

    def terminate_slice(self, slice_id: str) -> None:
        with self._lock:
            doomed = [n for n in self._nodes.values()
                      if n.slice_id == slice_id]
            for n in doomed:
                del self._nodes[n.node_id]
            self.terminate_calls.append(slice_id)

    def non_terminated_nodes(self) -> List[NodeInfo]:
        with self._lock:
            return list(self._nodes.values())
