"""KubeRay-style integration: scale by patching a RayCluster custom resource.

Role analog: ``python/ray/autoscaler/_private/kuberay/node_provider.py`` —
on Kubernetes the autoscaler does NOT create VMs; it patches the
``workerGroupSpecs[*].replicas`` field of the RayCluster CR and lets the
operator reconcile pods. This provider speaks that protocol against a
pluggable API client (anything with ``get(path)`` / ``patch(path, body)``
— the real cluster uses the kubelet service-account HTTP client; tests
use a fake), so the scaling logic is unit-testable without a cluster.

TPU notes: worker groups map 1:1 to TPU slice topologies (a
``numOfHosts > 1`` group is one multi-host slice, the KubeRay TPU
pattern), so ``create_nodes(group, k)`` bumps replicas and the operator
brings up whole slices atomically.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeInfo, NodeProvider


class KubeRayNodeProvider(NodeProvider):
    """Scales workerGroup replicas on a RayCluster CR."""

    def __init__(self, api_client, namespace: str, cluster_name: str):
        self.api = api_client
        self.ns = namespace
        self.name = cluster_name

    @property
    def _path(self) -> str:
        return (f"/apis/ray.io/v1/namespaces/{self.ns}"
                f"/rayclusters/{self.name}")

    def _cr(self) -> Dict[str, Any]:
        return self.api.get(self._path)

    def _groups(self, cr) -> List[Dict[str, Any]]:
        return cr["spec"].get("workerGroupSpecs", [])

    # -- NodeProvider surface ------------------------------------------

    def create_nodes(self, node_type: str, count: int) -> List[NodeInfo]:
        """node_type == workerGroup name; bumps replicas by ``count``.
        ONE read feeds the patch (no second GET whose staleness could
        stomp a concurrent scale-up)."""
        cr = self._cr()
        for i, g in enumerate(self._groups(cr)):
            if g["groupName"] == node_type:
                new = int(g.get("replicas", 0)) + count
                self.api.patch(self._path, [
                    {"op": "replace",
                     "path": f"/spec/workerGroupSpecs/{i}/replicas",
                     "value": new}])
                # pods materialize asynchronously via the operator; report
                # the REQUESTED identities (group/index) — they become
                # live in non_terminated_nodes once the operator acts
                res = self._group_resources(g)
                return [NodeInfo(f"{node_type}-{new - count + j}",
                                 node_type, None, dict(res))
                        for j in range(count)]
        raise ValueError(f"unknown worker group {node_type!r}")

    def terminate_node(self, node_id: str) -> None:
        """Scale the node's group down by one and mark the pod for
        deletion via the KubeRay ``workersToDelete`` protocol (the
        operator removes exactly that pod, not an arbitrary one).
        Appends to any pending workersToDelete so back-to-back
        terminations within one reconcile window all survive."""
        group = node_id.rsplit("-", 1)[0]
        cr = self._cr()
        for i, g in enumerate(self._groups(cr)):
            if g["groupName"] == group:
                replicas = max(0, int(g.get("replicas", 0)) - 1)
                pending = list((g.get("scaleStrategy") or {})
                               .get("workersToDelete") or [])
                if node_id not in pending:
                    pending.append(node_id)
                self.api.patch(self._path, [
                    {"op": "replace",
                     "path": f"/spec/workerGroupSpecs/{i}/replicas",
                     "value": replicas},
                    {"op": "add",
                     "path": (f"/spec/workerGroupSpecs/{i}/scaleStrategy"),
                     "value": {"workersToDelete": pending}},
                ])
                return
        raise ValueError(f"unknown group for node {node_id!r}")

    def non_terminated_nodes(self) -> List[NodeInfo]:
        cr = self._cr()
        out = []
        for g in self._groups(cr):
            res = self._group_resources(g)
            for i in range(int(g.get("replicas", 0))):
                out.append(NodeInfo(f"{g['groupName']}-{i}",
                                    g["groupName"], None, dict(res)))
        return out

    @staticmethod
    def _group_resources(g: Dict[str, Any]) -> Dict[str, float]:
        """Resources from rayStartParams (the KubeRay convention)."""
        params = g.get("rayStartParams", {})
        out: Dict[str, float] = {}
        if "num-cpus" in params:
            out["CPU"] = float(params["num-cpus"])
        if "num-tpus" in params:
            out["TPU"] = float(params["num-tpus"])
        extra = params.get("resources")
        if extra:
            out.update(json.loads(extra) if isinstance(extra, str)
                       else extra)
        return out


class FakeKubeApi:
    """In-memory stand-in for the k8s API server (tests/docs): stores one
    RayCluster CR and applies JSON-patch replace/add ops."""

    def __init__(self, cr: Dict[str, Any]):
        self.cr = cr
        self.patches: List[Any] = []

    def get(self, path: str) -> Dict[str, Any]:
        return json.loads(json.dumps(self.cr))  # deep copy

    def patch(self, path: str, ops: List[Dict[str, Any]]) -> None:
        self.patches.append(ops)
        for op in ops:
            parts = [p for p in op["path"].split("/") if p]
            tgt: Any = self.cr
            for p in parts[:-1]:
                tgt = tgt[int(p)] if isinstance(tgt, list) else tgt[p]
            key: Any = parts[-1]
            if isinstance(tgt, list):
                key = int(key)
            tgt[key] = op["value"]
