"""Cluster launcher: ``ray_tpu up/down <cluster.yaml>``.

Role analog: the reference launcher CLI (``python/ray/scripts/scripts.py``
``ray up`` at ``:1279``, YAML schema ``autoscaler/ray-schema.json``,
``TPUCommandRunner`` running setup on every pod host,
``gcp/tpu_command_runner.py``) — reduced to the path a TPU cluster needs:
ensure the head exists, run setup + start commands over SSH on every
host (all hosts of a TPU slice, like the reference's TPU runner), report
the address. Provider and command runner are injectable so the flow is
testable without a cloud.

YAML shape::

    cluster_name: demo
    provider: {type: gcp, project_id: p, availability_zone: us-central2-b}
    auth: {ssh_user: ubuntu}
    head_node_type: head
    available_node_types:
      head:
        kind: compute
        machine_type: n2-standard-8
        resources: {CPU: 8}
      v5e-16:
        kind: tpu
        accelerator_type: v5litepod-16
        min_workers: 0
        max_workers: 2
    setup_commands: [...]
    head_start_commands: [...]
    worker_start_commands: [...]
"""

from __future__ import annotations

import subprocess
from typing import Any, Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeInfo, NodeProvider


def load_config(path: str) -> Dict[str, Any]:
    import yaml

    with open(path) as f:
        cfg = yaml.safe_load(f)
    for key in ("cluster_name", "provider", "head_node_type",
                "available_node_types"):
        if key not in cfg:
            raise ValueError(f"cluster yaml missing required key {key!r}")
    if cfg["head_node_type"] not in cfg["available_node_types"]:
        raise ValueError("head_node_type not in available_node_types")
    return cfg


def make_provider(cfg: Dict[str, Any]) -> NodeProvider:
    p = cfg["provider"]
    kind = p.get("type", "fake")
    if kind == "gcp":
        from ray_tpu.autoscaler.gcp import GcpTpuNodeProvider

        return GcpTpuNodeProvider(
            project=p["project_id"], zone=p["availability_zone"],
            cluster_name=cfg["cluster_name"],
            node_types=cfg["available_node_types"])
    if kind == "fake":
        from ray_tpu.autoscaler.fake_provider import FakeTpuNodeProvider

        types = {name: spec.get("resources", {"CPU": 1})
                 for name, spec in cfg["available_node_types"].items()}
        return FakeTpuNodeProvider(types)
    raise ValueError(f"unknown provider type {kind!r}")


class SshRunner:
    """Runs shell commands on a node over ssh (role analog
    ``command_runner.py``/``tpu_command_runner.py``)."""

    def __init__(self, user: str, opts: Optional[List[str]] = None):
        self.user = user
        self.opts = opts or ["-o", "StrictHostKeyChecking=no",
                             "-o", "ConnectTimeout=15"]

    def run(self, node: NodeInfo, cmd: str) -> None:
        ip = node.tags.get("ip") or node.node_id
        subprocess.run(["ssh", *self.opts, f"{self.user}@{ip}", cmd],
                       check=True)


def up(cfg: Dict[str, Any], provider: Optional[NodeProvider] = None,
       runner=None, yes: bool = True) -> Dict[str, Any]:
    """Idempotently bring the head up; returns a summary dict."""
    provider = provider or make_provider(cfg)
    runner = runner or SshRunner(cfg.get("auth", {}).get("ssh_user", "rtpu"))
    head_type = cfg["head_node_type"]
    live = provider.non_terminated_nodes()
    head = next((n for n in live if n.node_type == head_type), None)
    created = False
    if head is None:
        spec = cfg["available_node_types"][head_type]
        if spec.get("kind") == "tpu":
            head = provider.create_slice(head_type)[0]
        else:
            head = provider.create_nodes(head_type, 1)[0]
        created = True
    for cmd in cfg.get("setup_commands", []):
        runner.run(head, cmd)
    for cmd in cfg.get("head_start_commands", []):
        runner.run(head, cmd)
    # min_workers of each worker type (the autoscaler grows past this)
    workers: List[NodeInfo] = []
    for name, spec in cfg["available_node_types"].items():
        if name == head_type:
            continue
        want = int(spec.get("min_workers", 0))
        have = len({(n.slice_id or n.node_id) for n in live
                    if n.node_type == name})
        for _ in range(max(0, want - have)):
            if spec.get("kind") == "tpu":
                hosts = provider.create_slice(name)
            else:
                hosts = provider.create_nodes(name, 1)
            workers.extend(hosts)
            for h in hosts:  # TPU: setup runs on EVERY pod host
                for cmd in cfg.get("setup_commands", []):
                    runner.run(h, cmd)
                for cmd in cfg.get("worker_start_commands", []):
                    runner.run(h, cmd)
    return {"head": head, "head_created": created,
            "workers_started": workers,
            "address": head.tags.get("ip") or head.node_id}


def down(cfg: Dict[str, Any],
         provider: Optional[NodeProvider] = None) -> int:
    """Terminate every node of the cluster; returns count torn down."""
    provider = provider or make_provider(cfg)
    live = provider.non_terminated_nodes()
    seen_slices = set()
    n = 0
    for node in live:
        if node.slice_id is not None:
            if node.slice_id in seen_slices:
                continue
            seen_slices.add(node.slice_id)
            provider.terminate_slice(node.slice_id)
        else:
            provider.terminate_node(node.node_id)
        n += 1
    return n
